"""Debezium CDC connector (reference ``python/pathway/io/debezium`` +
``DebeziumMessageParser``, src/connectors/data_format.rs:1053).

Consumes Debezium change envelopes (``payload.op``: c/r = insert, u = update
as delete+insert of the keyed row, d = delete) from a Kafka topic — the
framework's in-memory broker for tests/benchmarks, or a REAL cluster
through the gated ``confluent_kafka`` consumer (same transport as
``pw.io.kafka``, with per-partition offsets as the persistence position).
"""

from __future__ import annotations

import json
from typing import Any

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector
from pathway_tpu.io._utils import parse_record_fields
from pathway_tpu.io.kafka import (
    InMemoryKafkaBroker,
    _confluent,
    make_kafka_consumer,
)


class _CdcApplier:
    """Shared CDC envelope → delta translation with the keyed live map
    (the upsert session both transports need)."""

    def __init__(self, node, schema):
        self.schema = schema
        self.cols = list(node.column_names)
        self.dtypes = {n: c.dtype for n, c in schema.__columns__.items()}
        self.pk = schema.primary_key_columns() or ()
        self.live: dict[int, tuple] = {}

    def row_of(self, record: dict):
        from pathway_tpu.engine.value import hash_values

        values = parse_record_fields(record, self.cols, self.dtypes, self.schema)
        src = self.pk or self.cols
        key = hash_values(*[values[c] for c in src])
        return key, tuple(values[c] for c in self.cols)

    def apply(self, value: bytes) -> list[tuple[int, tuple, int]]:
        """Deltas for one envelope (empty for malformed/irrelevant —
        logged, so a misconfigured CDC pipeline is diagnosable, not
        silent data loss)."""
        try:
            env = json.loads(value)
        except (json.JSONDecodeError, TypeError):
            env = None
        payload = env.get("payload", env) if isinstance(env, dict) else None
        if not isinstance(payload, dict):
            from pathway_tpu.internals.errors import get_global_error_log

            get_global_error_log().log(
                "debezium: skipping malformed CDC envelope"
            )
            return []
        op = payload.get("op", "c")
        before, after = payload.get("before"), payload.get("after")
        rows: list[tuple[int, tuple, int]] = []
        if op in ("c", "r") and after:
            key, row = self.row_of(after)
            rows.append((key, row, 1))
            self.live[key] = row
        elif op == "u" and after:
            key, row = self.row_of(after)
            old = self.live.get(key)
            if old is not None:
                rows.append((key, old, -1))
            rows.append((key, row, 1))
            self.live[key] = row
        elif op == "d" and before:
            key, _row = self.row_of(before)
            old = self.live.pop(key, None)
            if old is not None:
                rows.append((key, old, -1))
        return rows

    def replay(self, rows) -> None:
        for key, row, diff in rows:
            if diff > 0:
                self.live[key] = row
            else:
                self.live.pop(key, None)


class _DebeziumConnector(BaseConnector):
    """In-memory broker transport."""

    heartbeat_ms = 500

    def __init__(self, node, broker, topic, schema):
        super().__init__(node)
        self.broker = broker
        self.topic = topic
        self._cdc = _CdcApplier(node, schema)
        self._offset = 0

    # persistence: broker log position + live map rebuilt from replay
    def current_offset(self):
        return self._offset

    def seek_offset(self, offset) -> None:
        if isinstance(offset, int):
            self._offset = offset

    def on_replay(self, rows) -> None:
        self._cdc.replay(rows)

    def run(self):
        import time as time_mod

        while not self.should_stop():
            msgs = self.broker.poll(self.topic, self._offset)
            self._offset += len(msgs)
            rows = []
            for _mkey, value in msgs:
                rows.extend(self._cdc.apply(value))
            if rows:
                self.commit_rows(rows)
            elif self.broker.closed:
                return
            else:
                time_mod.sleep(0.01)


class _DebeziumKafkaConnector(BaseConnector):
    """Real-cluster transport: the gated confluent_kafka consumer loop of
    ``pw.io.kafka`` feeding the shared CDC applier; per-partition offsets
    are the persistence position."""

    heartbeat_ms = 500
    MAX_DRAIN = 1024

    def __init__(self, node, settings: dict, topic: str, schema,
                 poll_timeout_s: float = 0.2):
        super().__init__(node)
        self.settings = dict(settings)
        self.topic = topic
        self._cdc = _CdcApplier(node, schema)
        self.poll_timeout_s = poll_timeout_s
        self._positions: dict[int, int] = {}
        self._seek_to: dict[int, int] = {}

    def current_offset(self):
        return dict(self._positions)

    def seek_offset(self, offset) -> None:
        if isinstance(offset, dict):
            self._seek_to = {int(p): int(o) for p, o in offset.items()}
            self._positions.update(self._seek_to)

    def on_replay(self, rows) -> None:
        self._cdc.replay(rows)

    def run(self):
        consumer = make_kafka_consumer(
            self.settings, self.topic, self._seek_to, start_from_latest=False
        )
        try:
            while not self.should_stop():
                msg = consumer.poll(self.poll_timeout_s)
                if msg is None:
                    continue
                rows: list = []
                n = 0
                while msg is not None and n < self.MAX_DRAIN:
                    if msg.error():
                        from pathway_tpu.internals.errors import (
                            get_global_error_log,
                        )

                        get_global_error_log().log(
                            f"debezium kafka error: {msg.error()}"
                        )
                    else:
                        rows.extend(self._cdc.apply(msg.value()))
                        self._positions[msg.partition()] = msg.offset()
                    n += 1
                    msg = consumer.poll(0)
                if rows:
                    self.commit_rows(rows)
        finally:
            consumer.close()


def read(
    rdkafka_settings: dict | InMemoryKafkaBroker,
    topic_name: str,
    *,
    schema: Any,
    db_type: str = "postgres",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    **kwargs,
) -> Table:
    """Read a Debezium CDC stream into an upserted table — from an
    ``InMemoryKafkaBroker`` or a real cluster (``rdkafka_settings`` dict,
    gated on ``confluent_kafka`` like ``pw.io.kafka``)."""
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"debezium({topic_name})")
    if isinstance(rdkafka_settings, InMemoryKafkaBroker):
        conn = _DebeziumConnector(node, rdkafka_settings, topic_name, schema)
    elif isinstance(rdkafka_settings, dict):
        _confluent()  # fail fast with a clear error when the client is absent
        conn = _DebeziumKafkaConnector(node, rdkafka_settings, topic_name, schema)
    else:
        raise TypeError(
            f"rdkafka_settings must be a settings dict or an "
            f"InMemoryKafkaBroker, got {type(rdkafka_settings).__name__}"
        )
    G.register_connector(conn)
    table = Table(node, schema, Universe())
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(persistent_id, conn)
    return table
