"""Airbyte source connector (reference ``python/pathway/io/airbyte``:
runs an Airbyte connector and streams its RECORD messages as a
``data: Json`` column, incremental STATE kept between polls).

Execution modes (reference ``io/airbyte/logic.py`` +
``third_party/airbyte_serverless/sources.py:89-140``):

* ``execution_type="local"`` — a local connector process. Either the
  ``airbyte_serverless`` package (PyPI venv runner) or any executable
  speaking the Airbyte protocol via :class:`ExecutableAirbyteSource`.
* ``execution_type="docker"`` — the connector's public Docker image,
  wrapped as ``docker run --rm -i --volume <tmp>:<mnt> <image>``
  (:class:`DockerAirbyteSource`). Gated on a ``docker`` binary.
* ``execution_type="remote"`` — the connector image runs as a Google
  Cloud Run JOB (:class:`RemoteAirbyteSource`): a self-contained runner
  script is delivered via env var, incremental state rides the execution
  overrides, and results come back through Cloud Logging in the
  reference-compatible chunked transport. Gated on the google-cloud
  SDKs; tests inject jobs/logs client doubles.
* ``_source=...`` — any object with ``extract(streams) -> iterable`` of
  Airbyte RECORD message dicts (in-process; used by tests and embedded
  sources).

The subprocess contract is the standard Airbyte connector CLI: actions
``spec`` / ``discover --config c.json`` / ``read --config c.json
--catalog cat.json [--state s.json]``, each emitting JSON-lines messages
on stdout; RECORD rows stream into the table, the latest STATE message is
fed back on the next poll so incremental streams resume instead of
re-reading."""

from __future__ import annotations

import json as json_mod
import os
import shlex
import subprocess
import tempfile
import time as time_mod
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals.config import environ_snapshot
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector


class AirbyteSourceError(RuntimeError):
    """A connector emitted a TRACE error message (reference
    ``executable_runner.py: AirbyteSourceException``)."""


class ExecutableAirbyteSource:
    """Runs any executable speaking the Airbyte connector CLI protocol.

    ``executable`` is the command prefix (string, shell-quoted as needed);
    config/catalog/state are passed as ``--name <tempdir>/name.json`` file
    arguments exactly like the reference's runner
    (``third_party/airbyte_serverless/executable_runner.py:208-246``).
    Incremental: the newest STATE message from each ``read`` is kept on
    ``self.state`` and passed back on the next ``extract``."""

    def __init__(self, executable: str, config: dict | None = None,
                 streams: Sequence[str] | None = None,
                 env_vars: dict[str, str] | None = None):
        self.executable = executable
        self.config = config or {}
        self.streams = list(streams or [])
        self.env_vars = env_vars
        self._temp_dir_obj = tempfile.TemporaryDirectory()
        self.temp_dir = self._temp_dir_obj.name
        # where the executable sees the temp dir (differs under docker,
        # where the host dir is volume-mounted)
        self.temp_dir_for_executable = self.temp_dir
        self.state: Any = None
        self._catalog: dict | None = None

    # -- protocol ----------------------------------------------------------
    def _run(self, action: str, state=None) -> Iterable[dict]:
        command = f"{self.executable} {action}"

        def add_argument(name: str, value) -> str:
            path = os.path.join(self.temp_dir, f"{name}.json")
            with open(path, "w", encoding="utf-8") as f:
                json_mod.dump(value, f)
            return (
                f" --{name} {self.temp_dir_for_executable}/{name}.json"
            )

        if action != "spec":
            command += add_argument("config", self.config)
        if action == "read":
            command += add_argument("catalog", self.configured_catalog)
            if state is not None:
                command += add_argument("state", state)
        env = (
            environ_snapshot(**self.env_vars) if self.env_vars else None
        )  # augment, never replace: the connector still needs PATH etc.
        proc = subprocess.Popen(
            command, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            shell=True, env=env,
        )
        assert proc.stdout is not None
        try:
            for line in iter(proc.stdout.readline, b""):
                content = line.decode(errors="replace").strip()
                if not content:
                    continue
                try:
                    message = json_mod.loads(content)
                except ValueError:
                    continue  # connectors log non-JSON noise on stdout
                if not isinstance(message, dict):
                    continue  # valid-JSON scalar noise (e.g. bare strings)
                if (message.get("trace") or {}).get("error"):
                    raise AirbyteSourceError(
                        json_mod.dumps(message["trace"]["error"])
                    )
                yield message
            proc.wait()
            if proc.returncode != 0:
                raise AirbyteSourceError(
                    f"connector exited with status {proc.returncode} "
                    f"(action {action!r})"
                )
        finally:
            # early generator close (_first_message, TRACE error) must not
            # leak a running connector process
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def _first_message(self, action: str) -> dict:
        for message in self._run(action):
            if message.get("type") not in ("LOG", "TRACE"):
                return message
        raise AirbyteSourceError(f"no message from action {action!r}")

    @property
    def spec(self) -> dict:
        return self._first_message("spec")["spec"]

    @property
    def catalog(self) -> dict:
        if self._catalog is None:
            self._catalog = self._first_message("discover")["catalog"]
        return self._catalog

    @property
    def configured_catalog(self) -> dict:
        """Every requested stream, incremental where the connector supports
        it (reference ``executable_runner.py: get_configured_catalog``)."""
        configured = []
        for stream in self.catalog.get("streams", []):
            if self.streams and stream.get("name") not in self.streams:
                continue
            modes = stream.get("supported_sync_modes") or ["full_refresh"]
            sync_mode = (
                "incremental" if "incremental" in modes else "full_refresh"
            )
            configured.append(
                {
                    "stream": stream,
                    "sync_mode": sync_mode,
                    "destination_sync_mode": "append",
                    "cursor_field": stream.get("default_cursor_field", []),
                }
            )
        return {"streams": configured}

    def extract(self, streams: Sequence[str] | None = None) -> list[dict]:
        """One ``read`` pass: returns RECORD messages, stores the newest
        STATE for the next call."""
        if streams:
            self.streams = list(streams)
        out = []
        for message in self._run("read", state=self.state):
            mtype = message.get("type")
            if mtype == "RECORD":
                out.append(message)
            elif mtype == "STATE":
                self.state = message.get("state")
        return out


def _docker_command(image: str, temp_dir: str, mount_dir: str,
                    env_vars: dict[str, str] | None = None) -> str:
    """The docker envelope the reference builds
    (``third_party/airbyte_serverless/sources.py:108-111``)."""
    env = " ".join(
        f"-e {shlex.quote(k)}={shlex.quote(v)}"
        for k, v in (env_vars or {}).items()
    )
    env = f"{env} " if env else ""
    return (
        f"docker run --rm -i --volume {temp_dir}:{mount_dir} "
        f"{env}{image}"
    )


class DockerAirbyteSource(ExecutableAirbyteSource):
    """Runs the connector's public Docker image. Gated: constructing
    without a ``docker`` binary raises (this build's image has none; the
    envelope itself is covered by tests through ``_docker_command``)."""

    def __init__(self, connector: str, config: dict | None = None,
                 streams: Sequence[str] | None = None,
                 env_vars: dict[str, str] | None = None):
        import shutil

        if shutil.which("docker") is None:
            raise RuntimeError(
                "execution_type='docker' needs a docker binary on PATH; "
                "use execution_type='local' or pass _source=..."
            )
        super().__init__("", config, streams)
        self.docker_image = connector
        self.temp_dir_for_executable = "/mnt/temp"
        self.executable = _docker_command(
            connector, self.temp_dir, self.temp_dir_for_executable, env_vars
        )


# ---------------------------------------------------------------------------
# remote (Google Cloud Run) execution


class LogChunkTransport:
    """The chunked stdout->Cloud-Logging result transport the reference's
    remote runner speaks (``executable_runner.py:52-160``): the run's
    messages + zlib/b64 catalog are JSON-serialized, split into
    log-entry-sized chunks, and printed with an index header so the
    collector can reassemble them from unordered log entries. Field names
    match the reference wire format, so either side's runner works with
    either side's collector."""

    ENTRY_TYPE = "__entry_type"
    INDEX = "index"
    PAYLOAD = "payload"
    MESSAGES = "messages"
    CATALOG = "catalog"
    METADATA = "metadata"
    CHUNK = "chunk"
    N_CHUNKS = "n_chunks"
    MAX_LOG_ENTRY_LENGTH = 262144
    MAX_ENV_LENGTH = 32768

    @classmethod
    def serialize(cls, messages: list, catalog: Any) -> list[dict]:
        import base64
        import zlib

        catalog_b64 = base64.b64encode(
            zlib.compress(
                json_mod.dumps(catalog, ensure_ascii=False).encode(),
                level=zlib.Z_BEST_COMPRESSION,
            )
        ).decode()
        if len(catalog_b64) > cls.MAX_ENV_LENGTH:
            catalog_b64 = None
        body = json_mod.dumps(
            {cls.MESSAGES: list(messages), cls.CATALOG: catalog_b64},
            ensure_ascii=False,
        )
        size = int(cls.MAX_LOG_ENTRY_LENGTH * 0.9 / 4 - 256) // 2
        chunks = [body[i : i + size] for i in range(0, len(body), size)]
        out = [{cls.ENTRY_TYPE: cls.METADATA, cls.N_CHUNKS: len(chunks)}]
        out.extend(
            {cls.ENTRY_TYPE: cls.CHUNK, cls.INDEX: i, cls.PAYLOAD: c}
            for i, c in enumerate(chunks)
        )
        return out

    def __init__(self):
        self._expected: int | None = None
        # keyed by index: Cloud Logging delivers at-least-once, and a
        # duplicated chunk entry must not wedge the count-based check
        self._chunks: dict[int, str] = {}

    def append(self, payload: Any) -> None:
        if not isinstance(payload, dict):
            return
        entry = payload.get(self.ENTRY_TYPE)
        if entry == self.METADATA:
            self._expected = payload[self.N_CHUNKS]
        elif entry == self.CHUNK:
            self._chunks[int(payload[self.INDEX])] = payload[self.PAYLOAD]

    def _restore(self):
        if self._expected is None or self._expected != len(self._chunks):
            return None
        return json_mod.loads(
            "".join(self._chunks[i] for i in sorted(self._chunks))
        )

    def messages(self):
        r = self._restore()
        return None if r is None else r[self.MESSAGES]

    def catalog_b64(self):
        r = self._restore()
        return None if r is None else r[self.CATALOG]


# The script delivered (base64, env var) into the connector container on
# Cloud Run: runs discover + read against the image's own entrypoint and
# prints the results through the chunked log transport. Self-contained —
# the container only needs python3 (every Airbyte connector image has it).
_REMOTE_RUNNER_TEMPLATE = r'''
import base64, json, os, shlex, subprocess, tempfile, zlib

MAX_LOG = @MAX_LOG@
MAX_ENV = @MAX_ENV@

def sh(cmd):
    out = subprocess.run(cmd, shell=True, capture_output=True, text=True)
    lines = []
    for line in out.stdout.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            lines.append(json.loads(line))
        except ValueError:
            continue
    return lines, out.returncode, out.stderr[-2000:]

cfg = json.loads(zlib.decompress(base64.b64decode(os.environ["PW_CONFIG"])))
entry = os.environ.get("AIRBYTE_ENTRYPOINT", "python /airbyte/integration_code/main.py")
tmp = tempfile.mkdtemp()
cpath = os.path.join(tmp, "config.json")
with open(cpath, "w") as f:
    json.dump(cfg.get("config", {}), f)
catalog = None
cached = os.environ.get("CACHED_CATALOG")
if cached:
    catalog = json.loads(zlib.decompress(base64.b64decode(cached)))
if catalog is None:
    found, rc, err = sh(f"{entry} discover --config {shlex.quote(cpath)}")
    for m in found:
        if m.get("type") == "CATALOG":
            catalog = m["catalog"]
    if catalog is None:
        raise SystemExit(f"no CATALOG from discover (rc={rc}): {err}")
streams = [s for s in (cfg.get("streams") or []) if s]
conf = {
    "streams": [
        {
            "stream": st,
            "sync_mode": (
                "incremental"
                if "incremental" in (st.get("supported_sync_modes") or [])
                else "full_refresh"
            ),
            "destination_sync_mode": "append",
            "cursor_field": st.get("default_cursor_field", []),
        }
        for st in catalog["streams"]
        if not streams or st["name"] in streams
    ]
}
catpath = os.path.join(tmp, "catalog.json")
with open(catpath, "w") as f:
    json.dump(conf, f)
cmd = f"{entry} read --config {shlex.quote(cpath)} --catalog {shlex.quote(catpath)}"
state = os.environ.get("AIRBYTE_STATE")
if state and state != "null":
    spath = os.path.join(tmp, "state.json")
    with open(spath, "w") as f:
        f.write(state)
    cmd += f" --state {shlex.quote(spath)}"
raw, rc, err = sh(cmd)
messages = [
    m for m in raw if m.get("type") in ("RECORD", "STATE", "TRACE")
]
if rc != 0 and not messages:
    # a silently-crashed read must surface as an ERROR, not an empty poll
    messages = [{
        "type": "TRACE",
        "trace": {"error": {"message": f"connector read failed rc={rc}",
                            "stderr": err}},
    }]
catalog_b64 = base64.b64encode(
    zlib.compress(json.dumps(catalog, ensure_ascii=False).encode(), 9)
).decode()
if len(catalog_b64) > MAX_ENV:
    catalog_b64 = None
body = json.dumps({"messages": messages, "catalog": catalog_b64},
                  ensure_ascii=False)
size = int(MAX_LOG * 0.9 / 4 - 256) // 2
chunks = [body[i:i + size] for i in range(0, len(body), size)]
print(json.dumps({"__entry_type": "metadata", "n_chunks": len(chunks)}))
for i, c in enumerate(chunks):
    print(json.dumps({"__entry_type": "chunk", "index": i, "payload": c},
                     ensure_ascii=False))
'''

# one source of truth for the wire constants: the embedded runner is the
# template with the transport's limits substituted in
_REMOTE_RUNNER_SOURCE = (
    _REMOTE_RUNNER_TEMPLATE
    .replace("@MAX_LOG@", str(LogChunkTransport.MAX_LOG_ENTRY_LENGTH))
    .replace("@MAX_ENV@", str(LogChunkTransport.MAX_ENV_LENGTH))
)


class RemoteAirbyteSource:
    """Runs the connector image as a Google Cloud Run JOB (reference
    ``RemoteAirbyteSource``, ``third_party/airbyte_serverless/
    sources.py:173``): the job is created at construction, each
    ``extract`` triggers one execution with the incremental state (and
    cached catalog) delivered via env overrides, and results come back
    through Cloud Logging using the chunked transport above.

    Gated: without the ``google-cloud-run`` / ``google-cloud-logging``
    SDKs, construction requires injected ``jobs_client`` (create_job /
    run_job / delete_job) and ``logs_lister(execution_id) ->
    iterable[payload]`` doubles — the air-gapped test surface."""

    def __init__(self, config: dict, streams: Sequence[str], *,
                 job_id: str, region: str,
                 credentials: Any = None,
                 env_vars: dict[str, str] | None = None,
                 project: str | None = None,
                 jobs_client: Any = None,
                 logs_lister: Any = None,
                 logs_timeout_s: float = 300.0):
        import base64
        import zlib

        self.config = config
        self.streams = list(streams)
        self.job_id = job_id
        self.region = region
        self.env_vars = dict(env_vars or {})
        self.state: Any = None
        self._cached_catalog_b64: str | None = None
        self.logs_timeout_s = logs_timeout_s
        self.project = project or getattr(credentials, "project_id", None)
        if self.project is None:
            # ambient (ADC) credentials carry no project id; the job
            # parent path needs one — fail here, not with a 404 later
            raise ValueError(
                "remote Airbyte execution needs a GCP project id: pass "
                "gcp_project=... (or credentials with project_id)"
            )
        if jobs_client is None or logs_lister is None:
            try:
                import google.cloud.logging as gcp_logging  # type: ignore
                import google.cloud.run_v2 as run_v2  # type: ignore
            except ImportError as exc:
                raise ImportError(
                    "execution_type='remote' needs the google-cloud-run "
                    "and google-cloud-logging SDKs (or injected "
                    "jobs_client/logs_lister doubles)"
                ) from exc
            jobs_client = jobs_client or run_v2.JobsClient(
                credentials=credentials
            )
            if logs_lister is None:
                log_client = gcp_logging.Client(
                    project=self.project, credentials=credentials
                )

                def logs_lister(execution_id):  # noqa: F811
                    return (
                        e.payload
                        for e in log_client.list_entries(
                            filter_=(
                                'labels."run.googleapis.com/'
                                f'execution_name" = {execution_id}'
                            ),
                            page_size=1000,
                        )
                    )
        self.jobs = jobs_client
        self.logs_lister = logs_lister
        payload = {
            "config": (config.get("source") or {}).get("config", {}),
            "streams": self.streams,
        }
        self._config_env = base64.b64encode(
            zlib.compress(json_mod.dumps(payload).encode(), 9)
        ).decode()
        if len(self._config_env) > LogChunkTransport.MAX_ENV_LENGTH:
            raise ValueError(
                "connector config too large for a Cloud Run env var "
                f"({len(self._config_env)} b64 bytes > "
                f"{LogChunkTransport.MAX_ENV_LENGTH})"
            )
        self._create_job()

    @property
    def job_name(self) -> str:
        return (
            f"projects/{self.project}/locations/{self.region}"
            f"/jobs/{self.job_id}"
        )

    def _create_job(self) -> None:
        import base64

        self.maybe_delete_job()
        image = (self.config.get("source") or {})["docker_image"]
        env = [{"name": k, "value": v} for k, v in self.env_vars.items()]
        env.append({"name": "PW_CONFIG", "value": self._config_env})
        env.append({
            "name": "RUNNER_CODE",
            "value": base64.b64encode(
                _REMOTE_RUNNER_SOURCE.encode()
            ).decode(),
        })
        container = {
            # the override at run time targets the container by NAME (a
            # DNS_LABEL) — the image string is not a valid name
            "name": "connector",
            "image": image,
            "command": ["/bin/sh"],
            "args": [
                "-c",
                " && ".join([
                    "echo $RUNNER_CODE > runner.txt",
                    "base64 -d < runner.txt > runner.py",
                    "python3 runner.py",
                ]),
            ],
            "env": env,
            "resources": {"limits": {"memory": "512Mi", "cpu": "1"}},
        }
        self.jobs.create_job(
            job={"template": {"template": {
                "containers": [container],
                "timeout": {"seconds": 3600},
                "max_retries": 0,
            }}},
            job_id=self.job_id,
            parent=f"projects/{self.project}/locations/{self.region}",
        ).result()

    def maybe_delete_job(self) -> None:
        try:
            self.jobs.delete_job(name=self.job_name).result()
        except Exception:  # noqa: BLE001 - absent job / NotFound
            pass

    def on_stop(self) -> None:
        self.maybe_delete_job()

    def extract(self, streams: Sequence[str] = ()) -> Iterable[dict]:
        prepared_state = json_mod.dumps(self.state)
        if len(prepared_state) > LogChunkTransport.MAX_ENV_LENGTH:
            raise ValueError(
                "incremental state too large for a Cloud Run env var; "
                "use fewer streams per read()"
            )
        overrides = []
        if self.state is not None:
            overrides.append({"name": "AIRBYTE_STATE",
                              "value": prepared_state})
        if self._cached_catalog_b64 is not None:
            overrides.append({"name": "CACHED_CATALOG",
                              "value": self._cached_catalog_b64})
        op = self.jobs.run_job({
            "name": self.job_name,
            "overrides": {"container_overrides": [{
                "name": "connector",
                "env": overrides,
            }]},
        })
        execution_id = op.metadata.name.split("/")[-1]
        result = op.result()
        if getattr(result, "succeeded_count", 1) != 1:
            raise AirbyteSourceError(
                f"Cloud Run execution {execution_id} failed"
            )
        messages = None
        deadline = time_mod.monotonic() + self.logs_timeout_s
        while messages is None:
            transport = LogChunkTransport()
            for payload in self.logs_lister(execution_id):
                transport.append(payload)
            messages = transport.messages()
            if messages is None:
                if time_mod.monotonic() > deadline:
                    raise AirbyteSourceError(
                        f"no complete result in Cloud Logging for "
                        f"execution {execution_id} after "
                        f"{self.logs_timeout_s}s"
                    )
                time_mod.sleep(3.0)
                continue
            self._cached_catalog_b64 = transport.catalog_b64()
        # fail BEFORE committing state: advancing the cursor while
        # discarding the batch's records would silently skip them forever
        for message in messages:
            if (message.get("trace") or {}).get("error"):
                raise AirbyteSourceError(
                    json_mod.dumps(message["trace"]["error"])
                )
        for message in messages:
            if message.get("type") == "STATE":
                self.state = message.get("state")
        return [m for m in messages if m.get("type") == "RECORD"]


def _make_serverless_source(config_file_path, streams, env_vars, enforce_method):
    try:
        import yaml
        from airbyte_serverless.sources import DockerizedSource  # type: ignore
    except ImportError as exc:
        raise ImportError(
            "pw.io.airbyte.read needs the airbyte-serverless package for "
            "local/docker execution (or pass _source=... for an in-process "
            "source)"
        ) from exc
    with open(config_file_path) as f:
        config = yaml.safe_load(f)
    source_config = config["source"]
    return DockerizedSource(
        connector=source_config["docker_image"],
        config=source_config.get("config", {}),
        streams=",".join(streams),
    )


class _AirbyteConnector(BaseConnector):
    def __init__(self, node, source, streams: Sequence[str], mode: str,
                 refresh_interval_ms: int):
        super().__init__(node)
        self.source = source
        self.streams = list(streams)
        self.mode = mode
        self.refresh_interval = refresh_interval_ms / 1000.0
        self._counter = 0
        if mode != "static":
            self.heartbeat_ms = 500

    def _poll_once(self) -> list[tuple[int, tuple, int]]:
        rows = []
        for message in self.source.extract(self.streams):
            record = message.get("record") if isinstance(message, dict) else None
            if record is None:
                continue
            if self.streams and record.get("stream") not in self.streams:
                continue
            key = hash_values("airbyte", self._counter)
            self._counter += 1
            rows.append((key, (Json(record.get("data", {})),), 1))
        return rows

    def run(self) -> None:
        rows = self._poll_once()
        self.commit_rows(rows)
        if self.mode == "static":
            return
        while not self.should_stop():
            time_mod.sleep(self.refresh_interval)
            rows = self._poll_once()
            if rows:
                self.commit_rows(rows)


def read(
    config_file_path: "os.PathLike | str" = "",
    streams: Sequence[str] = (),
    *,
    execution_type: str = "local",
    mode: str = "streaming",
    env_vars: dict[str, str] | None = None,
    service_user_credentials_file: str | None = None,
    gcp_region: str = "europe-west1",
    gcp_job_name: str | None = None,
    gcp_project: str | None = None,
    enforce_method: str | None = None,
    refresh_interval_ms: int = 60000,
    persistent_id: str | None = None,
    _source=None,
) -> Table:
    """Stream Airbyte RECORD messages of the selected ``streams`` into a
    ``data: Json`` table (reference ``io/airbyte/__init__.py:107``)."""
    if _source is None:
        if execution_type == "docker":
            import yaml

            with open(config_file_path) as f:
                config = yaml.safe_load(f)
            source_config = config["source"]
            _source = DockerAirbyteSource(
                source_config["docker_image"],
                source_config.get("config", {}),
                streams,
                env_vars,
            )
        elif execution_type == "remote":
            import yaml

            with open(config_file_path) as f:
                config = yaml.safe_load(f)
            credentials = None
            if service_user_credentials_file is not None:
                from google.oauth2 import service_account  # type: ignore

                credentials = (
                    service_account.Credentials.from_service_account_file(
                        service_user_credentials_file
                    )
                )
            job_id = gcp_job_name or (
                "pathway-airbyte-"
                + format(hash_values(str(config_file_path)) & 0xFFFFFF, "x")
            )
            _source = RemoteAirbyteSource(
                config, streams, job_id=job_id, region=gcp_region,
                credentials=credentials, env_vars=env_vars,
                project=gcp_project,
            )
        elif execution_type != "local":
            raise ValueError(
                f"unknown execution_type {execution_type!r}; expected "
                "'local', 'docker' or 'remote'"
            )
        else:
            _source = _make_serverless_source(
                config_file_path, streams, env_vars, enforce_method
            )
    schema = schema_mod.schema_from_types(data=dt.JSON)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"airbyte({','.join(streams)})")
    conn = _AirbyteConnector(node, _source, streams, mode, refresh_interval_ms)
    G.register_connector(conn)
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(str(persistent_id), conn)
    return Table(node, schema, Universe())
