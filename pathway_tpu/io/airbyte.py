"""Airbyte source connector (reference ``python/pathway/io/airbyte``:
runs an Airbyte connector and streams its RECORD messages as a
``data: Json`` column, incremental STATE kept between polls).

Execution modes (reference ``io/airbyte/logic.py`` +
``third_party/airbyte_serverless/sources.py:89-140``):

* ``execution_type="local"`` — a local connector process. Either the
  ``airbyte_serverless`` package (PyPI venv runner) or any executable
  speaking the Airbyte protocol via :class:`ExecutableAirbyteSource`.
* ``execution_type="docker"`` — the connector's public Docker image,
  wrapped as ``docker run --rm -i --volume <tmp>:<mnt> <image>``
  (:class:`DockerAirbyteSource`). Gated on a ``docker`` binary.
* ``_source=...`` — any object with ``extract(streams) -> iterable`` of
  Airbyte RECORD message dicts (in-process; used by tests and embedded
  sources).

The subprocess contract is the standard Airbyte connector CLI: actions
``spec`` / ``discover --config c.json`` / ``read --config c.json
--catalog cat.json [--state s.json]``, each emitting JSON-lines messages
on stdout; RECORD rows stream into the table, the latest STATE message is
fed back on the next poll so incremental streams resume instead of
re-reading."""

from __future__ import annotations

import json as json_mod
import os
import shlex
import subprocess
import tempfile
import time as time_mod
from typing import Any, Iterable, Sequence

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector


class AirbyteSourceError(RuntimeError):
    """A connector emitted a TRACE error message (reference
    ``executable_runner.py: AirbyteSourceException``)."""


class ExecutableAirbyteSource:
    """Runs any executable speaking the Airbyte connector CLI protocol.

    ``executable`` is the command prefix (string, shell-quoted as needed);
    config/catalog/state are passed as ``--name <tempdir>/name.json`` file
    arguments exactly like the reference's runner
    (``third_party/airbyte_serverless/executable_runner.py:208-246``).
    Incremental: the newest STATE message from each ``read`` is kept on
    ``self.state`` and passed back on the next ``extract``."""

    def __init__(self, executable: str, config: dict | None = None,
                 streams: Sequence[str] | None = None,
                 env_vars: dict[str, str] | None = None):
        self.executable = executable
        self.config = config or {}
        self.streams = list(streams or [])
        self.env_vars = env_vars
        self._temp_dir_obj = tempfile.TemporaryDirectory()
        self.temp_dir = self._temp_dir_obj.name
        # where the executable sees the temp dir (differs under docker,
        # where the host dir is volume-mounted)
        self.temp_dir_for_executable = self.temp_dir
        self.state: Any = None
        self._catalog: dict | None = None

    # -- protocol ----------------------------------------------------------
    def _run(self, action: str, state=None) -> Iterable[dict]:
        command = f"{self.executable} {action}"

        def add_argument(name: str, value) -> str:
            path = os.path.join(self.temp_dir, f"{name}.json")
            with open(path, "w", encoding="utf-8") as f:
                json_mod.dump(value, f)
            return (
                f" --{name} {self.temp_dir_for_executable}/{name}.json"
            )

        if action != "spec":
            command += add_argument("config", self.config)
        if action == "read":
            command += add_argument("catalog", self.configured_catalog)
            if state is not None:
                command += add_argument("state", state)
        env = (
            {**os.environ, **self.env_vars} if self.env_vars else None
        )  # augment, never replace: the connector still needs PATH etc.
        proc = subprocess.Popen(
            command, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            shell=True, env=env,
        )
        assert proc.stdout is not None
        try:
            for line in iter(proc.stdout.readline, b""):
                content = line.decode(errors="replace").strip()
                if not content:
                    continue
                try:
                    message = json_mod.loads(content)
                except ValueError:
                    continue  # connectors log non-JSON noise on stdout
                if not isinstance(message, dict):
                    continue  # valid-JSON scalar noise (e.g. bare strings)
                if (message.get("trace") or {}).get("error"):
                    raise AirbyteSourceError(
                        json_mod.dumps(message["trace"]["error"])
                    )
                yield message
            proc.wait()
            if proc.returncode != 0:
                raise AirbyteSourceError(
                    f"connector exited with status {proc.returncode} "
                    f"(action {action!r})"
                )
        finally:
            # early generator close (_first_message, TRACE error) must not
            # leak a running connector process
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def _first_message(self, action: str) -> dict:
        for message in self._run(action):
            if message.get("type") not in ("LOG", "TRACE"):
                return message
        raise AirbyteSourceError(f"no message from action {action!r}")

    @property
    def spec(self) -> dict:
        return self._first_message("spec")["spec"]

    @property
    def catalog(self) -> dict:
        if self._catalog is None:
            self._catalog = self._first_message("discover")["catalog"]
        return self._catalog

    @property
    def configured_catalog(self) -> dict:
        """Every requested stream, incremental where the connector supports
        it (reference ``executable_runner.py: get_configured_catalog``)."""
        configured = []
        for stream in self.catalog.get("streams", []):
            if self.streams and stream.get("name") not in self.streams:
                continue
            modes = stream.get("supported_sync_modes") or ["full_refresh"]
            sync_mode = (
                "incremental" if "incremental" in modes else "full_refresh"
            )
            configured.append(
                {
                    "stream": stream,
                    "sync_mode": sync_mode,
                    "destination_sync_mode": "append",
                    "cursor_field": stream.get("default_cursor_field", []),
                }
            )
        return {"streams": configured}

    def extract(self, streams: Sequence[str] | None = None) -> list[dict]:
        """One ``read`` pass: returns RECORD messages, stores the newest
        STATE for the next call."""
        if streams:
            self.streams = list(streams)
        out = []
        for message in self._run("read", state=self.state):
            mtype = message.get("type")
            if mtype == "RECORD":
                out.append(message)
            elif mtype == "STATE":
                self.state = message.get("state")
        return out


def _docker_command(image: str, temp_dir: str, mount_dir: str,
                    env_vars: dict[str, str] | None = None) -> str:
    """The docker envelope the reference builds
    (``third_party/airbyte_serverless/sources.py:108-111``)."""
    env = " ".join(
        f"-e {shlex.quote(k)}={shlex.quote(v)}"
        for k, v in (env_vars or {}).items()
    )
    env = f"{env} " if env else ""
    return (
        f"docker run --rm -i --volume {temp_dir}:{mount_dir} "
        f"{env}{image}"
    )


class DockerAirbyteSource(ExecutableAirbyteSource):
    """Runs the connector's public Docker image. Gated: constructing
    without a ``docker`` binary raises (this build's image has none; the
    envelope itself is covered by tests through ``_docker_command``)."""

    def __init__(self, connector: str, config: dict | None = None,
                 streams: Sequence[str] | None = None,
                 env_vars: dict[str, str] | None = None):
        import shutil

        if shutil.which("docker") is None:
            raise RuntimeError(
                "execution_type='docker' needs a docker binary on PATH; "
                "use execution_type='local' or pass _source=..."
            )
        super().__init__("", config, streams)
        self.docker_image = connector
        self.temp_dir_for_executable = "/mnt/temp"
        self.executable = _docker_command(
            connector, self.temp_dir, self.temp_dir_for_executable, env_vars
        )


def _make_serverless_source(config_file_path, streams, env_vars, enforce_method):
    try:
        import yaml
        from airbyte_serverless.sources import DockerizedSource  # type: ignore
    except ImportError as exc:
        raise ImportError(
            "pw.io.airbyte.read needs the airbyte-serverless package for "
            "local/docker execution (or pass _source=... for an in-process "
            "source)"
        ) from exc
    with open(config_file_path) as f:
        config = yaml.safe_load(f)
    source_config = config["source"]
    return DockerizedSource(
        connector=source_config["docker_image"],
        config=source_config.get("config", {}),
        streams=",".join(streams),
    )


class _AirbyteConnector(BaseConnector):
    def __init__(self, node, source, streams: Sequence[str], mode: str,
                 refresh_interval_ms: int):
        super().__init__(node)
        self.source = source
        self.streams = list(streams)
        self.mode = mode
        self.refresh_interval = refresh_interval_ms / 1000.0
        self._counter = 0
        if mode != "static":
            self.heartbeat_ms = 500

    def _poll_once(self) -> list[tuple[int, tuple, int]]:
        rows = []
        for message in self.source.extract(self.streams):
            record = message.get("record") if isinstance(message, dict) else None
            if record is None:
                continue
            if self.streams and record.get("stream") not in self.streams:
                continue
            key = hash_values("airbyte", self._counter)
            self._counter += 1
            rows.append((key, (Json(record.get("data", {})),), 1))
        return rows

    def run(self) -> None:
        rows = self._poll_once()
        self.commit_rows(rows)
        if self.mode == "static":
            return
        while not self.should_stop():
            time_mod.sleep(self.refresh_interval)
            rows = self._poll_once()
            if rows:
                self.commit_rows(rows)


def read(
    config_file_path: "os.PathLike | str" = "",
    streams: Sequence[str] = (),
    *,
    execution_type: str = "local",
    mode: str = "streaming",
    env_vars: dict[str, str] | None = None,
    service_user_credentials_file: str | None = None,
    gcp_region: str = "europe-west1",
    gcp_job_name: str | None = None,
    enforce_method: str | None = None,
    refresh_interval_ms: int = 60000,
    persistent_id: str | None = None,
    _source=None,
) -> Table:
    """Stream Airbyte RECORD messages of the selected ``streams`` into a
    ``data: Json`` table (reference ``io/airbyte/__init__.py:107``)."""
    if _source is None:
        if execution_type == "docker":
            import yaml

            with open(config_file_path) as f:
                config = yaml.safe_load(f)
            source_config = config["source"]
            _source = DockerAirbyteSource(
                source_config["docker_image"],
                source_config.get("config", {}),
                streams,
                env_vars,
            )
        elif execution_type != "local":
            raise NotImplementedError(
                "remote (GCP) Airbyte execution requires cloud access; use "
                "execution_type='local'/'docker' or pass _source=..."
            )
        else:
            _source = _make_serverless_source(
                config_file_path, streams, env_vars, enforce_method
            )
    schema = schema_mod.schema_from_types(data=dt.JSON)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"airbyte({','.join(streams)})")
    conn = _AirbyteConnector(node, _source, streams, mode, refresh_interval_ms)
    G.register_connector(conn)
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(str(persistent_id), conn)
    return Table(node, schema, Universe())
