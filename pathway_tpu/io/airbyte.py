"""Airbyte source connector (reference ``python/pathway/io/airbyte``:
runs an Airbyte connector via `airbyte-serverless` (PyPI venv or docker) and
streams its record messages as a ``data: Json`` column, incremental state
kept between polls).

This build has no network/docker egress, so the runner is pluggable: pass
``_source`` (any object with ``extract(streams) -> iterable`` yielding
Airbyte RECORD message dicts) to use an in-process source; otherwise the
``airbyte_serverless`` package is required, matching the reference's local
execution type."""

from __future__ import annotations

import os
import time as time_mod
from typing import Any, Sequence

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector


def _make_serverless_source(config_file_path, streams, env_vars, enforce_method):
    try:
        import yaml
        from airbyte_serverless.sources import DockerizedSource  # type: ignore
    except ImportError as exc:
        raise ImportError(
            "pw.io.airbyte.read needs the airbyte-serverless package for "
            "local/docker execution (or pass _source=... for an in-process "
            "source)"
        ) from exc
    with open(config_file_path) as f:
        config = yaml.safe_load(f)
    source_config = config["source"]
    return DockerizedSource(
        connector=source_config["docker_image"],
        config=source_config.get("config", {}),
        streams=",".join(streams),
    )


class _AirbyteConnector(BaseConnector):
    def __init__(self, node, source, streams: Sequence[str], mode: str,
                 refresh_interval_ms: int):
        super().__init__(node)
        self.source = source
        self.streams = list(streams)
        self.mode = mode
        self.refresh_interval = refresh_interval_ms / 1000.0
        self._counter = 0
        if mode != "static":
            self.heartbeat_ms = 500

    def _poll_once(self) -> list[tuple[int, tuple, int]]:
        rows = []
        for message in self.source.extract(self.streams):
            record = message.get("record") if isinstance(message, dict) else None
            if record is None:
                continue
            if self.streams and record.get("stream") not in self.streams:
                continue
            key = hash_values("airbyte", self._counter)
            self._counter += 1
            rows.append((key, (Json(record.get("data", {})),), 1))
        return rows

    def run(self) -> None:
        rows = self._poll_once()
        self.commit_rows(rows)
        if self.mode == "static":
            return
        while not self.should_stop():
            time_mod.sleep(self.refresh_interval)
            rows = self._poll_once()
            if rows:
                self.commit_rows(rows)


def read(
    config_file_path: "os.PathLike | str" = "",
    streams: Sequence[str] = (),
    *,
    execution_type: str = "local",
    mode: str = "streaming",
    env_vars: dict[str, str] | None = None,
    service_user_credentials_file: str | None = None,
    gcp_region: str = "europe-west1",
    gcp_job_name: str | None = None,
    enforce_method: str | None = None,
    refresh_interval_ms: int = 60000,
    persistent_id: str | None = None,
    _source=None,
) -> Table:
    """Stream Airbyte RECORD messages of the selected ``streams`` into a
    ``data: Json`` table (reference ``io/airbyte/__init__.py:107``)."""
    if _source is None:
        if execution_type != "local":
            raise NotImplementedError(
                "remote (GCP) Airbyte execution requires cloud access; use "
                "execution_type='local' or pass _source=..."
            )
        _source = _make_serverless_source(
            config_file_path, streams, env_vars, enforce_method
        )
    schema = schema_mod.schema_from_types(data=dt.JSON)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"airbyte({','.join(streams)})")
    conn = _AirbyteConnector(node, _source, streams, mode, refresh_interval_ms)
    G.register_connector(conn)
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(str(persistent_id), conn)
    return Table(node, schema, Universe())
