"""Redpanda connector — Kafka-protocol alias (reference
``python/pathway/io/redpanda/__init__.py``: same reader/writer as
``pw.io.kafka`` pointed at a Redpanda cluster)."""

from __future__ import annotations

from pathway_tpu.io.kafka import InMemoryKafkaBroker, read, write

__all__ = ["read", "write", "InMemoryKafkaBroker"]
