"""Slack alert sink (reference ``python/pathway/io/slack/__init__.py:11``:
``send_alerts`` posts each value of a column to a channel via
``chat.postMessage``)."""

from __future__ import annotations

import json

from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.parse_graph import G

_SLACK_URL = "https://slack.com/api/chat.postMessage"


def _default_sender(slack_token: str):
    import urllib.request

    def send(payload: dict) -> None:
        req = urllib.request.Request(
            _SLACK_URL,
            data=json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization": f"Bearer {slack_token}",
            },
            method="POST",
        )
        urllib.request.urlopen(req, timeout=30)

    return send


def send_alerts(
    alerts: ColumnReference,
    slack_channel_id: str,
    slack_token: str,
    *,
    _sender=None,
) -> None:
    """Post every added value of the ``alerts`` column as a Slack message.
    ``_sender(payload_dict)`` is injectable for offline tests."""
    table = alerts._table.select(_alert=alerts)
    sender = _sender or _default_sender(slack_token)

    def write_batch(time, batch):
        for _key, row, diff in batch.rows():
            if diff <= 0:
                continue
            sender({"channel": slack_channel_id, "text": str(row[0])})

    node = SinkNode(
        G.engine_graph, table._node, write_batch,
        name=f"slack({slack_channel_id})",
    )
    G.register_sink(node)
