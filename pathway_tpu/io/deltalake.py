"""Delta Lake connector (reference ``python/pathway/io/deltalake``; engine
``DeltaTableReader``/``DeltaTableWriter`` data_storage.rs:1924,1621). Gated
on the ``deltalake`` package.

The reader FOLLOWS the table's version log as a stream (reference
``DeltaTableReader`` polls table versions and emits row-level actions,
data_storage.rs:1924-2100): each poll compares ``DeltaTable.version()``
with the last ingested version and emits +1/-1 deltas for the rows that
changed. Two mechanisms, best first:

* Change Data Feed — ``table.load_cdf(starting_version=...)`` rows carry
  ``_change_type`` (insert / delete / update_preimage / update_postimage),
  mapping directly to deltas;
* snapshot diff — reload the table at the new version and diff the full
  row multiset against the tracked live rows (works on tables without CDF
  enabled; costs a full scan per version hop).

The version number is the connector offset, so persistence restarts
resume from the next version instead of re-reading the table.
"""

from __future__ import annotations

from collections import Counter
from typing import Any

import time as time_mod

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector
from pathway_tpu.io._utils import format_value_for_output, parse_record_fields


def _require_deltalake():
    try:
        import deltalake  # noqa: F401

        return deltalake
    except ImportError as exc:  # pragma: no cover - gated dependency
        raise ImportError("pw.io.deltalake requires the `deltalake` package") from exc


_CDC_COLS = ("_change_type", "_commit_version", "_commit_timestamp")


class _DeltaLakeConnector(BaseConnector):
    """Version-following reader: snapshot at start, then per-version
    incremental deltas (CDF when the table provides it, multiset snapshot
    diff otherwise)."""

    def __init__(self, node, table, uri: str, schema, mode: str,
                 refresh_interval: float = 1.0):
        super().__init__(node)
        self.table = table
        self.uri = uri
        self.schema = schema
        self.mode = mode
        self.refresh_interval = refresh_interval
        self._version = -1  # last fully ingested version
        # row tuple -> live multiplicity (content-addressed rows: delta
        # tables have no engine-visible row ids; key = hash(values, i))
        self._live: Counter = Counter()
        self._emitted_pk: dict[int, tuple] = {}
        if mode != "static":
            self.heartbeat_ms = 500

    # -- persistence: resume from the version after the snapshotted one ----
    def current_offset(self):
        return self._version

    def seek_offset(self, offset) -> None:
        if isinstance(offset, int):
            self._version = offset

    def on_replay(self, rows) -> None:
        pk = bool(self.schema.primary_key_columns())
        for key, row, diff in rows:
            if pk:
                if diff > 0:
                    self._emitted_pk[key] = row
                else:
                    self._emitted_pk.pop(key, None)
            else:
                self._live[row] += diff
                if self._live[row] <= 0:
                    del self._live[row]

    # -- row plumbing ------------------------------------------------------
    def _parse_df(self, df) -> list[tuple]:
        cols = list(self.node.column_names)
        dtypes = {n: c.dtype for n, c in self.schema.__columns__.items()}
        out = []
        for rec in df.to_dict("records"):
            values = parse_record_fields(rec, cols, dtypes, self.schema)
            out.append(tuple(values[c] for c in cols))
        return out

    def _key_of(self, row: tuple, occurrence: int, pk_idx) -> int:
        if pk_idx is not None:
            return hash_values(*[row[j] for j in pk_idx])
        return hash_values(*row, occurrence)

    def _pk_idx(self):
        pk = self.schema.primary_key_columns()
        if not pk:
            return None
        cols = list(self.node.column_names)
        return [cols.index(c) for c in pk]

    def _deltas_for_multiset(self, new_counts: Counter) -> list:
        """Move the live multiset to ``new_counts``; occurrence-indexed keys
        make repeated identical rows retract deterministically."""
        deltas = []
        for row in set(self._live) | set(new_counts):
            old_n, new_n = self._live[row], new_counts[row]
            if new_n > old_n:
                for i in range(old_n, new_n):
                    deltas.append((hash_values(*row, i), row, 1))
            elif new_n < old_n:
                for i in range(new_n, old_n):
                    deltas.append((hash_values(*row, i), row, -1))
        self._live = new_counts
        return deltas

    def _deltas_for_upsert(self, rows: list[tuple], pk_idx,
                           deletes: list[tuple] | None = None) -> list:
        deltas = []
        for row in deletes or ():
            key = self._key_of(row, 0, pk_idx)
            old = self._emitted_pk.pop(key, None)
            if old is not None:
                deltas.append((key, old, -1))
        for row in rows:
            key = self._key_of(row, 0, pk_idx)
            old = self._emitted_pk.get(key)
            if old == row:
                continue
            if old is not None:
                deltas.append((key, old, -1))
            deltas.append((key, row, 1))
            self._emitted_pk[key] = row
        return deltas

    # -- version ingestion -------------------------------------------------
    def _snapshot_deltas(self, version: int) -> list:
        """Full-table load at ``version`` diffed against tracked state."""
        if hasattr(self.table, "load_as_version"):
            try:
                self.table.load_as_version(version)
            except Exception:  # noqa: BLE001 - reader may already be there
                pass
        rows = self._parse_df(self.table.to_pandas())
        pk_idx = self._pk_idx()
        if pk_idx is not None:
            # snapshot = the complete live set: retract pks gone from it
            new_keys = {self._key_of(r, 0, pk_idx) for r in rows}
            gone = [k for k in self._emitted_pk if k not in new_keys]
            deltas = []
            for k in gone:
                deltas.append((k, self._emitted_pk.pop(k), -1))
            return deltas + self._deltas_for_upsert(rows, pk_idx)
        return self._deltas_for_multiset(Counter(rows))

    def _cdf_deltas(self, start_version: int, end_version: int) -> list | None:
        """Change-data-feed rows for (start, end]; None when CDF is not
        available on this table."""
        load_cdf = getattr(self.table, "load_cdf", None)
        if load_cdf is None:
            return None
        try:
            cdf = load_cdf(starting_version=start_version,
                           ending_version=end_version)
        except Exception:  # noqa: BLE001 - CDF not enabled on the table
            return None
        df = cdf.read_all().to_pandas() if hasattr(cdf, "read_all") else (
            cdf.to_pandas() if hasattr(cdf, "to_pandas") else cdf
        )
        if "_change_type" not in df.columns:
            return None
        adds = df[df["_change_type"].isin(["insert", "update_postimage"])]
        dels = df[df["_change_type"].isin(["delete", "update_preimage"])]
        drop = [c for c in _CDC_COLS if c in df.columns]
        add_rows = self._parse_df(adds.drop(columns=drop))
        del_rows = self._parse_df(dels.drop(columns=drop))
        pk_idx = self._pk_idx()
        if pk_idx is not None:
            return self._deltas_for_upsert(add_rows, pk_idx, deletes=del_rows)
        new_counts = Counter(self._live)
        new_counts.update(add_rows)
        new_counts.subtract(del_rows)
        new_counts = Counter({r: n for r, n in new_counts.items() if n > 0})
        return self._deltas_for_multiset(new_counts)

    def _poll(self) -> list:
        current = self.table.version()
        if current is None or current <= self._version:
            return []
        if self._version < 0:
            deltas = self._snapshot_deltas(current)
        else:
            deltas = self._cdf_deltas(self._version, current)
            if deltas is None:
                deltas = self._snapshot_deltas(current)
        self._version = current
        return deltas

    def run(self):
        deltas = self._poll()
        if deltas or self._persistence is None:
            self.commit_rows(deltas)
        if self.mode == "static":
            return
        while not self.should_stop():
            time_mod.sleep(self.refresh_interval)
            self._refresh_log()
            deltas = self._poll()
            if deltas:
                self.commit_rows(deltas)

    def _refresh_log(self) -> None:
        """See new table versions: ``update_incremental()`` when the reader
        object provides it, else RE-OPEN the table at the uri — newer
        deltalake releases drop update_incremental, and without a refresh
        ``version()`` would return the construction-time snapshot forever
        (a silently frozen source)."""
        update = getattr(self.table, "update_incremental", None)
        if update is not None:
            try:
                update()
                return
            except Exception:  # noqa: BLE001 - fall through to re-open
                pass
        try:
            import deltalake as dl

            fresh = dl.DeltaTable(self.uri)
        except Exception:  # noqa: BLE001 - injected tables / no package
            return
        if fresh.version() > self._version:
            self.table = fresh


def read(uri: str, schema: Any, *, mode: str = "streaming",
         autocommit_duration_ms: int | None = 1500,
         refresh_interval: float = 1.0,
         persistent_id: str | None = None,
         _table: Any = None, **kwargs) -> Table:
    """Read a Delta table. ``mode="streaming"`` follows the version log
    live (CDF when enabled, snapshot diff otherwise); ``mode="static"``
    ingests the current snapshot and finishes. ``_table`` injects a ready
    DeltaTable-shaped object for offline tests."""
    table = _table
    if table is None:
        dl = _require_deltalake()
        table = dl.DeltaTable(uri)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"deltalake({uri})")
    conn = _DeltaLakeConnector(
        node, table, uri, schema, mode, refresh_interval=refresh_interval
    )
    G.register_connector(conn)
    out = Table(node, schema, Universe())
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(persistent_id, conn)
    return out


def write(table, uri: str, *, partition_columns=None,
          min_commit_frequency: int | None = 60_000, **kwargs) -> None:
    dl = _require_deltalake()
    cols = list(table.column_names())

    def write_batch(time, batch):
        import pandas as pd

        rows = []
        for _key, row, diff in batch.rows():
            doc = {c: format_value_for_output(v) for c, v in zip(cols, row)}
            doc["time"] = time
            doc["diff"] = diff
            rows.append(doc)
        if rows:
            dl.write_deltalake(uri, pd.DataFrame(rows), mode="append",
                               partition_by=partition_columns)

    node = SinkNode(G.engine_graph, table._node, write_batch, name=f"deltalake({uri})")
    G.register_sink(node)
