"""Delta Lake connector (reference ``python/pathway/io/deltalake``; engine
``DeltaTableReader``/``DeltaTableWriter`` data_storage.rs:1924,1621). Gated
on the ``deltalake`` package."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import format_value_for_output


def _require_deltalake():
    try:
        import deltalake  # noqa: F401

        return deltalake
    except ImportError as exc:  # pragma: no cover - gated dependency
        raise ImportError("pw.io.deltalake requires the `deltalake` package") from exc


def read(uri: str, schema: Any, *, mode: str = "streaming",
         autocommit_duration_ms: int | None = 1500, **kwargs):
    dl = _require_deltalake()
    import pandas as pd  # noqa: F401

    import pathway_tpu as pw

    table = dl.DeltaTable(uri)
    df = table.to_pandas()
    cols = list(schema.column_names())
    return pw.debug.table_from_pandas(df[cols], schema=schema)


def write(table, uri: str, *, partition_columns=None,
          min_commit_frequency: int | None = 60_000, **kwargs) -> None:
    dl = _require_deltalake()
    cols = list(table.column_names())

    def write_batch(time, batch):
        import pandas as pd

        rows = []
        for _key, row, diff in batch.rows():
            doc = {c: format_value_for_output(v) for c, v in zip(cols, row)}
            doc["time"] = time
            doc["diff"] = diff
            rows.append(doc)
        if rows:
            dl.write_deltalake(uri, pd.DataFrame(rows), mode="append",
                               partition_by=partition_columns)

    node = SinkNode(G.engine_graph, table._node, write_batch, name=f"deltalake({uri})")
    G.register_sink(node)
