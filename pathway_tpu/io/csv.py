"""CSV connector (reference ``python/pathway/io/csv``) — thin wrapper over fs."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs
from pathway_tpu.io._utils import CsvParserSettings


def read(
    path,
    *,
    schema: Any | None = None,
    csv_settings: CsvParserSettings | None = None,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    persistent_id: str | None = None,
    with_metadata: bool = False,
    **kwargs,
):
    return fs.read(
        path,
        format="csv",
        schema=schema,
        csv_settings=csv_settings,
        mode=mode,
        autocommit_duration_ms=autocommit_duration_ms,
        persistent_id=persistent_id,
        with_metadata=with_metadata,
        **kwargs,
    )


def write(table, filename, **kwargs) -> None:
    fs.write(table, filename, format="csv", **kwargs)
