"""Null sink (reference ``python/pathway/io/null``)."""

from __future__ import annotations

from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.internals.parse_graph import G


def write(table, **kwargs) -> None:
    node = SinkNode(G.engine_graph, table._node, lambda t, b: None, name="null-sink")
    G.register_sink(node)
