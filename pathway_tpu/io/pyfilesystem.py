"""PyFilesystem reader (reference ``python/pathway/io/pyfilesystem/__init__.py:142``):
ingest any `fs.FS <https://docs.pyfilesystem.org>`_ source (zip, ftp, mem,
osfs, ...) as a binary ``data`` column with optional ``_metadata``, polling
for new/changed/deleted files in streaming mode."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._object_store import ObjectStoreConnector


class _PyFsProvider:
    """Adapter over an ``fs.FS``-like object (``walk.files``/``listdir``,
    ``getinfo``, ``readbytes``)."""

    def __init__(self, source, path: str):
        self.source = source
        self.path = path or "/"

    def _files(self) -> list[str]:
        walk = getattr(self.source, "walk", None)
        if walk is not None:
            return list(walk.files(self.path))
        out: list[str] = []

        def rec(p: str) -> None:
            for entry in self.source.listdir(p):
                full = p.rstrip("/") + "/" + entry
                if self.source.isdir(full):
                    rec(full)
                else:
                    out.append(full)

        rec(self.path)
        return out

    def list_objects(self) -> dict[str, tuple[Any, dict]]:
        listing: dict[str, tuple[Any, dict]] = {}
        for path in self._files():
            try:
                info = self.source.getinfo(path, namespaces=["details"])
            except Exception:
                continue
            modified = getattr(info, "modified", None)
            size = getattr(info, "size", None)
            version = (str(modified), size)
            listing[path] = (
                version,
                {
                    "path": path,
                    "name": getattr(info, "name", path.rsplit("/", 1)[-1]),
                    "modified_at": str(modified) if modified else None,
                    "size": size,
                },
            )
        return listing

    def fetch(self, object_id: str) -> bytes:
        reader = getattr(self.source, "readbytes", None) or getattr(
            self.source, "getbytes"
        )
        return reader(object_id)


def read(
    source,
    *,
    path: str = "",
    refresh_interval: float = 30,
    mode: str = "streaming",
    with_metadata: bool = False,
    persistent_id: str | None = None,
    _provider=None,
) -> Table:
    """Read every file under ``path`` of the PyFilesystem ``source`` into a
    single binary ``data`` column (plus ``_metadata`` when requested).
    With ``persistent_id``, downloaded objects are cached by URI in the
    persistence backend so restarts replay deterministically. ``_provider``
    (duck-typed ``list_objects``/``fetch``) is injectable for offline
    tests."""
    schema = schema_mod.schema_from_types(data=bytes)
    if with_metadata:
        schema = schema | schema_mod.schema_from_types(_metadata=dt.JSON)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"pyfilesystem({path or '/'})")
    conn = ObjectStoreConnector(
        node, _provider or _PyFsProvider(source, path), mode, with_metadata,
        refresh_interval,
    )
    G.register_connector(conn)
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(persistent_id, conn)
    return Table(node, schema, Universe())
