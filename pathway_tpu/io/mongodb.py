"""MongoDB sink (reference ``python/pathway/io/mongodb``; engine
``MongoWriter`` data_storage.rs:2232, ``BsonFormatter``). Gated on
``pymongo``."""

from __future__ import annotations

from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import format_value_for_output


def write(table, connection_string: str, database: str, collection: str,
          *, max_batch_size: int | None = None, **kwargs) -> None:
    try:
        import pymongo
    except ImportError as exc:  # pragma: no cover - gated dependency
        raise ImportError("pw.io.mongodb requires the `pymongo` package") from exc
    client = pymongo.MongoClient(connection_string)
    coll = client[database][collection]
    cols = list(table.column_names())

    def write_batch(time, batch):
        docs = []
        for _key, row, diff in batch.rows():
            doc = {c: format_value_for_output(v) for c, v in zip(cols, row)}
            doc["time"] = time
            doc["diff"] = diff
            docs.append(doc)
        if docs:
            coll.insert_many(docs)

    node = SinkNode(G.engine_graph, table._node, write_batch, name=f"mongodb({collection})")
    G.register_sink(node)
