"""MongoDB sink (reference ``python/pathway/io/mongodb``; engine
``MongoWriter`` data_storage.rs:2232, ``BsonFormatter``). Gated on
``pymongo``."""

from __future__ import annotations

from pathway_tpu.engine.operators.output import SinkNode
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._utils import format_value_for_output


def write(table, connection_string: str, database: str, collection: str,
          *, max_batch_size: int | None = None, _client=None, **kwargs) -> None:
    """``_client`` (pymongo-shaped ``client[db][coll].insert_many``) is
    injectable for offline tests, like the gdrive/sharepoint connectors."""
    if _client is None:
        try:
            import pymongo
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("pw.io.mongodb requires the `pymongo` package") from exc
        _client = pymongo.MongoClient(connection_string)
    coll = _client[database][collection]
    cols = list(table.column_names())

    def write_batch(time, batch):
        docs = []
        for _key, row, diff in batch.rows():
            doc = {c: format_value_for_output(v) for c, v in zip(cols, row)}
            doc["time"] = time
            doc["diff"] = diff
            docs.append(doc)
        # chunk inserts: one giant insert_many can exceed Mongo's message
        # size limits and fail the whole batch
        chunk = max_batch_size or len(docs) or 1
        for start in range(0, len(docs), chunk):
            coll.insert_many(docs[start : start + chunk])

    node = SinkNode(G.engine_graph, table._node, write_batch, name=f"mongodb({collection})")
    G.register_sink(node)
