"""S3 CSV reader (reference ``python/pathway/io/s3_csv/__init__.py``: the
legacy ``pw.io.s3_csv.read`` alias of ``pw.io.s3.read(format="csv")``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io.s3 import AwsS3Settings, read as _s3_read


def read(
    path: str,
    *,
    aws_s3_settings: AwsS3Settings | None = None,
    schema: Any | None = None,
    mode: str = "streaming",
    csv_settings=None,
    **kwargs,
):
    return _s3_read(
        path,
        aws_s3_settings=aws_s3_settings,
        format="csv",
        schema=schema,
        mode=mode,
        csv_settings=csv_settings,
        **kwargs,
    )
