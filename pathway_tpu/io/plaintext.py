"""Plaintext connector (reference ``python/pathway/io/plaintext``)."""

from __future__ import annotations

from pathway_tpu.io import fs


def read(path, *, mode: str = "streaming", object_pattern: str = "*", with_metadata: bool = False, persistent_id: str | None = None, **kwargs):
    return fs.read(
        path,
        format="plaintext",
        mode=mode,
        with_metadata=with_metadata,
        persistent_id=persistent_id,
        **kwargs,
    )
