"""SQLite connector (reference ``src/connectors/data_storage.rs``
``SqliteReader``): snapshot read of a table, optional polling for changes."""

from __future__ import annotations

import sqlite3
import time as time_mod
from typing import Any

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector, next_commit_time


class _SqliteConnector(BaseConnector):
    def __init__(self, node, path, table_name, schema, mode):
        super().__init__(node)
        self.path = path
        self.table_name = table_name
        self.schema = schema
        self.mode = mode
        if mode != "static":
            self.heartbeat_ms = 500

    def _snapshot(self):
        cols = list(self.node.column_names)
        conn = sqlite3.connect(self.path)
        try:
            cur = conn.execute(
                f"SELECT {', '.join(cols)} FROM {self.table_name}"  # noqa: S608
            )
            rows = {}
            pk = self.schema.primary_key_columns()
            for i, rec in enumerate(cur.fetchall()):
                values = dict(zip(cols, rec))
                key = (
                    hash_values(*[values[c] for c in pk])
                    if pk
                    else hash_values(i, *rec)
                )
                rows[key] = tuple(rec)
            return rows
        finally:
            conn.close()

    def run(self):
        prev: dict[int, tuple] = {}
        while True:
            cur = self._snapshot()
            rows = []
            for k, row in prev.items():
                if cur.get(k) != row:
                    rows.append((k, row, -1))
            for k, row in cur.items():
                if prev.get(k) != row:
                    rows.append((k, row, 1))
            prev = cur
            if rows:
                self.commit_rows(rows)
            if self.mode == "static" or self.should_stop():
                return
            time_mod.sleep(0.5)


def read(
    path: str,
    table_name: str,
    schema: Any,
    *,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = 1500,
    **kwargs,
) -> Table:
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"sqlite({table_name})")
    conn = _SqliteConnector(node, path, table_name, schema, mode)
    G.register_connector(conn)
    return Table(node, schema, Universe())
