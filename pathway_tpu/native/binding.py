"""Safe lazy resolution of native-backed helpers.

Every Python hot path that prefers a C++ implementation binds it through
:func:`native_bind` — one place for the import guard and the AVAILABLE
check, instead of a copy of the try/import/except memoizer per call site.
Returns the wrapper defined in :mod:`pathway_tpu.native` when one exists
(e.g. ``hash_tokenize_native``), else the raw extension symbol, else None
(callers then take their pure-Python path).
"""

from __future__ import annotations

import functools


@functools.lru_cache(maxsize=None)
def native_bind(name: str):
    try:
        from pathway_tpu import native as native_mod
    except Exception:  # noqa: BLE001 - a broken extension degrades, never breaks
        return None
    if not native_mod.AVAILABLE:
        return None
    fn = getattr(native_mod, name, None)
    if fn is not None:
        return fn
    return getattr(native_mod.lib, name, None)
