"""pathway_tpu.native — the C++ host runtime.

Builds ``_native.cpp`` into a CPython extension on first import (g++ -O3;
cached next to the source, rebuilt when the source changes) and exposes the
hot host-side loops the reference implements in Rust:

* ``hash_object_column`` — canonical-serialize + XXH64 a whole value column
  (reference ``Key::for_values``, src/engine/value.rs:57)
* ``consolidate_pairs`` — (key, row-hash) delta grouping with diff summing
  (differential-dataflow consolidation)
* ``split_lines`` — newline tokenizer for line-based connectors
  (reference src/connectors/data_tokenize.rs)

Everything degrades gracefully: if the toolchain is missing the Python/numpy
paths are used and ``AVAILABLE`` is False.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_native.cpp")

AVAILABLE = False
lib = None


def _build_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, f"_native-{digest}{suffix}")


def _compile(out_path: str) -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++20",
        f"-I{include}", _SRC, "-o", out_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except Exception as exc:  # noqa: BLE001
        logger.info("native build unavailable: %s", exc)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def _load():
    global AVAILABLE, lib
    from pathway_tpu.internals.config import pathway_config

    if pathway_config.disable_native:
        return
    # a pip-built extension (setup.py) is preferred when it is at least as
    # new as the source; a stale binary (source edited after `pip install
    # -e .`) falls through to the JIT path, which content-hashes the source
    # and rebuilds
    try:
        import importlib
        import importlib.util

        spec = importlib.util.find_spec("pathway_tpu.native._native")
        if (
            spec is not None
            and spec.origin
            and os.path.getmtime(spec.origin) >= os.path.getmtime(_SRC)
        ):
            lib = importlib.import_module("pathway_tpu.native._native")
            AVAILABLE = True
            return
    except (ImportError, OSError):
        pass
    path = _build_path()
    if not os.path.exists(path):
        tmp = path + f".tmp{os.getpid()}"
        if not _compile(tmp):
            return
        os.replace(tmp, path)
    try:
        spec = importlib.util.spec_from_file_location("_native", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)  # type: ignore[union-attr]
    except Exception as exc:  # noqa: BLE001
        logger.warning("native load failed: %s", exc)
        return
    lib = mod
    AVAILABLE = True


_load()

if AVAILABLE:
    import numpy as np

    def hash_object_column_native(col) -> "np.ndarray | None":
        """Column hash via the C++ path; rows the native serializer can't
        handle (ndarray/Json/datetimes/bigints) fall back per-row in Python.
        Returns None when native is unavailable."""
        n = len(col)
        out = np.empty(n, dtype=np.uint64)
        fallback = lib.hash_object_column(col, memoryview(out.view(np.uint8)))
        if fallback:
            from pathway_tpu.engine import value as value_mod

            for i in fallback:
                out[i] = value_mod.hash_one(col[i])
        return out

    def consolidate_pairs_native(keys, rowh, diffs):
        """Returns (first_indices u64 array, summed_diffs i64 array)."""
        idx_b, diff_b = lib.consolidate_pairs(
            memoryview(np.ascontiguousarray(keys, dtype=np.uint64)),
            memoryview(np.ascontiguousarray(rowh, dtype=np.uint64)),
            memoryview(np.ascontiguousarray(diffs, dtype=np.int64)),
        )
        return (
            np.frombuffer(idx_b, dtype=np.uint64),
            np.frombuffer(diff_b, dtype=np.int64),
        )

    def split_lines_native(data: bytes):
        """(start, end) offsets per line as an (n, 2) uint64 array."""
        offs = np.frombuffer(lib.split_lines(data), dtype=np.uint64)
        return offs.reshape(-1, 2)

    def hash_tokenize_native(texts, max_length: int, reserved: int,
                             span: int):
        """Batch HashTokenizer ids as (writable (n, width) int32 matrix,
        fallback row indices needing Python re-tokenization — texts with
        non-ASCII bytes, where Unicode case folding applies), or None for
        inputs the C++ path rejects outright (non-strings)."""
        try:
            buf, width, fallback = lib.hash_tokenize(
                texts, max_length, reserved, span
            )
        except TypeError:
            return None
        ids = np.frombuffer(buf, dtype=np.int32).reshape(len(texts), width)
        return ids, fallback

    def wordpiece_load_native(tokens) -> int:
        """Register a WordPiece vocab (list of token strings, index = id);
        returns an opaque handle for wordpiece_tokenize_native."""
        return lib.wordpiece_load(list(tokens))

    def wordpiece_tokenize_native(handle: int, texts, max_length: int,
                                  cls_id: int, sep_id: int, unk_id: int,
                                  pad_id: int):
        """Batch WordPiece ids as (writable (n, width) int32 matrix,
        per-row real lengths, fallback row indices — non-ASCII texts
        needing the Python path), or None for inputs the C++ path rejects
        (non-strings)."""
        try:
            buf, width, lens_buf, fallback = lib.wordpiece_tokenize(
                handle, texts, max_length, cls_id, sep_id, unk_id, pad_id
            )
        except TypeError:
            return None
        ids = np.frombuffer(buf, dtype=np.int32).reshape(len(texts), width)
        lens = np.frombuffer(lens_buf, dtype=np.uint32)
        return ids, lens, fallback

else:
    hash_object_column_native = None  # type: ignore[assignment]
    consolidate_pairs_native = None  # type: ignore[assignment]
    split_lines_native = None  # type: ignore[assignment]
    hash_tokenize_native = None  # type: ignore[assignment]
    wordpiece_load_native = None  # type: ignore[assignment]
    wordpiece_tokenize_native = None  # type: ignore[assignment]
