// pathway_tpu native runtime — CPython extension.
//
// The role the Rust engine core plays in the reference (value hashing/keys:
// src/engine/value.rs:28-57; delta consolidation: differential-dataflow
// consolidation) is played here by a small C++ extension on the host hot
// paths: canonical value serialization + XXH64 keying over whole columns,
// and (key,row-hash) delta consolidation for batches. Dense math stays in
// XLA; this covers the irregular host-side inner loops.
//
// XXH64 implemented from the public algorithm specification
// (github.com/Cyan4973/xxHash — public domain); must produce identical
// digests to python-xxhash's xxh64 so native and Python key paths agree.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>
#include <algorithm>

// ---------------------------------------------------------------- XXH64
static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64/arm64)
}
static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
static inline uint64_t xxh_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  return acc * P1;
}
static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  val = xxh_round(0, val);
  acc ^= val;
  return acc * P1 + P4;
}

static uint64_t xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed + 0;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh_round(v1, read64(p)); p += 8;
      v2 = xxh_round(v2, read64(p)); p += 8;
      v3 = xxh_round(v3, read64(p)); p += 8;
      v4 = xxh_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// --------------------------------------------- canonical value serialization
// Byte-for-byte identical to engine/value.py serialize_value for the types
// handled natively; exotic types (ndarray, Json, datetimes, PyObjectWrapper)
// signal a fallback to the Python encoder.

static PyObject* g_pointer_type = nullptr;  // set by set_pointer_type()

struct SerializeError {};

static bool serialize(PyObject* v, std::string& out);

static inline void put_u32(std::string& out, uint32_t x) {
  out.append(reinterpret_cast<const char*>(&x), 4);
}
static inline void put_u64(std::string& out, uint64_t x) {
  out.append(reinterpret_cast<const char*>(&x), 8);
}

static bool serialize(PyObject* v, std::string& out) {
  if (v == Py_None) {
    out.push_back('\x00');
  } else if (PyBool_Check(v)) {
    out.push_back('\x01');
    out.push_back(v == Py_True ? '\x01' : '\x00');
  } else if (PyLong_Check(v)) {
    int overflow = 0;
    long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (!overflow) {
      out.push_back('\x02');
      put_u64(out, (uint64_t)ll);
    } else if (overflow > 0) {
      // positive ints in [2^63, 2^64) — every uint64 row key lands here.
      // Python encodes them as TAG_BIGINT + u32 len + to_bytes(little,
      // signed): bit_length 64 -> 9 bytes, low 8 LE + 0x00 sign byte.
      unsigned long long ull = PyLong_AsUnsignedLongLong(v);
      if (PyErr_Occurred()) {
        PyErr_Clear();
        return false;  // > 2^64: rare — python fallback
      }
      out.push_back('\x0f');
      put_u32(out, 9);
      put_u64(out, (uint64_t)ull);
      out.push_back('\x00');
    } else {
      return false;  // < -2^63: rare — python fallback
    }
  } else if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    out.push_back('\x03');
    out.append(reinterpret_cast<const char*>(&d), 8);
  } else if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char* s = PyUnicode_AsUTF8AndSize(v, &n);
    if (s == nullptr) throw SerializeError{};
    out.push_back('\x04');
    put_u32(out, (uint32_t)n);
    out.append(s, (size_t)n);
  } else if (PyBytes_Check(v)) {
    out.push_back('\x05');
    put_u32(out, (uint32_t)PyBytes_GET_SIZE(v));
    out.append(PyBytes_AS_STRING(v), (size_t)PyBytes_GET_SIZE(v));
  } else if (g_pointer_type != nullptr &&
             PyObject_TypeCheck(v, (PyTypeObject*)g_pointer_type)) {
    PyObject* val = PyObject_GetAttrString(v, "value");
    if (val == nullptr) throw SerializeError{};
    uint64_t k = PyLong_AsUnsignedLongLongMask(val);
    Py_DECREF(val);
    if (PyErr_Occurred()) throw SerializeError{};
    out.push_back('\x06');
    put_u64(out, k);
  } else if (PyTuple_Check(v) || PyList_Check(v)) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
    out.push_back('\x07');
    put_u32(out, (uint32_t)n);
    PyObject** items = PySequence_Fast_ITEMS(v);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!serialize(items[i], out)) return false;
    }
  } else {
    return false;  // exotic type -> python fallback
  }
  return true;
}

// hash_object_column(seq, out_buffer) -> list_of_fallback_indices
// out_buffer: writable buffer of n*8 bytes receiving LE uint64 digests.
static PyObject* py_hash_object_column(PyObject*, PyObject* args) {
  PyObject* seq;
  Py_buffer out_buf;
  if (!PyArg_ParseTuple(args, "Ow*", &seq, &out_buf)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "expected a sequence");
  if (fast == nullptr) {
    PyBuffer_Release(&out_buf);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  if ((Py_ssize_t)out_buf.len < n * 8) {
    PyBuffer_Release(&out_buf);
    Py_DECREF(fast);
    PyErr_SetString(PyExc_ValueError, "output buffer too small");
    return nullptr;
  }
  uint64_t* out = reinterpret_cast<uint64_t*>(out_buf.buf);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  PyObject* fallback = PyList_New(0);
  std::string buf;
  buf.reserve(64);
  try {
    for (Py_ssize_t i = 0; i < n; i++) {
      buf.clear();
      if (serialize(items[i], buf)) {
        out[i] = xxh64(reinterpret_cast<const uint8_t*>(buf.data()),
                       buf.size(), 0);
      } else {
        PyObject* idx = PyLong_FromSsize_t(i);
        PyList_Append(fallback, idx);
        Py_DECREF(idx);
      }
    }
  } catch (SerializeError&) {
    PyBuffer_Release(&out_buf);
    Py_DECREF(fast);
    Py_DECREF(fallback);
    return nullptr;
  }
  PyBuffer_Release(&out_buf);
  Py_DECREF(fast);
  return fallback;
}

// xxh64_digest(bytes_like, seed=0) -> int
static PyObject* py_xxh64(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned long long seed = 0;
  if (!PyArg_ParseTuple(args, "y*|K", &buf, &seed)) return nullptr;
  uint64_t h = xxh64(reinterpret_cast<const uint8_t*>(buf.buf),
                     (size_t)buf.len, (uint64_t)seed);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLongLong(h);
}

// consolidate_pairs(keys_u64, rowh_u64, diffs_i64) -> (idx_bytes, diff_bytes)
// Groups rows by (key, row_hash), sums diffs, drops zero groups; returns the
// first-occurrence index (uint64 LE) and summed diff (int64 LE) per kept
// group, ordered by first occurrence.
static PyObject* py_consolidate_pairs(PyObject*, PyObject* args) {
  Py_buffer kb, rb, db;
  if (!PyArg_ParseTuple(args, "y*y*y*", &kb, &rb, &db)) return nullptr;
  size_t n = kb.len / 8;
  if (rb.len / 8 != (Py_ssize_t)n || db.len / 8 != (Py_ssize_t)n) {
    PyBuffer_Release(&kb); PyBuffer_Release(&rb); PyBuffer_Release(&db);
    PyErr_SetString(PyExc_ValueError, "length mismatch");
    return nullptr;
  }
  const uint64_t* keys = reinterpret_cast<const uint64_t*>(kb.buf);
  const uint64_t* rowh = reinterpret_cast<const uint64_t*>(rb.buf);
  const int64_t* diffs = reinterpret_cast<const int64_t*>(db.buf);

  std::vector<uint32_t> order(n);
  for (size_t i = 0; i < n; i++) order[i] = (uint32_t)i;
  std::sort(order.begin(), order.end(), [&](uint32_t a, uint32_t b) {
    if (keys[a] != keys[b]) return keys[a] < keys[b];
    if (rowh[a] != rowh[b]) return rowh[a] < rowh[b];
    return a < b;
  });

  std::vector<uint64_t> first;
  std::vector<int64_t> summed;
  first.reserve(n);
  summed.reserve(n);
  size_t i = 0;
  while (i < n) {
    size_t j = i;
    int64_t s = 0;
    uint32_t f = order[i];
    while (j < n && keys[order[j]] == keys[order[i]] &&
           rowh[order[j]] == rowh[order[i]]) {
      s += diffs[order[j]];
      if (order[j] < f) f = order[j];
      j++;
    }
    if (s != 0) {
      first.push_back(f);
      summed.push_back(s);
    }
    i = j;
  }
  // order kept groups by first occurrence for deterministic batch layout
  std::vector<uint32_t> gorder(first.size());
  for (size_t g = 0; g < gorder.size(); g++) gorder[g] = (uint32_t)g;
  std::sort(gorder.begin(), gorder.end(), [&](uint32_t a, uint32_t b) {
    return first[a] < first[b];
  });
  PyObject* idx_bytes = PyBytes_FromStringAndSize(nullptr, first.size() * 8);
  PyObject* diff_bytes = PyBytes_FromStringAndSize(nullptr, summed.size() * 8);
  if (idx_bytes && diff_bytes) {
    uint64_t* ip = reinterpret_cast<uint64_t*>(PyBytes_AS_STRING(idx_bytes));
    int64_t* dp = reinterpret_cast<int64_t*>(PyBytes_AS_STRING(diff_bytes));
    for (size_t g = 0; g < gorder.size(); g++) {
      ip[g] = first[gorder[g]];
      dp[g] = summed[gorder[g]];
    }
  }
  PyBuffer_Release(&kb); PyBuffer_Release(&rb); PyBuffer_Release(&db);
  if (!idx_bytes || !diff_bytes) {
    Py_XDECREF(idx_bytes); Py_XDECREF(diff_bytes);
    return nullptr;
  }
  PyObject* ret = PyTuple_Pack(2, idx_bytes, diff_bytes);
  Py_DECREF(idx_bytes);
  Py_DECREF(diff_bytes);
  return ret;
}

// split_lines(bytes) -> bytes of uint64 LE (start,end) offset pairs per line,
// skipping a trailing empty line — the tokenizer core for jsonlines/plaintext
// readers (reference: src/connectors/data_tokenize.rs).
static PyObject* py_split_lines(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  const char* data = reinterpret_cast<const char*>(buf.buf);
  size_t n = (size_t)buf.len;
  std::vector<uint64_t> offs;
  size_t start = 0;
  for (size_t i = 0; i < n; i++) {
    if (data[i] == '\n') {
      offs.push_back(start);
      offs.push_back(i);
      start = i + 1;
    }
  }
  if (start < n) {
    offs.push_back(start);
    offs.push_back(n);
  }
  PyObject* out = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(offs.data()), offs.size() * 8);
  PyBuffer_Release(&buf);
  return out;
}

// hash_tokenize(texts, max_length, reserved, span)
//   -> (ids_bytearray, width, fallback_indices)
// The HashTokenizer hot loop (models/tokenizer.py): per text emit
// [CLS] word-ids [SEP] where word-id = reserved + fnv1a(word) % span, words
// are maximal [a-z0-9]+ runs of the ASCII-lowercased text, truncated so
// len(ids) <= max_length. Output is an n*width int32 LE row-major matrix,
// 0-padded (PAD id is 0 and every real id is > 0, so the attention mask is
// simply ids != 0). Texts containing non-ASCII bytes are listed in
// fallback_indices with a bare [CLS][SEP] row: Python's str.lower() does
// Unicode case folding (U+212A KELVIN SIGN -> 'k' etc.) that a byte scan
// cannot reproduce, so those rows re-tokenize on the Python path to keep
// native and fallback ids identical for every input.
static PyObject* py_hash_tokenize(PyObject*, PyObject* args) {
  PyObject* seq;
  long max_length, reserved;
  unsigned long long span;
  if (!PyArg_ParseTuple(args, "OllK", &seq, &max_length, &reserved, &span))
    return nullptr;
  if (span == 0) {
    PyErr_SetString(PyExc_ValueError, "span must be positive");
    return nullptr;
  }
  PyObject* fast = PySequence_Fast(seq, "expected a sequence of strings");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  std::vector<int32_t> flat;
  flat.reserve((size_t)n * 16);
  std::vector<uint32_t> lens((size_t)n);
  size_t width = 2;
  PyObject* fallback = PyList_New(0);
  if (fallback == nullptr) {
    Py_DECREF(fast);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t slen;
    const char* s = PyUnicode_AsUTF8AndSize(items[i], &slen);
    if (s == nullptr) {
      Py_DECREF(fast);
      Py_DECREF(fallback);
      return nullptr;  // non-string: caller falls back to the Python path
    }
    bool ascii = true;
    for (Py_ssize_t j = 0; j < slen; j++) {
      if ((unsigned char)s[j] >= 0x80) {
        ascii = false;
        break;
      }
    }
    size_t row_start = flat.size();
    flat.push_back(101);  // [CLS]
    long count = 1;
    if (!ascii) {
      PyObject* idx = PyLong_FromSsize_t(i);
      if (idx == nullptr || PyList_Append(fallback, idx) < 0) {
        Py_XDECREF(idx);
        Py_DECREF(fast);
        Py_DECREF(fallback);
        return nullptr;
      }
      Py_DECREF(idx);
    } else {
      size_t j = 0;
      while (j < (size_t)slen && count < max_length - 1) {
        unsigned char c = (unsigned char)s[j];
        unsigned char lc = (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
        bool is_word = (lc >= 'a' && lc <= 'z') || (lc >= '0' && lc <= '9');
        if (!is_word) {
          j++;
          continue;
        }
        uint64_t h = 0xCBF29CE484222325ULL;
        while (j < (size_t)slen) {
          c = (unsigned char)s[j];
          lc = (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
          if (!((lc >= 'a' && lc <= 'z') || (lc >= '0' && lc <= '9'))) break;
          h ^= (uint64_t)lc;
          h = (h * 0x100000001B3ULL) & 0xFFFFFFFFFFFFFFFFULL;
          j++;
        }
        flat.push_back((int32_t)(reserved + (long)(h % span)));
        count++;
      }
    }
    flat.push_back(102);  // [SEP]
    count++;
    lens[(size_t)i] = (uint32_t)(flat.size() - row_start);
    if ((size_t)count > width) width = (size_t)count;
  }
  Py_DECREF(fast);
  PyObject* out = PyByteArray_FromStringAndSize(nullptr, (Py_ssize_t)(n * width * 4));
  if (out == nullptr) {
    Py_DECREF(fallback);
    return nullptr;
  }
  int32_t* dst = reinterpret_cast<int32_t*>(PyByteArray_AS_STRING(out));
  std::memset(dst, 0, (size_t)n * width * 4);
  size_t pos = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    std::memcpy(dst + (size_t)i * width, flat.data() + pos,
                (size_t)lens[(size_t)i] * 4);
    pos += lens[(size_t)i];
  }
  return Py_BuildValue("(NnN)", out, (Py_ssize_t)width, fallback);
}

// ------------------------------------------------------------- WordPiece
// The reference's embedders tokenize through HuggingFace's Rust
// `tokenizers` (BERT BasicTokenizer + WordPiece greedy longest-match);
// this is the same algorithm as a native batch kernel. ASCII rows are
// handled here; rows with non-ASCII bytes are returned as fallback
// indices for the Python path (Unicode NFD accent stripping / case
// folding). Parity with transformers.BertTokenizer is pinned by test.

#include <deque>
#include <string_view>
#include <unordered_map>

// greedy longest-match probes are substrings of the word buffer, looked
// up as string_views with ZERO per-probe allocations (the old per-probe
// "##"+substr std::string construction dominated the single-core
// tokenizer profile). The maps are keyed on string_view directly —
// backed by owned strings with stable addresses — rather than relying
// on C++20 heterogeneous unordered lookup (P0919), which libstdc++ only
// ships from GCC 11.
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view s) const noexcept {
    return std::hash<std::string_view>{}(s);
  }
};

using WpMap =
    std::unordered_map<std::string_view, int32_t, SvHash, std::equal_to<>>;

struct WordPieceVocab {
  // deque: push_back never moves earlier elements, so the map's views
  // into these strings stay valid as the vocab grows
  std::deque<std::string> storage;
  // word_start also answers single-char punctuation lookups (a 1-char
  // token can never start with "##")
  WpMap word_start;   // tokens NOT starting with "##"
  WpMap word_suffix;  // tokens starting with "##", stored WITHOUT the "##"
};
static std::vector<WordPieceVocab*> g_wp_vocabs;

// wordpiece_load(tokens) -> handle
static PyObject* py_wordpiece_load(PyObject*, PyObject* args) {
  PyObject* seq;
  if (!PyArg_ParseTuple(args, "O", &seq)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "expected a sequence of strings");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  auto* vocab = new WordPieceVocab();
  vocab->word_start.reserve((size_t)n);
  vocab->word_suffix.reserve((size_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t slen;
    const char* s = PyUnicode_AsUTF8AndSize(items[i], &slen);
    if (s == nullptr) {
      delete vocab;
      Py_DECREF(fast);
      return nullptr;
    }
    // assignment (not emplace): duplicate tokens keep the LAST id, matching
    // dict comprehension / HF vocab-load semantics
    vocab->storage.emplace_back(s, (size_t)slen);
    std::string_view tok(vocab->storage.back());
    if (slen >= 2 && s[0] == '#' && s[1] == '#') {
      vocab->word_suffix[tok.substr(2)] = (int32_t)i;
    } else {
      vocab->word_start[tok] = (int32_t)i;
    }
  }
  Py_DECREF(fast);
  // reuse a freed slot before growing the registry
  for (size_t h = 0; h < g_wp_vocabs.size(); h++) {
    if (g_wp_vocabs[h] == nullptr) {
      g_wp_vocabs[h] = vocab;
      return PyLong_FromSsize_t((Py_ssize_t)h);
    }
  }
  g_wp_vocabs.push_back(vocab);
  return PyLong_FromSsize_t((Py_ssize_t)g_wp_vocabs.size() - 1);
}

// wordpiece_free(handle): release a vocab registered by wordpiece_load
static PyObject* py_wordpiece_free(PyObject*, PyObject* args) {
  Py_ssize_t handle;
  if (!PyArg_ParseTuple(args, "n", &handle)) return nullptr;
  if (handle >= 0 && (size_t)handle < g_wp_vocabs.size()) {
    delete g_wp_vocabs[(size_t)handle];
    g_wp_vocabs[(size_t)handle] = nullptr;
  }
  Py_RETURN_NONE;
}

static inline bool wp_is_punct(unsigned char c) {
  // BERT _is_punctuation ASCII ranges
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
         (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

// greedy longest-match of one lowercased ASCII word into piece ids;
// probes are string_views into the word buffer — no allocations
static void wp_word(const WordPieceVocab& v, const std::string& w,
                    int32_t unk_id, std::vector<int32_t>& out) {
  if (w.size() > 200) {  // BERT max_input_chars_per_word
    out.push_back(unk_id);
    return;
  }
  size_t start = 0;
  std::vector<int32_t> pieces;
  while (start < w.size()) {
    const WpMap& m = start ? v.word_suffix : v.word_start;
    size_t end = w.size();
    int32_t id = -1;
    while (end > start) {
      auto it = m.find(std::string_view(w.data() + start, end - start));
      if (it != m.end()) {
        id = it->second;
        break;
      }
      end--;
    }
    if (id < 0) {  // whole word becomes [UNK]
      out.push_back(unk_id);
      return;
    }
    pieces.push_back(id);
    start = end;
  }
  out.insert(out.end(), pieces.begin(), pieces.end());
}

// wordpiece_tokenize(handle, texts, max_length, cls_id, sep_id, unk_id,
//                    pad_id) -> (ids_bytearray, width, lens_bytearray,
//                                fallback_indices)
static PyObject* py_wordpiece_tokenize(PyObject*, PyObject* args) {
  Py_ssize_t handle;
  PyObject* seq;
  long max_length, cls_id, sep_id, unk_id, pad_id;
  if (!PyArg_ParseTuple(args, "nOlllll", &handle, &seq, &max_length,
                        &cls_id, &sep_id, &unk_id, &pad_id))
    return nullptr;
  if (handle < 0 || (size_t)handle >= g_wp_vocabs.size() ||
      g_wp_vocabs[(size_t)handle] == nullptr) {
    PyErr_SetString(PyExc_ValueError, "bad wordpiece vocab handle");
    return nullptr;
  }
  const WordPieceVocab& vocab = *g_wp_vocabs[(size_t)handle];
  PyObject* fast = PySequence_Fast(seq, "expected a sequence of strings");
  if (fast == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  std::vector<int32_t> flat;
  flat.reserve((size_t)n * 32);
  std::vector<uint32_t> lens((size_t)n);
  size_t width = 2;
  PyObject* fallback = PyList_New(0);
  if (fallback == nullptr) {
    Py_DECREF(fast);
    return nullptr;
  }
  std::string word;
  std::vector<int32_t> pieces;
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t slen;
    const char* s = PyUnicode_AsUTF8AndSize(items[i], &slen);
    if (s == nullptr) {
      Py_DECREF(fast);
      Py_DECREF(fallback);
      return nullptr;
    }
    bool ascii = true;
    for (Py_ssize_t j = 0; j < slen; j++) {
      if ((unsigned char)s[j] >= 0x80) {
        ascii = false;
        break;
      }
    }
    size_t row_start = flat.size();
    flat.push_back((int32_t)cls_id);
    if (!ascii) {
      PyObject* idx = PyLong_FromSsize_t(i);
      if (idx == nullptr || PyList_Append(fallback, idx) < 0) {
        Py_XDECREF(idx);
        Py_DECREF(fast);
        Py_DECREF(fallback);
        return nullptr;
      }
      Py_DECREF(idx);
    } else {
      pieces.clear();
      word.clear();
      for (Py_ssize_t j = 0; j <= slen; j++) {
        unsigned char c = j < slen ? (unsigned char)s[j] : (unsigned char)' ';
        unsigned char lc = (c >= 'A' && c <= 'Z') ? (unsigned char)(c + 32) : c;
        bool is_space = (c == ' ' || c == '\t' || c == '\n' || c == '\r');
        bool is_ctrl = (c < 0x20 && !is_space) || c == 0x7f;
        if (is_ctrl) continue;  // control chars are REMOVED (BERT
        // clean_text): 'ab\x01cd' stays ONE word, it does not split
        if (is_space || wp_is_punct(c)) {
          if (!word.empty()) {
            wp_word(vocab, word, (int32_t)unk_id, pieces);
            word.clear();
          }
          if (wp_is_punct(c)) {
            char pc = (char)c;
            auto it = vocab.word_start.find(std::string_view(&pc, 1));
            pieces.push_back(it != vocab.word_start.end()
                                 ? it->second
                                 : (int32_t)unk_id);
          }
        } else {
          word.push_back((char)lc);
        }
      }
      long budget = max_length > 2 ? max_length - 2 : 0;  // [CLS]/[SEP] room
      long take = (long)pieces.size() < budget ? (long)pieces.size() : budget;
      flat.insert(flat.end(), pieces.begin(), pieces.begin() + take);
    }
    flat.push_back((int32_t)sep_id);
    lens[(size_t)i] = (uint32_t)(flat.size() - row_start);
    if (lens[(size_t)i] > width) width = lens[(size_t)i];
  }
  Py_DECREF(fast);
  PyObject* out = PyByteArray_FromStringAndSize(
      nullptr, (Py_ssize_t)(n * width * 4));
  PyObject* lens_out = PyByteArray_FromStringAndSize(
      nullptr, (Py_ssize_t)(n * 4));
  if (out == nullptr || lens_out == nullptr) {
    Py_XDECREF(out);
    Py_XDECREF(lens_out);
    Py_DECREF(fallback);
    return nullptr;
  }
  int32_t* dst = reinterpret_cast<int32_t*>(PyByteArray_AS_STRING(out));
  uint32_t* lp = reinterpret_cast<uint32_t*>(PyByteArray_AS_STRING(lens_out));
  size_t pos = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    uint32_t len = lens[(size_t)i];
    std::memcpy(dst + (size_t)i * width, flat.data() + pos, (size_t)len * 4);
    for (size_t j = len; j < width; j++)
      dst[(size_t)i * width + j] = (int32_t)pad_id;
    lp[i] = len;
    pos += len;
  }
  return Py_BuildValue("(NnNN)", out, (Py_ssize_t)width, lens_out, fallback);
}

// rows_from_records(records, cols, dtype_codes, defaults)
//   -> (rows list[tuple], fallback_indices list[int])
// Batch schema extraction+coercion — the per-record half the reference
// does in Rust (src/connectors/data_format.rs JsonLinesParser). For each
// record dict, produce one row tuple in column order with the FAST
// coercions applied in C: exact-type passthrough, int->float, absent ->
// schema default / None. A record needing anything slower (string->int
// parses, datetimes, JSON wrapping, non-dict records) lands in
// fallback_indices and is re-parsed wholesale by the Python path, so
// semantics cannot drift. dtype_codes per column: 0=always-fallback,
// 1=INT, 2=FLOAT, 3=BOOL, 4=STR, 5=BYTES, 6=ANY(passthrough).
static PyObject* py_rows_from_records(PyObject*, PyObject* args) {
  PyObject *records, *cols, *codes_obj, *defaults;
  if (!PyArg_ParseTuple(args, "OOOO", &records, &cols, &codes_obj, &defaults))
    return nullptr;
  PyObject* rec_fast = PySequence_Fast(records, "records must be a sequence");
  if (rec_fast == nullptr) return nullptr;
  PyObject* col_fast = PySequence_Fast(cols, "cols must be a sequence");
  if (col_fast == nullptr) {
    Py_DECREF(rec_fast);
    return nullptr;
  }
  PyObject* code_fast = PySequence_Fast(codes_obj, "codes must be a sequence");
  if (code_fast == nullptr) {
    Py_DECREF(rec_fast);
    Py_DECREF(col_fast);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(rec_fast);
  Py_ssize_t nc = PySequence_Fast_GET_SIZE(col_fast);
  if (PySequence_Fast_GET_SIZE(code_fast) != nc || !PyDict_Check(defaults)) {
    Py_DECREF(rec_fast);
    Py_DECREF(col_fast);
    Py_DECREF(code_fast);
    PyErr_SetString(PyExc_ValueError, "cols/codes length mismatch or bad defaults");
    return nullptr;
  }
  std::vector<long> codes((size_t)nc);
  for (Py_ssize_t j = 0; j < nc; j++) {
    codes[(size_t)j] = PyLong_AsLong(PySequence_Fast_GET_ITEM(code_fast, j));
  }
  PyObject** recs = PySequence_Fast_ITEMS(rec_fast);
  PyObject** colnames = PySequence_Fast_ITEMS(col_fast);
  PyObject* rows = PyList_New(n);
  PyObject* fallback = PyList_New(0);
  if (rows == nullptr || fallback == nullptr) goto fail;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* rec = recs[i];
    bool ok = PyDict_Check(rec);
    PyObject* row = ok ? PyTuple_New(nc) : nullptr;
    if (ok && row == nullptr) goto fail;
    for (Py_ssize_t j = 0; ok && j < nc; j++) {
      PyObject* v = PyDict_GetItem(rec, colnames[j]);  // borrowed
      PyObject* outv = nullptr;
      if (v == nullptr) {  // absent field: schema default, else null
        outv = PyDict_GetItem(defaults, colnames[j]);
        if (outv == nullptr) outv = Py_None;
        Py_INCREF(outv);
      } else if (v == Py_None) {
        outv = Py_None;
        Py_INCREF(outv);
      } else {
        switch (codes[(size_t)j]) {
          case 1:  // INT
            if (PyLong_Check(v) && !PyBool_Check(v)) {
              outv = v;
              Py_INCREF(outv);
            }
            break;
          case 2:  // FLOAT
            if (PyFloat_Check(v)) {
              outv = v;
              Py_INCREF(outv);
            } else if (PyLong_Check(v) && !PyBool_Check(v)) {
              double d = PyLong_AsDouble(v);
              if (d == -1.0 && PyErr_Occurred()) {
                PyErr_Clear();
              } else {
                outv = PyFloat_FromDouble(d);
              }
            }
            break;
          case 3:  // BOOL
            if (PyBool_Check(v)) {
              outv = v;
              Py_INCREF(outv);
            }
            break;
          case 4:  // STR
            if (PyUnicode_Check(v)) {
              outv = v;
              Py_INCREF(outv);
            }
            break;
          case 5:  // BYTES
            if (PyBytes_Check(v)) {
              outv = v;
              Py_INCREF(outv);
            }
            break;
          case 6:  // ANY: passthrough
            outv = v;
            Py_INCREF(outv);
            break;
          default:
            break;  // 0: always fallback
        }
      }
      if (outv == nullptr) {
        ok = false;  // slow coercion needed: whole record -> Python
      } else {
        PyTuple_SET_ITEM(row, j, outv);
      }
    }
    if (ok) {
      PyList_SET_ITEM(rows, i, row);  // steals
    } else {
      Py_XDECREF(row);
      Py_INCREF(Py_None);
      PyList_SET_ITEM(rows, i, Py_None);
      PyObject* idx = PyLong_FromSsize_t(i);
      if (idx == nullptr || PyList_Append(fallback, idx) < 0) {
        Py_XDECREF(idx);
        goto fail;
      }
      Py_DECREF(idx);
    }
  }
  Py_DECREF(rec_fast);
  Py_DECREF(col_fast);
  Py_DECREF(code_fast);
  return Py_BuildValue("(NN)", rows, fallback);
fail:
  Py_DECREF(rec_fast);
  Py_DECREF(col_fast);
  Py_DECREF(code_fast);
  Py_XDECREF(rows);
  Py_XDECREF(fallback);
  return nullptr;
}

// jsonl_rows(data, cols, dtype_codes, defaults)
//   -> (rows list[tuple|None], fallback list[(index, line_bytes)])
// One-pass JSON-lines parse + schema extraction + fast coercion straight
// from bytes — the full Rust-parser analog (data_format.rs JsonLinesParser
// over data_tokenize.rs lines). Flat objects with string/int/float/bool/
// null values parse here; any line with escapes, nested containers,
// overflowing ints, or coercions outside the fast table is returned as a
// fallback (index, bytes) pair for the Python path. Blank lines produce no
// row. Rows list holds None at fallback positions (caller patches/drops).
namespace jsonl {

struct Cursor {
  const char* p;
  const char* end;
};

static inline void skip_ws(Cursor& c) {
  while (c.p < c.end &&
         (*c.p == ' ' || *c.p == '\t' || *c.p == '\r')) {
    c.p++;
  }
}

// scan a JSON string (after the opening quote); false => escape/invalid
static bool scan_string(Cursor& c, const char** s, size_t* len) {
  *s = c.p;
  while (c.p < c.end) {
    unsigned char ch = (unsigned char)*c.p;
    if (ch == '"') {
      *len = (size_t)(c.p - *s);
      c.p++;
      return true;
    }
    if (ch == '\\' || ch < 0x20) return false;  // escapes -> python path
    c.p++;
  }
  return false;
}

enum ValKind { V_FAIL, V_STR, V_INT, V_FLOAT, V_TRUE, V_FALSE, V_NULL };

struct Val {
  ValKind kind;
  const char* s;
  size_t len;
  long long i;
  double d;
};

static Val parse_value(Cursor& c) {
  Val v;
  v.kind = V_FAIL;
  skip_ws(c);
  if (c.p >= c.end) return v;
  char ch = *c.p;
  if (ch == '"') {
    c.p++;
    if (scan_string(c, &v.s, &v.len)) v.kind = V_STR;
    return v;
  }
  if (ch == 't') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "true", 4) == 0) {
      c.p += 4;
      v.kind = V_TRUE;
    }
    return v;
  }
  if (ch == 'f') {
    if (c.end - c.p >= 5 && std::memcmp(c.p, "false", 5) == 0) {
      c.p += 5;
      v.kind = V_FALSE;
    }
    return v;
  }
  if (ch == 'n') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "null", 4) == 0) {
      c.p += 4;
      v.kind = V_NULL;
    }
    return v;
  }
  if (ch == '-' || (ch >= '0' && ch <= '9')) {
    // strict JSON number grammar: -?(0|[1-9]\d*)(\.\d+)?([eE][+-]?\d+)?
    // — leading-zero ints ('0123') and empty fractions ('1.') must FAIL
    // here exactly like json.loads rejects them, or the fast path would
    // emit rows from lines the Python path drops
    const char* start = c.p;
    bool is_float = false;
    if (ch == '-') c.p++;
    const char* int_start = c.p;
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') c.p++;
    size_t int_digits = (size_t)(c.p - int_start);
    if (int_digits == 0 ||
        (int_digits > 1 && *int_start == '0')) {
      return v;
    }
    if (c.p < c.end && *c.p == '.') {
      is_float = true;
      c.p++;
      const char* frac_start = c.p;
      while (c.p < c.end && *c.p >= '0' && *c.p <= '9') c.p++;
      if (c.p == frac_start) return v;  // '1.' is not JSON
    }
    if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
      is_float = true;
      c.p++;
      if (c.p < c.end && (*c.p == '+' || *c.p == '-')) c.p++;
      const char* exp_start = c.p;
      while (c.p < c.end && *c.p >= '0' && *c.p <= '9') c.p++;
      if (c.p == exp_start) return v;  // '1e' is not JSON
    }
    std::string num(start, (size_t)(c.p - start));
    if (is_float) {
      char* endp = nullptr;
      v.d = std::strtod(num.c_str(), &endp);
      if (endp == num.c_str() + num.size()) v.kind = V_FLOAT;
    } else {
      errno = 0;
      char* endp = nullptr;
      v.i = std::strtoll(num.c_str(), &endp, 10);
      if (errno == 0 && endp == num.c_str() + num.size()) v.kind = V_INT;
    }
    return v;
  }
  return v;  // '{' / '[' / garbage -> fallback
}

}  // namespace jsonl

static PyObject* py_jsonl_rows(PyObject*, PyObject* args) {
  Py_buffer buf;
  PyObject *cols, *codes_obj, *defaults;
  int columnar = 0;  // 1: emit per-column LISTS (no row tuples) — the
                     // bulk fs reader consumes columns, so the row-tuple
                     // detour and its transpose disappear entirely
  if (!PyArg_ParseTuple(args, "y*OOO|i", &buf, &cols, &codes_obj, &defaults,
                        &columnar))
    return nullptr;
  PyObject* col_fast = PySequence_Fast(cols, "cols must be a sequence");
  PyObject* code_fast =
      col_fast ? PySequence_Fast(codes_obj, "codes must be a sequence")
               : nullptr;
  if (col_fast == nullptr || code_fast == nullptr) {
    Py_XDECREF(col_fast);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t nc = PySequence_Fast_GET_SIZE(col_fast);
  std::vector<std::string> names((size_t)nc);
  std::vector<long> codes((size_t)nc);
  std::vector<PyObject*> defvals((size_t)nc);  // borrowed (or nullptr)
  bool arg_err = PySequence_Fast_GET_SIZE(code_fast) != nc ||
                 !PyDict_Check(defaults);
  for (Py_ssize_t j = 0; !arg_err && j < nc; j++) {
    PyObject* nm = PySequence_Fast_GET_ITEM(col_fast, j);
    Py_ssize_t sl;
    const char* s = PyUnicode_AsUTF8AndSize(nm, &sl);
    if (s == nullptr) {
      arg_err = true;
      break;
    }
    names[(size_t)j].assign(s, (size_t)sl);
    codes[(size_t)j] = PyLong_AsLong(PySequence_Fast_GET_ITEM(code_fast, j));
    defvals[(size_t)j] = PyDict_GetItem(defaults, nm);
  }
  if (arg_err) {
    Py_DECREF(col_fast);
    Py_DECREF(code_fast);
    PyBuffer_Release(&buf);
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_ValueError, "bad cols/codes/defaults");
    return nullptr;
  }
  PyObject* rows = columnar ? nullptr : PyList_New(0);
  PyObject* fallback = PyList_New(0);
  std::vector<PyObject*> col_out;  // columnar mode: one list per column
  bool mem_err = (!columnar && rows == nullptr) || fallback == nullptr;
  if (columnar) {
    col_out.resize((size_t)nc, nullptr);
    for (Py_ssize_t j = 0; !mem_err && j < nc; j++) {
      col_out[(size_t)j] = PyList_New(0);
      if (col_out[(size_t)j] == nullptr) mem_err = true;
    }
  }
  Py_ssize_t n_rows_out = 0;  // emitted rows incl. fallback placeholders
  const char* data = reinterpret_cast<const char*>(buf.buf);
  const char* data_end = data + buf.len;
  std::vector<PyObject*> rowvals((size_t)nc);  // owned per row
  const char* line = data;
  while (line < data_end && !mem_err) {
    const char* nl = (const char*)std::memchr(line, '\n', (size_t)(data_end - line));
    const char* line_end = nl ? nl : data_end;
    jsonl::Cursor c{line, line_end};
    jsonl::skip_ws(c);
    if (c.p == line_end) {  // blank line: no row
      line = nl ? nl + 1 : data_end;
      continue;
    }
    bool ok = (*c.p == '{');
    if (ok) c.p++;
    for (Py_ssize_t j = 0; j < nc; j++) rowvals[(size_t)j] = nullptr;
    if (ok) {
      jsonl::skip_ws(c);
      if (c.p < line_end && *c.p == '}') {
        c.p++;  // empty object
      } else {
        while (ok) {
          jsonl::skip_ws(c);
          if (c.p >= line_end || *c.p != '"') {
            ok = false;
            break;
          }
          c.p++;
          const char* ks;
          size_t klen;
          if (!jsonl::scan_string(c, &ks, &klen)) {
            ok = false;
            break;
          }
          jsonl::skip_ws(c);
          if (c.p >= line_end || *c.p != ':') {
            ok = false;
            break;
          }
          c.p++;
          jsonl::Val v = jsonl::parse_value(c);
          if (v.kind == jsonl::V_FAIL) {
            ok = false;
            break;
          }
          // which column? (linear scan; schemas are narrow)
          Py_ssize_t target = -1;
          for (Py_ssize_t j = 0; j < nc; j++) {
            if (names[(size_t)j].size() == klen &&
                std::memcmp(names[(size_t)j].data(), ks, klen) == 0) {
              target = j;
              break;
            }
          }
          if (target >= 0) {
            PyObject* outv = nullptr;
            long code = codes[(size_t)target];
            switch (v.kind) {
              case jsonl::V_NULL:
                outv = Py_None;
                Py_INCREF(outv);
                break;
              case jsonl::V_STR:
                if (code == 4 || code == 6)
                  outv = PyUnicode_FromStringAndSize(v.s, (Py_ssize_t)v.len);
                break;
              case jsonl::V_INT:
                if (code == 1 || code == 6)
                  outv = PyLong_FromLongLong(v.i);
                else if (code == 2)
                  outv = PyFloat_FromDouble((double)v.i);
                break;
              case jsonl::V_FLOAT:
                if (code == 2 || code == 6)
                  outv = PyFloat_FromDouble(v.d);
                break;
              case jsonl::V_TRUE:
              case jsonl::V_FALSE:
                if (code == 3 || code == 6) {
                  outv = v.kind == jsonl::V_TRUE ? Py_True : Py_False;
                  Py_INCREF(outv);
                }
                break;
              default:
                break;
            }
            if (outv == nullptr) {
              // slow coercion -> python (clear any allocation/decoding
              // error PyUnicode_FromStringAndSize may have set)
              if (PyErr_Occurred()) PyErr_Clear();
              ok = false;
              break;
            }
            Py_XDECREF(rowvals[(size_t)target]);  // duplicate key: last wins
            rowvals[(size_t)target] = outv;
          }
          jsonl::skip_ws(c);
          if (c.p < line_end && *c.p == ',') {
            c.p++;
            continue;
          }
          if (c.p < line_end && *c.p == '}') {
            c.p++;
            break;
          }
          ok = false;
        }
      }
      if (ok) {  // only trailing whitespace may follow
        jsonl::skip_ws(c);
        ok = (c.p == line_end);
      }
    }
    if (ok) {
      if (columnar) {
        for (Py_ssize_t j = 0; j < nc && !mem_err; j++) {
          PyObject* outv = rowvals[(size_t)j];
          if (outv == nullptr) {
            outv = defvals[(size_t)j] ? defvals[(size_t)j] : Py_None;
            Py_INCREF(outv);
          }
          if (PyList_Append(col_out[(size_t)j], outv) < 0) mem_err = true;
          Py_DECREF(outv);
          rowvals[(size_t)j] = nullptr;
        }
        for (Py_ssize_t j = 0; j < nc; j++) {  // on error: free leftovers
          Py_XDECREF(rowvals[(size_t)j]);
          rowvals[(size_t)j] = nullptr;
        }
        n_rows_out++;
      } else {
        PyObject* row = PyTuple_New(nc);
        if (row == nullptr) {
          mem_err = true;
        } else {
          for (Py_ssize_t j = 0; j < nc; j++) {
            PyObject* outv = rowvals[(size_t)j];
            if (outv == nullptr) {
              outv = defvals[(size_t)j] ? defvals[(size_t)j] : Py_None;
              Py_INCREF(outv);
            }
            PyTuple_SET_ITEM(row, j, outv);
            rowvals[(size_t)j] = nullptr;
          }
          if (PyList_Append(rows, row) < 0) mem_err = true;
          Py_DECREF(row);
          n_rows_out++;
        }
      }
    } else {
      for (Py_ssize_t j = 0; j < nc; j++) Py_XDECREF(rowvals[(size_t)j]);
      PyObject* entry = Py_BuildValue(
          "(ny#)", n_rows_out, line, (Py_ssize_t)(line_end - line));
      if (entry == nullptr || PyList_Append(fallback, entry) < 0) {
        Py_XDECREF(entry);
        mem_err = true;
      } else {
        Py_DECREF(entry);
        if (columnar) {
          for (Py_ssize_t j = 0; j < nc && !mem_err; j++) {
            if (PyList_Append(col_out[(size_t)j], Py_None) < 0)
              mem_err = true;
          }
        } else {
          Py_INCREF(Py_None);
          if (PyList_Append(rows, Py_None) < 0) mem_err = true;
          Py_DECREF(Py_None);
        }
        n_rows_out++;
      }
    }
    line = nl ? nl + 1 : data_end;
  }
  Py_DECREF(col_fast);
  Py_DECREF(code_fast);
  PyBuffer_Release(&buf);
  if (mem_err) {
    Py_XDECREF(rows);
    Py_XDECREF(fallback);
    for (PyObject* cl : col_out) Py_XDECREF(cl);
    return nullptr;
  }
  if (columnar) {
    PyObject* cols_tuple = PyTuple_New(nc);
    if (cols_tuple == nullptr) {
      Py_XDECREF(fallback);
      for (PyObject* cl : col_out) Py_XDECREF(cl);
      return nullptr;
    }
    for (Py_ssize_t j = 0; j < nc; j++) {
      PyTuple_SET_ITEM(cols_tuple, j, col_out[(size_t)j]);  // steals ref
    }
    return Py_BuildValue("(NnN)", cols_tuple, n_rows_out, fallback);
  }
  return Py_BuildValue("(NN)", rows, fallback);
}

// ------------------------------------------------------------- CSV (DSV)
// RFC4180-style state machine mirroring Python's csv.DictReader semantics
// for the common settings (1-byte delimiter/quote): records split on
// newlines OUTSIDE quotes, quoted fields may contain delimiter/newline and
// doubled quotes, a trailing \r before the record break is stripped.
// Simple coercions (int/float/bool/str) happen here; any record with a
// field the simple parser cannot coerce exactly like io/_utils.parse_value
// is returned as a fallback (record index, raw record bytes) for the
// Python csv module to re-parse — results are identical either way.

namespace csvn {

// exact mirror of parse_value's int(): optional sign + digits only
// (anything else — underscores, whitespace, hex — goes to fallback)
static bool parse_int(const std::string& f, long long* out) {
  if (f.empty()) return false;
  size_t i = (f[0] == '+' || f[0] == '-') ? 1 : 0;
  if (i == f.size()) return false;
  long long v = 0;
  for (; i < f.size(); i++) {
    if (f[i] < '0' || f[i] > '9') return false;
    if (v > (9223372036854775807LL - 9) / 10) return false;  // overflow
    v = v * 10 + (f[i] - '0');
  }
  *out = f[0] == '-' ? -v : v;
  return true;
}

static bool parse_float(const std::string& f, double* out) {
  if (f.empty()) return false;
  // strtod accepts inf/nan/hex and leading whitespace, which Python's
  // float() treats differently in part — allow only the plain forms
  for (char c : f) {
    if (!((c >= '0' && c <= '9') || c == '+' || c == '-' || c == '.' ||
          c == 'e' || c == 'E'))
      return false;
  }
  errno = 0;
  char* end = nullptr;
  double v = std::strtod(f.c_str(), &end);
  if (end != f.c_str() + f.size() || errno == ERANGE) return false;
  *out = v;
  return true;
}

static inline bool is_strip_ws(char c) {
  // the ASCII subset of what Python str.strip() removes (incl. the
  // \x1c-\x1f separator control chars, which are .isspace() in Python)
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == '\v' || (c >= '\x1c' && c <= '\x1f');
}

// Mirrors str(raw).strip().lower() in ("1","true","yes","on").  Returns
// false (caller falls back to the Python parser) when the field holds
// non-ASCII bytes, where Python's strip()/lower() could diverge.
static bool parse_bool(const std::string& f, bool* out) {
  std::string t;
  t.reserve(f.size());
  size_t b = 0, e = f.size();
  while (b < e && is_strip_ws(f[b])) b++;
  while (e > b && is_strip_ws(f[e - 1])) e--;
  for (size_t i = b; i < e; i++) {
    char c = f[i];
    if ((unsigned char)c >= 0x80) return false;
    t.push_back(c >= 'A' && c <= 'Z' ? (char)(c + 32) : c);
  }
  *out = (t == "1" || t == "true" || t == "yes" || t == "on");
  return true;
}

}  // namespace csvn

// csv_cols(data, delimiter, quote, cols, codes, defaults)
//   -> (header_list, col_lists_tuple, n_rows, fallback[(idx, bytes)])
static PyObject* py_csv_cols(PyObject*, PyObject* args) {
  Py_buffer buf;
  int delim_i, quote_i;
  PyObject *cols, *codes_obj, *defaults;
  if (!PyArg_ParseTuple(args, "y*iiOOO", &buf, &delim_i, &quote_i, &cols,
                        &codes_obj, &defaults))
    return nullptr;
  const char delim = (char)delim_i, quote = (char)quote_i;
  PyObject* col_fast = PySequence_Fast(cols, "cols must be a sequence");
  PyObject* code_fast =
      col_fast ? PySequence_Fast(codes_obj, "codes must be a sequence")
               : nullptr;
  if (col_fast == nullptr || code_fast == nullptr) {
    Py_XDECREF(col_fast);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  Py_ssize_t nc = PySequence_Fast_GET_SIZE(col_fast);
  std::vector<std::string> names((size_t)nc);
  std::vector<long> codes((size_t)nc);
  std::vector<PyObject*> defvals((size_t)nc);  // borrowed or nullptr
  bool arg_err = PySequence_Fast_GET_SIZE(code_fast) != nc ||
                 !PyDict_Check(defaults);
  for (Py_ssize_t j = 0; !arg_err && j < nc; j++) {
    PyObject* nm = PySequence_Fast_GET_ITEM(col_fast, j);
    Py_ssize_t sl;
    const char* s = PyUnicode_AsUTF8AndSize(nm, &sl);
    if (s == nullptr) { arg_err = true; break; }
    names[(size_t)j].assign(s, (size_t)sl);
    codes[(size_t)j] = PyLong_AsLong(PySequence_Fast_GET_ITEM(code_fast, j));
    defvals[(size_t)j] = PyDict_GetItem(defaults, nm);
  }
  if (arg_err) {
    Py_DECREF(col_fast);
    Py_DECREF(code_fast);
    PyBuffer_Release(&buf);
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_ValueError, "bad cols/codes/defaults");
    return nullptr;
  }
  const char* p = reinterpret_cast<const char*>(buf.buf);
  const char* end = p + buf.len;

  // one record: fields split on delim outside quotes; doubled quotes
  // inside a quoted field unescape; returns false at EOF with no data
  std::vector<std::string> fields;
  auto read_record = [&](const char** cursor, const char** rec_start,
                         const char** rec_end) -> bool {
    const char* c = *cursor;
    if (c >= end) return false;
    *rec_start = c;
    fields.clear();
    std::string cur;
    bool in_quotes = false;
    bool any = false;
    // csv.reader opens a quoted section only when the quote is the very
    // first character of a field; any later quote is a literal char
    // (e.g. '5" disk,x' -> ['5" disk', 'x'], '"a"b"c,d' -> ['ab"c', 'd'])
    bool field_fresh = true;
    while (c < end) {
      char ch = *c;
      if (in_quotes) {
        if (ch == quote) {
          if (c + 1 < end && c[1] == quote) { cur.push_back(quote); c += 2; }
          else { in_quotes = false; c++; }
        } else { cur.push_back(ch); c++; }
      } else if (ch == quote) {
        if (field_fresh) in_quotes = true;
        else cur.push_back(quote);
        field_fresh = false;
        any = true;
        c++;
      } else if (ch == delim) {
        fields.push_back(cur);
        cur.clear();
        field_fresh = true;
        any = true;
        c++;
      } else if (ch == '\n' || ch == '\r') {
        const char* brk = c;
        if (ch == '\r' && c + 1 < end && c[1] == '\n') c += 2; else c++;
        if (!any && cur.empty() && fields.empty()) {
          // blank line: csv.reader yields [] and DictReader skips it
          *cursor = c;
          *rec_start = c;
          continue;
        }
        fields.push_back(cur);
        *rec_end = brk;
        *cursor = c;
        return true;
      } else {
        cur.push_back(ch);
        field_fresh = false;
        any = true;
        c++;
      }
    }
    if (!any && cur.empty() && fields.empty()) { *cursor = c; return false; }
    fields.push_back(cur);
    *rec_end = c;
    *cursor = c;
    return true;
  };

  const char* cursor = p;
  const char *rs, *re;
  PyObject* header = PyList_New(0);
  std::vector<Py_ssize_t> field_to_col;  // header position -> schema col
  bool mem_err = header == nullptr;
  if (!mem_err && read_record(&cursor, &rs, &re)) {
    for (const std::string& h : fields) {
      PyObject* hs = PyUnicode_DecodeUTF8(h.data(), (Py_ssize_t)h.size(),
                                          "replace");
      if (hs == nullptr || PyList_Append(header, hs) < 0) {
        Py_XDECREF(hs);
        mem_err = true;
        break;
      }
      Py_DECREF(hs);
      Py_ssize_t target = -1;
      for (Py_ssize_t j = 0; j < nc; j++) {
        if (names[(size_t)j] == h) { target = j; break; }
      }
      field_to_col.push_back(target);
    }
  }
  std::vector<PyObject*> col_out((size_t)nc, nullptr);
  PyObject* fallback = PyList_New(0);
  if (fallback == nullptr) mem_err = true;
  for (Py_ssize_t j = 0; !mem_err && j < nc; j++) {
    col_out[(size_t)j] = PyList_New(0);
    if (col_out[(size_t)j] == nullptr) mem_err = true;
  }
  // schema columns ABSENT from the header take defaults every row
  // (parse_record_fields absent-field semantics); header-mapped columns
  // missing from a SHORT row get None (DictReader's restval)
  std::vector<bool> col_in_header((size_t)nc, false);
  for (Py_ssize_t t : field_to_col) {
    if (t >= 0) col_in_header[(size_t)t] = true;
  }
  std::vector<PyObject*> rowvals((size_t)nc);
  Py_ssize_t n_rows = 0;
  while (!mem_err && read_record(&cursor, &rs, &re)) {
    for (Py_ssize_t j = 0; j < nc; j++) rowvals[(size_t)j] = nullptr;
    bool ok = true;
    for (size_t fi = 0; ok && fi < fields.size() && fi < field_to_col.size();
         fi++) {
      Py_ssize_t target = field_to_col[fi];
      if (target < 0) continue;
      const std::string& f = fields[fi];
      long code = codes[(size_t)target];
      PyObject* outv = nullptr;
      switch (code) {
        case 1: {
          long long v;
          if (csvn::parse_int(f, &v)) outv = PyLong_FromLongLong(v);
          break;
        }
        case 2: {
          double v;
          if (csvn::parse_float(f, &v)) outv = PyFloat_FromDouble(v);
          break;
        }
        case 3: {
          bool v;
          if (csvn::parse_bool(f, &v)) {
            outv = v ? Py_True : Py_False;
            Py_INCREF(outv);
          }
          break;
        }
        case 4:
        case 6:
          outv = PyUnicode_DecodeUTF8(f.data(), (Py_ssize_t)f.size(),
                                      "replace");
          break;
        default:
          break;  // bytes/json/datetime/containers -> python fallback
      }
      if (outv == nullptr) {
        if (PyErr_Occurred()) PyErr_Clear();
        ok = false;
        break;
      }
      Py_XDECREF(rowvals[(size_t)target]);
      rowvals[(size_t)target] = outv;
    }
    if (ok) {
      for (Py_ssize_t j = 0; j < nc && !mem_err; j++) {
        PyObject* outv = rowvals[(size_t)j];
        if (outv == nullptr) {
          if (!col_in_header[(size_t)j] && defvals[(size_t)j] != nullptr) {
            outv = defvals[(size_t)j];  // absent column -> schema default
          } else {
            outv = Py_None;  // short row (restval) or absent w/o default
          }
          Py_INCREF(outv);
        }
        if (PyList_Append(col_out[(size_t)j], outv) < 0) mem_err = true;
        Py_DECREF(outv);
        rowvals[(size_t)j] = nullptr;
      }
      for (Py_ssize_t j = 0; j < nc; j++) {
        Py_XDECREF(rowvals[(size_t)j]);
        rowvals[(size_t)j] = nullptr;
      }
      n_rows++;
    } else {
      for (Py_ssize_t j = 0; j < nc; j++) {
        Py_XDECREF(rowvals[(size_t)j]);
        rowvals[(size_t)j] = nullptr;
      }
      PyObject* entry = Py_BuildValue("(ny#)", n_rows, rs,
                                      (Py_ssize_t)(re - rs));
      if (entry == nullptr || PyList_Append(fallback, entry) < 0) {
        Py_XDECREF(entry);
        mem_err = true;
      } else {
        Py_DECREF(entry);
        for (Py_ssize_t j = 0; j < nc && !mem_err; j++) {
          if (PyList_Append(col_out[(size_t)j], Py_None) < 0) mem_err = true;
        }
        n_rows++;
      }
    }
  }
  Py_DECREF(col_fast);
  Py_DECREF(code_fast);
  PyBuffer_Release(&buf);
  if (mem_err) {
    Py_XDECREF(header);
    Py_XDECREF(fallback);
    for (PyObject* cl : col_out) Py_XDECREF(cl);
    return nullptr;
  }
  PyObject* cols_tuple = PyTuple_New(nc);
  if (cols_tuple == nullptr) {
    Py_XDECREF(header);
    Py_XDECREF(fallback);
    for (PyObject* cl : col_out) Py_XDECREF(cl);
    return nullptr;
  }
  for (Py_ssize_t j = 0; j < nc; j++) {
    PyTuple_SET_ITEM(cols_tuple, j, col_out[(size_t)j]);  // steals ref
  }
  return Py_BuildValue("(NNnN)", header, cols_tuple, n_rows, fallback);
}

static PyObject* py_set_pointer_type(PyObject*, PyObject* args) {
  PyObject* t;
  if (!PyArg_ParseTuple(args, "O", &t)) return nullptr;
  Py_XINCREF(t);
  Py_XDECREF(g_pointer_type);
  g_pointer_type = t;
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------- join emit
// join_ld_cross(works, sides, idxs)
// splitmix64 rehash with salt — must match value.py hash_keys_with.
static inline uint64_t splitmix_salt(uint64_t x, uint64_t salt) {
  x += salt;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

static const uint64_t kSeqSalt = 0x9E3779B97F4A7C15ULL;  // value.py _SEQ_SALT
static const uint64_t kColPrime = 0x100000001B3ULL;

// xxh64 of the canonical serialization of one value (the per-element body
// of hash_object_column). Returns false when the value can't be
// canonically serialized in C (exotic types) — callers raise.
static bool hash_value_u64(PyObject* v, std::string& scratch, uint64_t* out) {
  scratch.clear();
  if (!serialize(v, scratch)) return false;
  *out = xxh64(reinterpret_cast<const uint8_t*>(scratch.data()),
               scratch.size(), 0);
  return true;
}

//   works: list of (ld, rbucket) where ld = [(key, row, diff), ...] and
//          rbucket = {rkey: rrow}; rows are tuples. diff (+/-1) is the
//          emission weight: a retracted left row crossed against the
//          bucket emits its pairs with diff -1 (the weighted bilinear
//          delta — mixed insert/retract streams ride the same path).
//   sides: bytes, one per output column, 1 = from lrow else rrow.
//   idxs:  list of ints, source position within that row.
// One call per engine step covers every fast-path join key: emits the
// dL x R cross product COLUMNAR — (col_lists, out_keys_u64_bytes,
// diffs_i64_bytes) — with the pair output keys (Key::for_values(lk,
// rk), matching value.py keys_for_value_columns) hashed inline. The
// caller wraps the columns + key/diff buffers straight into a Batch:
// no row tuples, no re-split, no second hashing pass.
static PyObject* py_join_ld_cross(PyObject*, PyObject* args) {
  PyObject *works, *sides_obj, *idxs_obj;
  if (!PyArg_ParseTuple(args, "OSO", &works, &sides_obj, &idxs_obj))
    return nullptr;
  const char* sides = PyBytes_AS_STRING(sides_obj);
  Py_ssize_t ncols = PyBytes_GET_SIZE(sides_obj);
  PyObject* idx_fast = PySequence_Fast(idxs_obj, "idxs must be a sequence");
  if (idx_fast == nullptr) return nullptr;
  if (PySequence_Fast_GET_SIZE(idx_fast) != ncols) {
    Py_DECREF(idx_fast);
    PyErr_SetString(PyExc_ValueError, "sides/idxs length mismatch");
    return nullptr;
  }
  std::vector<Py_ssize_t> idxs((size_t)ncols);
  for (Py_ssize_t j = 0; j < ncols; j++) {
    idxs[(size_t)j] =
        PyLong_AsSsize_t(PySequence_Fast_GET_ITEM(idx_fast, j));
    if (idxs[(size_t)j] < 0) {  // conversion error OR a negative index —
      // both invalid (unchecked GET_ITEM macros below must never see <0)
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "idxs must be non-negative");
      Py_DECREF(idx_fast);
      return nullptr;
    }
  }
  PyObject* works_fast = PySequence_Fast(works, "works must be a sequence");
  if (works_fast == nullptr) {
    Py_DECREF(idx_fast);
    return nullptr;
  }
  Py_ssize_t nwork = PySequence_Fast_GET_SIZE(works_fast);
  // total pair count up front (lens only) so the key buffer and column
  // lists are allocated exactly once
  Py_ssize_t total = 0;
  bool fail = false;
  for (Py_ssize_t w = 0; !fail && w < nwork; w++) {
    PyObject* pair = PySequence_Fast_GET_ITEM(works_fast, w);
    if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) < 2 ||
        !PyDict_Check(PyTuple_GET_ITEM(pair, 1))) {
      PyErr_SetString(PyExc_TypeError,
                      "work item must be (delta, bucket[, swapped])");
      fail = true;
      break;
    }
    Py_ssize_t nld = PySequence_Size(PyTuple_GET_ITEM(pair, 0));
    if (nld < 0) { fail = true; break; }
    total += nld * PyDict_GET_SIZE(PyTuple_GET_ITEM(pair, 1));
  }
  PyObject* keys_buf =
      fail ? nullptr : PyByteArray_FromStringAndSize(nullptr, total * 8);
  PyObject* diffs_buf =
      fail ? nullptr : PyByteArray_FromStringAndSize(nullptr, total * 8);
  PyObject* cols = fail || keys_buf == nullptr || diffs_buf == nullptr
                       ? nullptr
                       : PyTuple_New(ncols);
  fail = fail || keys_buf == nullptr || diffs_buf == nullptr ||
         cols == nullptr;
  for (Py_ssize_t j = 0; !fail && j < ncols; j++) {
    PyObject* lst = PyList_New(total);
    if (lst == nullptr) { fail = true; break; }
    PyTuple_SET_ITEM(cols, j, lst);
  }
  uint64_t* keys_out =
      fail ? nullptr
           : reinterpret_cast<uint64_t*>(PyByteArray_AS_STRING(keys_buf));
  int64_t* diffs_out =
      fail ? nullptr
           : reinterpret_cast<int64_t*>(PyByteArray_AS_STRING(diffs_buf));
  std::string scratch;
  std::vector<uint64_t> rk_hash;  // per-work rbucket hashes (reused rows)
  Py_ssize_t outpos = 0;
  for (Py_ssize_t w = 0; !fail && w < nwork; w++) {
    PyObject* pair = PySequence_Fast_GET_ITEM(works_fast, w);
    PyObject* ld = PyTuple_GET_ITEM(pair, 0);
    PyObject* rbucket = PyTuple_GET_ITEM(pair, 1);
    // swapped: the delta is the RIGHT side crossed against a LEFT
    // bucket (L x dR term) — output-column sourcing and the two key-
    // hash salts flip, everything else is symmetric
    int swapped = 0;
    if (PyTuple_GET_SIZE(pair) >= 3) {
      swapped = PyObject_IsTrue(PyTuple_GET_ITEM(pair, 2));
      if (swapped < 0) { fail = true; break; }
    }
    PyObject* ld_fast = PySequence_Fast(ld, "ld must be a sequence");
    if (ld_fast == nullptr) { fail = true; break; }
    Py_ssize_t nld = PySequence_Fast_GET_SIZE(ld_fast);
    Py_ssize_t nrb = PyDict_GET_SIZE(rbucket);
    // hash each bucket key once per work item (shared across delta rows);
    // the left-position hash carries the column-combine prime so the pair
    // key is a plain XOR either way
    rk_hash.resize((size_t)nrb);
    {
      PyObject *rk, *rrow;
      Py_ssize_t pos = 0, ri = 0;
      while (PyDict_Next(rbucket, &pos, &rk, &rrow)) {
        uint64_t h;
        if (!hash_value_u64(rk, scratch, &h)) {
          PyErr_SetString(PyExc_TypeError, "unhashable join row key");
          fail = true;
          break;
        }
        rk_hash[(size_t)ri++] =
            swapped ? splitmix_salt(h, kSeqSalt) * kColPrime
                    : splitmix_salt(h, kSeqSalt * 2);
      }
    }
    for (Py_ssize_t i = 0; !fail && i < nld; i++) {
      PyObject* entry = PySequence_Fast_GET_ITEM(ld_fast, i);
      if (!PyTuple_Check(entry) || PyTuple_GET_SIZE(entry) < 2 ||
          !PyTuple_Check(PyTuple_GET_ITEM(entry, 1))) {
        PyErr_SetString(PyExc_TypeError, "ld entry must be (key, row, diff)");
        fail = true;
        break;
      }
      PyObject* lk = PyTuple_GET_ITEM(entry, 0);
      PyObject* lrow = PyTuple_GET_ITEM(entry, 1);
      long long weight = 1;
      if (PyTuple_GET_SIZE(entry) >= 3) {
        weight = PyLong_AsLongLong(PyTuple_GET_ITEM(entry, 2));
        if (PyErr_Occurred()) { fail = true; break; }
      }
      uint64_t lh;
      if (!hash_value_u64(lk, scratch, &lh)) {
        PyErr_SetString(PyExc_TypeError, "unhashable join row key");
        fail = true;
        break;
      }
      lh = swapped ? splitmix_salt(lh, kSeqSalt * 2)
                   : splitmix_salt(lh, kSeqSalt) * kColPrime;
      PyObject *rk, *rrow;
      Py_ssize_t pos = 0, ri = 0;
      while (!fail && PyDict_Next(rbucket, &pos, &rk, &rrow)) {
        if (!PyTuple_Check(rrow)) {
          PyErr_SetString(PyExc_TypeError, "rrow must be a tuple");
          fail = true;
          break;
        }
        for (Py_ssize_t j = 0; j < ncols; j++) {
          PyObject* src = ((sides[j] != 0) != (swapped != 0)) ? lrow : rrow;
          Py_ssize_t k = idxs[(size_t)j];
          if (k >= PyTuple_GET_SIZE(src)) {
            PyErr_SetString(PyExc_IndexError, "row index out of range");
            fail = true;
            break;
          }
          PyObject* v = PyTuple_GET_ITEM(src, k);
          Py_INCREF(v);
          PyList_SET_ITEM(PyTuple_GET_ITEM(cols, j), outpos, v);
        }
        if (fail) break;
        keys_out[outpos] = lh ^ rk_hash[(size_t)ri++];
        diffs_out[outpos] = (int64_t)weight;
        outpos++;
      }
    }
    Py_DECREF(ld_fast);
  }
  Py_DECREF(works_fast);
  Py_DECREF(idx_fast);
  if (!fail && outpos != total) {
    PyErr_SetString(PyExc_RuntimeError, "join cross emitted short");
    fail = true;
  }
  if (fail) {
    Py_XDECREF(keys_buf);
    Py_XDECREF(diffs_buf);
    Py_XDECREF(cols);
    return nullptr;
  }
  PyObject* result = PyTuple_Pack(3, cols, keys_buf, diffs_buf);
  Py_DECREF(cols);
  Py_DECREF(keys_buf);
  Py_DECREF(diffs_buf);
  return result;
}

// batch_rows_split(rows, ncols, keys_u64_buf, diffs_i64_buf)
//   rows: list of (key:int, row:tuple, diff:int). Fills the key/diff
//   buffers and returns a tuple of ncols value lists — the SoA transpose
//   behind Batch.from_rows, one C pass instead of n*ncols Python steps.
static PyObject* py_batch_rows_split(PyObject*, PyObject* args) {
  PyObject* rows;
  Py_ssize_t ncols;
  Py_buffer keys_buf, diffs_buf;
  if (!PyArg_ParseTuple(args, "Onw*w*", &rows, &ncols, &keys_buf,
                        &diffs_buf))
    return nullptr;
  PyObject* fast = PySequence_Fast(rows, "rows must be a sequence");
  if (fast == nullptr) {
    PyBuffer_Release(&keys_buf);
    PyBuffer_Release(&diffs_buf);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  bool fail = false;
  if ((Py_ssize_t)keys_buf.len < n * 8 ||
      (Py_ssize_t)diffs_buf.len < n * 8) {
    PyErr_SetString(PyExc_ValueError, "key/diff buffer too small");
    fail = true;
  }
  uint64_t* keys = reinterpret_cast<uint64_t*>(keys_buf.buf);
  int64_t* diffs = reinterpret_cast<int64_t*>(diffs_buf.buf);
  PyObject* cols = fail ? nullptr : PyTuple_New(ncols);
  if (cols == nullptr) fail = true;
  for (Py_ssize_t j = 0; !fail && j < ncols; j++) {
    PyObject* lst = PyList_New(n);
    if (lst == nullptr) { fail = true; break; }
    PyTuple_SET_ITEM(cols, j, lst);
  }
  for (Py_ssize_t i = 0; !fail && i < n; i++) {
    PyObject* triple = PySequence_Fast_GET_ITEM(fast, i);
    if (!PyTuple_Check(triple) || PyTuple_GET_SIZE(triple) != 3) {
      PyErr_SetString(PyExc_TypeError, "row entry must be (key, row, diff)");
      fail = true;
      break;
    }
    PyObject* key = PyTuple_GET_ITEM(triple, 0);
    PyObject* row = PyTuple_GET_ITEM(triple, 1);
    PyObject* diff = PyTuple_GET_ITEM(triple, 2);
    keys[i] = PyLong_AsUnsignedLongLongMask(key);
    int64_t d = PyLong_AsLongLong(diff);
    if (PyErr_Occurred()) { fail = true; break; }
    diffs[i] = d;
    if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) != ncols) {
      PyErr_SetString(PyExc_TypeError, "row tuple arity mismatch");
      fail = true;
      break;
    }
    for (Py_ssize_t j = 0; j < ncols; j++) {
      PyObject* v = PyTuple_GET_ITEM(row, j);
      Py_INCREF(v);
      PyList_SET_ITEM(PyTuple_GET_ITEM(cols, j), i, v);
    }
  }
  Py_DECREF(fast);
  PyBuffer_Release(&keys_buf);
  PyBuffer_Release(&diffs_buf);
  if (fail) {
    Py_XDECREF(cols);
    return nullptr;
  }
  return cols;
}

// Ensure deltas[jk] exists and return it (borrowed); nullptr on error.
static PyObject* join_delta_list(PyObject* deltas, PyObject* jk) {
  PyObject* dl = PyDict_GetItemWithError(deltas, jk);  // borrowed
  if (dl == nullptr) {
    if (PyErr_Occurred()) return nullptr;
    dl = PyList_New(0);
    if (dl == nullptr || PyDict_SetItem(deltas, jk, dl) < 0) {
      Py_XDECREF(dl);
      return nullptr;
    }
    Py_DECREF(dl);  // deltas holds it; borrowed ref stays valid
  }
  return dl;
}

// undo[jk].append((key, old_row_or_None)) — the per-mutation undo log the
// recompute path replays in reverse to reconstruct pre-batch buckets
// (replacing the old always-materialized emitted-pairs cache).
static int join_log_undo(PyObject* undo, PyObject* jk, PyObject* key,
                         PyObject* old) {
  PyObject* lst = join_delta_list(undo, jk);  // borrowed ensure-list
  if (lst == nullptr) return -1;
  PyObject* pairt = PyTuple_Pack(2, key, old ? old : Py_None);
  if (pairt == nullptr) return -1;
  int rc = PyList_Append(lst, pairt);
  Py_DECREF(pairt);
  return rc;
}

// Remove `key` from state[jk]'s bucket (dropping an emptied bucket),
// logging the removed row to the undo log. Returns 0 ok, -1 error.
static int join_evict(PyObject* state, PyObject* jk, PyObject* key,
                      PyObject* undo) {
  PyObject* bucket = PyDict_GetItemWithError(state, jk);  // borrowed
  if (bucket == nullptr) return PyErr_Occurred() ? -1 : 0;
  PyObject* old = PyDict_GetItemWithError(bucket, key);  // borrowed
  if (old == nullptr) return PyErr_Occurred() ? -1 : 0;
  if (join_log_undo(undo, jk, key, old) < 0) return -1;
  if (PyDict_DelItem(bucket, key) < 0) return -1;
  if (PyDict_GET_SIZE(bucket) == 0 && PyDict_DelItem(state, jk) < 0)
    return -1;
  return 0;
}

// join_apply_side(state, key2jk, keys, diffs, col_lists, jk_idx,
//                 error_sentinel)
//   state: dict jk -> {rowkey: rowtuple}; key2jk: dict rowkey -> its
//   current jk (stale-bucket eviction for key-changing raw
//   re-deliveries); keys/diffs: lists; col_lists: tuple of per-column
//   value lists (the SoA batch); jk_idx: which column is the (single)
//   join key. Builds each row tuple once, applies the delta to the
//   bucket state, and groups deltas per jk — the whole Python
//   _side_deltas pass in one C loop. Every bucket mutation is logged to
//   an undo dict (jk -> [(key, old_row|None), ...]) so the recompute
//   path can rebuild pre-batch buckets. Returns (deltas_dict,
//   dirty_list, undo_dict, n_errors).
static PyObject* py_join_apply_side(PyObject*, PyObject* args) {
  PyObject *state, *key2jk, *keys, *diffs, *col_lists, *sentinel;
  Py_ssize_t jk_idx;
  if (!PyArg_ParseTuple(args, "O!O!OOO!nO", &PyDict_Type, &state,
                        &PyDict_Type, &key2jk, &keys, &diffs,
                        &PyTuple_Type, &col_lists, &jk_idx, &sentinel))
    return nullptr;
  PyObject* keys_fast = PySequence_Fast(keys, "keys");
  PyObject* diffs_fast = PySequence_Fast(diffs, "diffs");
  if (keys_fast == nullptr || diffs_fast == nullptr) {
    Py_XDECREF(keys_fast);
    Py_XDECREF(diffs_fast);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(keys_fast);
  Py_ssize_t ncols = PyTuple_GET_SIZE(col_lists);
  std::vector<PyObject**> col_items((size_t)ncols);
  bool fail = PySequence_Fast_GET_SIZE(diffs_fast) != n || jk_idx < 0 ||
              jk_idx >= ncols;
  if (fail) PyErr_SetString(PyExc_ValueError, "bad apply_side arguments");
  for (Py_ssize_t j = 0; !fail && j < ncols; j++) {
    PyObject* col = PyTuple_GET_ITEM(col_lists, j);
    if (!PyList_Check(col) || PyList_GET_SIZE(col) != n) {
      PyErr_SetString(PyExc_TypeError, "columns must be n-length lists");
      fail = true;
      break;
    }
    col_items[(size_t)j] = ((PyListObject*)col)->ob_item;
  }
  PyObject* deltas = fail ? nullptr : PyDict_New();
  PyObject* dirty = fail ? nullptr : PyList_New(0);
  PyObject* undo = fail ? nullptr : PyDict_New();
  Py_ssize_t n_err = 0;
  if (deltas == nullptr || dirty == nullptr || undo == nullptr) fail = true;
  for (Py_ssize_t i = 0; !fail && i < n; i++) {
    PyObject* jk = col_items[(size_t)jk_idx][i];
    if (jk == sentinel) { n_err++; continue; }
    PyObject* key = PySequence_Fast_GET_ITEM(keys_fast, i);
    long long d = PyLong_AsLongLong(PySequence_Fast_GET_ITEM(diffs_fast, i));
    if (PyErr_Occurred()) { fail = true; break; }
    PyObject* row = PyTuple_New(ncols);
    if (row == nullptr) { fail = true; break; }
    for (Py_ssize_t j = 0; j < ncols; j++) {
      PyObject* v = col_items[(size_t)j][i];
      Py_INCREF(v);
      PyTuple_SET_ITEM(row, j, v);
    }
    PyObject* old = PyDict_GetItemWithError(key2jk, key);  // borrowed
    if (old == nullptr && PyErr_Occurred()) {
      Py_DECREF(row);
      fail = true;
      break;
    }
    int moved = 0;  // row key is live under a DIFFERENT jk
    if (old != nullptr && old != jk) {
      moved = PyObject_RichCompareBool(old, jk, Py_EQ);
      if (moved < 0) { Py_DECREF(row); fail = true; break; }
      moved = !moved;
    }
    // which deltas[...] list this triple lands in: the delivered jk for
    // inserts, the row's ACTUAL bucket for retractions (a retraction
    // carrying a stale join key must drain from where the row lives)
    PyObject* grp;
    if (d > 0) {
      if (moved) {
        // key-changing raw re-delivery: evict the stale row and mark
        // the old bucket for recompute (its pairs must retract)
        if (join_evict(state, old, key, undo) < 0 ||
            PyList_Append(dirty, old) < 0 ||
            join_delta_list(deltas, old) == nullptr) {
          Py_DECREF(row);
          fail = true;
          break;
        }
      }
      grp = jk;
      Py_INCREF(grp);
      PyObject* bucket = PyDict_GetItemWithError(state, jk);  // borrowed
      if (bucket == nullptr && PyErr_Occurred()) {
        Py_DECREF(grp);
        Py_DECREF(row);
        fail = true;
        break;
      }
      PyObject* prev = nullptr;  // row stored under this key pre-insert
      if (bucket == nullptr) {
        bucket = PyDict_New();
        if (bucket == nullptr ||
            PyDict_SetItem(state, jk, bucket) < 0) {
          Py_XDECREF(bucket);
          Py_DECREF(grp);
          Py_DECREF(row);
          fail = true;
          break;
        }
        Py_DECREF(bucket);  // state holds it; borrowed ref stays valid
      } else {
        prev = PyDict_GetItemWithError(bucket, key);  // borrowed
        if (prev == nullptr && PyErr_Occurred()) {
          Py_DECREF(grp);
          Py_DECREF(row);
          fail = true;
          break;
        }
        // upsert-style re-delivery of a row key: recompute path
        if (prev != nullptr && PyList_Append(dirty, jk) < 0) {
          Py_DECREF(grp);
          Py_DECREF(row);
          fail = true;
          break;
        }
      }
      if (join_log_undo(undo, jk, key, prev) < 0 ||
          PyDict_SetItem(bucket, key, row) < 0 ||
          PyDict_SetItem(key2jk, key, jk) < 0) {
        Py_DECREF(grp);
        Py_DECREF(row);
        fail = true;
        break;
      }
    } else {
      grp = old != nullptr ? old : jk;
      Py_INCREF(grp);  // must survive the key2jk delete below
      if (old != nullptr && PyDict_DelItem(key2jk, key) < 0) {
        Py_DECREF(grp);
        Py_DECREF(row);
        fail = true;
        break;
      }
      if (join_evict(state, grp, key, undo) < 0 ||
          (moved && PyList_Append(dirty, grp) < 0)) {
        Py_DECREF(grp);
        Py_DECREF(row);
        fail = true;
        break;
      }
    }
    // deltas[grp].append((key, row, diff))
    PyObject* dl = join_delta_list(deltas, grp);
    if (dl == nullptr) {
      Py_DECREF(grp);
      Py_DECREF(row);
      fail = true;
      break;
    }
    PyObject* triple = PyTuple_New(3);
    if (triple == nullptr) {
      Py_DECREF(grp);
      Py_DECREF(row);
      fail = true;
      break;
    }
    Py_INCREF(key);
    PyTuple_SET_ITEM(triple, 0, key);
    PyTuple_SET_ITEM(triple, 1, row);  // steals the row ref
    PyObject* dobj = PyLong_FromLongLong(d);
    if (dobj == nullptr) {
      Py_DECREF(grp);
      Py_DECREF(triple);
      fail = true;
      break;
    }
    PyTuple_SET_ITEM(triple, 2, dobj);
    if (PyList_Append(dl, triple) < 0) fail = true;
    Py_DECREF(triple);
    Py_DECREF(grp);
  }
  Py_DECREF(keys_fast);
  Py_DECREF(diffs_fast);
  if (fail) {
    Py_XDECREF(deltas);
    Py_XDECREF(dirty);
    Py_XDECREF(undo);
    return nullptr;
  }
  PyObject* nerr = PyLong_FromSsize_t(n_err);
  PyObject* out =
      nerr ? PyTuple_Pack(4, deltas, dirty, undo, nerr) : nullptr;
  Py_DECREF(deltas);
  Py_DECREF(dirty);
  Py_DECREF(undo);
  Py_XDECREF(nerr);
  return out;
}

static PyMethodDef methods[] = {
    {"join_apply_side", py_join_apply_side, METH_VARARGS,
     "apply one side's columnar batch to join bucket state"},
    {"join_ld_cross", py_join_ld_cross, METH_VARARGS,
     "emit dL x R cross products columnar with hashed pair output keys"},
    {"batch_rows_split", py_batch_rows_split, METH_VARARGS,
     "SoA transpose of (key, row, diff) triples"},
    {"hash_object_column", py_hash_object_column, METH_VARARGS,
     "hash a sequence of values into an n*8-byte output buffer; returns "
     "indices needing python fallback"},
    {"xxh64_digest", py_xxh64, METH_VARARGS, "xxh64 of a bytes-like"},
    {"consolidate_pairs", py_consolidate_pairs, METH_VARARGS,
     "group (key,row_hash) deltas, sum diffs, drop zeros"},
    {"split_lines", py_split_lines, METH_VARARGS,
     "newline tokenizer returning (start,end) offset pairs"},
    {"hash_tokenize", py_hash_tokenize, METH_VARARGS,
     "batch HashTokenizer: texts -> padded int32 id matrix + width"},
    {"wordpiece_load", py_wordpiece_load, METH_VARARGS,
     "register a WordPiece vocab; returns a handle"},
    {"wordpiece_free", py_wordpiece_free, METH_VARARGS,
     "release a WordPiece vocab handle"},
    {"rows_from_records", py_rows_from_records, METH_VARARGS,
     "batch record-dict -> row-tuple extraction with fast coercions"},
    {"jsonl_rows", py_jsonl_rows, METH_VARARGS,
     "one-pass jsonlines bytes -> row tuples with schema coercion"},
    {"csv_cols", py_csv_cols, METH_VARARGS,
     "one-pass CSV bytes -> per-column value lists with schema coercion"},
    {"wordpiece_tokenize", py_wordpiece_tokenize, METH_VARARGS,
     "batch WordPiece: texts -> padded int32 id matrix + width + fallbacks"},
    {"set_pointer_type", py_set_pointer_type, METH_VARARGS,
     "register the engine Pointer type"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_native",
    "pathway_tpu native host runtime (hashing, consolidation, tokenizing)",
    -1, methods};

PyMODINIT_FUNC PyInit__native(void) { return PyModule_Create(&moduledef); }
