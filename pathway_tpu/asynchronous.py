"""DEPRECATED — content moved to ``pathway_tpu.udfs``.

Reference parity: ``python/pathway/asynchronous.py`` (deprecated alias
module forwarding to ``pathway.internals.udfs``). Kept so code written
against the old import path keeps working with a warning.
"""

from warnings import warn

from pathway_tpu.internals import udfs


def __getattr__(name):
    warn(
        "pathway_tpu.asynchronous is deprecated; use pathway_tpu.udfs.",
        DeprecationWarning,
        stacklevel=2,
    )
    return getattr(udfs, name)
