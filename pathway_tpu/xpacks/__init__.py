"""pathway_tpu.xpacks — extension packs (LLM/RAG toolkit, enterprise connectors).

Parity with reference ``python/pathway/xpacks/``.
"""

from pathway_tpu.xpacks import connectors

__all__ = ["connectors"]
