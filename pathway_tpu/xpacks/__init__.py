"""pathway_tpu.xpacks — extension packs (LLM/RAG toolkit).

Parity with reference ``python/pathway/xpacks/``.
"""
