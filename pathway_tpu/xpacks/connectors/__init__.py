"""Enterprise connectors xpack (reference ``python/pathway/xpacks/connectors``)."""

from pathway_tpu.xpacks.connectors import sharepoint

__all__ = ["sharepoint"]
