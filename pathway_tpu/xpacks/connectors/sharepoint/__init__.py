"""SharePoint reader (reference
``python/pathway/xpacks/connectors/sharepoint/__init__.py:255``, licensed):
polls a SharePoint document library over the Office365 REST API, emitting
binary ``data`` rows with change/deletion tracking — built on the same
object-store poller as ``pw.io.gdrive`` / ``pw.io.pyfilesystem``."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._object_store import ObjectStoreConnector


class _SharePointProvider:
    """office365-rest-python-client wrapper; duck-typed ``_client`` with
    ``list_files(root_path, recursive)`` / ``download(server_relative_url)``
    is injectable for offline tests."""

    def __init__(self, client, root_path: str, recursive: bool,
                 object_size_limit: int | None):
        self.client = client
        self.root_path = root_path
        self.recursive = recursive
        self.object_size_limit = object_size_limit

    def list_objects(self) -> dict[str, tuple[Any, dict]]:
        import time as time_mod

        listing: dict[str, tuple[Any, dict]] = {}
        for meta in self.client.list_files(self.root_path, self.recursive):
            size = int(meta.get("size", 0) or 0)
            if self.object_size_limit is not None and size > self.object_size_limit:
                continue
            version = (meta.get("modified_at"), size)
            meta = dict(meta)
            # reference metadata shape (_SharePointEntryMeta.as_dict +
            # url property, sharepoint/__init__.py:29-76)
            base = meta.get("base_url")
            if base and "url" not in meta:
                meta["url"] = f"{base}{meta['path']}"
            meta["seen_at"] = int(time_mod.time())
            meta["status"] = "downloaded"
            listing[meta["path"]] = (version, meta)
        return listing

    def fetch(self, object_id: str) -> bytes:
        return self.client.download(object_id)


def _office365_client(url: str, tenant: str, client_id: str, cert_path: str,
                      thumbprint: str):
    try:
        from office365.sharepoint.client_context import ClientContext  # type: ignore
    except ImportError as exc:
        raise ImportError(
            "pw.xpacks.connectors.sharepoint.read needs "
            "office365-rest-python-client (or pass _client=...)"
        ) from exc

    ctx = ClientContext(url).with_client_certificate(
        tenant, client_id, thumbprint, cert_path
    )

    class _Client:
        def list_files(self, root_path, recursive):
            folder = ctx.web.get_folder_by_server_relative_url(root_path)
            files = folder.get_files(recursive).execute_query()
            return [
                {
                    "path": f.serverRelativeUrl,
                    "name": f.name,
                    "modified_at": str(f.time_last_modified),
                    "size": f.length,
                }
                for f in files
            ]

        def download(self, server_relative_url):
            import io

            buf = io.BytesIO()
            ctx.web.get_file_by_server_relative_url(
                server_relative_url
            ).download(buf).execute_query()
            return buf.getvalue()

    return _Client()


def read(
    url: str = "",
    *,
    tenant: str = "",
    client_id: str = "",
    cert_path: str = "",
    thumbprint: str = "",
    root_path: str = "",
    mode: str = "streaming",
    recursive: bool = True,
    object_size_limit: int | None = None,
    with_metadata: bool = False,
    refresh_interval: int = 30,
    max_failed_attempts_in_row: int | None = 8,
    persistent_id: str | None = None,
    _client=None,
) -> Table:
    """Read a SharePoint document library as binary rows. Transient scan
    failures retry up to ``max_failed_attempts_in_row`` consecutive polls
    before propagating (reference behavior). With ``persistent_id``,
    downloads are cached by URI for deterministic replay."""
    client = _client or _office365_client(url, tenant, client_id, cert_path, thumbprint)
    schema = schema_mod.schema_from_types(data=bytes)
    if with_metadata:
        schema = schema | schema_mod.schema_from_types(_metadata=dt.JSON)
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name=f"sharepoint({root_path})")
    provider = _SharePointProvider(client, root_path, recursive, object_size_limit)
    conn = ObjectStoreConnector(
        node, provider, mode, with_metadata, float(refresh_interval),
        max_failed_attempts_in_row=max_failed_attempts_in_row,
    )
    G.register_connector(conn)
    if persistent_id is not None:
        from pathway_tpu.persistence import register_persistent_source

        register_persistent_source(persistent_id, conn)
    return Table(node, schema, Universe())
