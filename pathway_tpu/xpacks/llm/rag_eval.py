"""RAG answer-quality evaluation harness.

Reference parity: ``integration_tests/rag_evals`` — ``run_eval_experiment``
(experiment.py:23-102, accuracy = mean per-question similarity) and the CI
gate ``eval_accuracy >= MIN_ACCURACY`` with ``MIN_ACCURACY = 0.6``
(test_eval.py:133,153). The reference scores answers with a RAGAS-style
LLM judge against a labeled CSV dataset served over its REST app; this
harness is its zero-network equivalent: the labeled QA set is synthesized,
answers come from the local TPU stack (BM25/KNN retrieval + the TPU
decoder), and scoring is normalized exact/contains accuracy — deterministic
and runnable in CI without any external service.

The synthesized task is retrieval-grounded by construction: every question
names an entity whose answer code exists ONLY in that entity's document,
so a correct answer requires the indexer to return the right document AND
the generator to ground its answer in the retrieved context. Retrieval
misses or hallucinated codes both score 0.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = [
    "RagSample",
    "generate_qa_dataset",
    "docs_table",
    "queries_table",
    "normalize_answer",
    "score_answer",
    "run_rag_eval",
]


@dataclass(frozen=True)
class RagSample:
    """One labeled QA example: the document holding the fact, its metadata
    path, the question, and the expected answer."""

    doc: str
    path: str
    question: str
    answer: str


_LETTERS = "abcdefghijklmnopqrstuvwxyz"
_DIGITS = "0123456789"


def generate_qa_dataset(n: int, seed: int = 0) -> list[RagSample]:
    """Synthesize ``n`` single-fact documents with unique entity names and
    unique numeric answer codes (the reference ships a hand-labeled CSV,
    ``integration_tests/rag_evals/dataset``; a synthesized set keeps the
    gate hermetic). Names are letters-only and codes digits-only so an
    answer can never accidentally appear in another document's text or in
    any path string."""
    import numpy as np

    rng = np.random.default_rng(seed)
    names: set[str] = set()
    codes: set[str] = set()
    samples: list[RagSample] = []
    while len(samples) < n:
        name = "".join(rng.choice(list(_LETTERS), 5))
        code = "".join(rng.choice(list(_DIGITS), 4))
        if name in names or code in codes:
            continue
        names.add(name)
        codes.add(code)
        samples.append(
            RagSample(
                doc=f"access code for {name} is {code}",
                path=f"/{name}.txt",
                question=f"what is the access code for {name}",
                answer=code,
            )
        )
    return samples


def docs_table(samples: list[RagSample]):
    """DocumentStore-shaped table (``data`` + ``_metadata``) for the set."""
    import pandas as pd

    import pathway_tpu as pw
    from pathway_tpu.internals.json import Json

    return pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "data": [s.doc for s in samples],
                "_metadata": [
                    Json({"path": s.path, "modified_at": i})
                    for i, s in enumerate(samples)
                ],
            }
        )
    )


def queries_table(samples: list[RagSample]):
    """pw_ai-shaped query table for ``BaseRAGQuestionAnswerer.answer_query``."""
    import pandas as pd

    import pathway_tpu as pw

    n = len(samples)
    return pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "prompt": [s.question for s in samples],
                "filters": [None] * n,
                "model": [None] * n,
                "return_context_docs": [False] * n,
            }
        )
    )


def normalize_answer(text: str) -> str:
    """Lowercase, collapse whitespace, strip punctuation at the edges —
    the usual exact-match normalization for extractive QA scoring."""
    text = re.sub(r"\s+", " ", str(text)).strip().lower()
    return text.strip(".,;:!?\"'")


def score_answer(response: str, expected: str) -> tuple[bool, bool]:
    """(exact, contains) after normalization. ``contains`` is the headline
    metric: generated answers legitimately carry surrounding words."""
    got = normalize_answer(response)
    want = normalize_answer(expected)
    return got == want, want in got


def run_rag_eval(qa, samples: list[RagSample]) -> dict:
    """Run every sample's question through ``qa.answer_query`` (the full
    pipeline: retrieve -> prompt-assemble -> generate) and score.

    Returns ``{"accuracy_exact", "accuracy_contains", "n", "results"}``
    where ``results`` is per-sample ``(question, response, expected,
    contains)``. The reference's experiment writes the same per-question
    table plus the mean to MLflow (experiment.py:96-102)."""
    from pathway_tpu.internals.json import unwrap_json
    from pathway_tpu.internals.run import capture_table

    queries = queries_table(samples)
    by_question = {s.question: s for s in samples}
    q_cap = capture_table(queries)
    res = qa.answer_query(queries)
    cap = capture_table(res)
    q_cols = {c: i for i, c in enumerate(q_cap.column_names)}
    cols = {c: i for i, c in enumerate(cap.column_names)}
    q_rows = dict(q_cap.state.rows)
    results = []
    n_exact = n_contains = 0
    for key, row in dict(cap.state.rows).items():
        q_row = q_rows.get(key)
        question = q_row[q_cols["prompt"]] if q_row is not None else None
        sample = by_question.get(question)
        if sample is None:
            continue
        result = unwrap_json(row[cols["result"]])
        response = (
            result.get("response") if isinstance(result, dict) else result
        )
        exact, contains = score_answer(str(response), sample.answer)
        n_exact += exact
        n_contains += contains
        results.append((question, str(response), sample.answer, contains))
    n = len(results)
    return {
        "accuracy_exact": n_exact / n if n else 0.0,
        "accuracy_contains": n_contains / n if n else 0.0,
        "n": n,
        "results": results,
    }
