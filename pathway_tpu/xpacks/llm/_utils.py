"""Shared helpers for the LLM xpack (reference ``xpacks/llm/_utils.py``)."""

from __future__ import annotations

import logging
from typing import Any, TypedDict

from pathway_tpu.internals.json import Json

logger = logging.getLogger(__name__)


class Doc(TypedDict, total=False):
    """A retrieved document chunk: ``text`` plus arbitrary metadata."""

    text: str
    metadata: dict
    dist: float


def _coerce_sync(fun):
    """Run an async callable synchronously if needed."""
    import asyncio
    import inspect

    if inspect.iscoroutinefunction(fun):
        def wrapper(*args, **kwargs):
            return asyncio.run(fun(*args, **kwargs))

        return wrapper
    return fun


def unwrap_udf(udf_or_callable):
    """Return the raw callable behind a UDF (or the callable itself)."""
    wrapped = getattr(udf_or_callable, "__wrapped__", None)
    if wrapped is not None and not isinstance(wrapped, type):
        return wrapped
    return udf_or_callable


def _unwrap_json(value: Any) -> Any:
    if isinstance(value, Json):
        return value.value
    return value


def _to_dict(doc: Any) -> dict:
    doc = _unwrap_json(doc)
    if isinstance(doc, dict):
        return {k: _unwrap_json(v) for k, v in doc.items()}
    return {"text": str(doc)}


def combine_metadata(docs: list[Any]) -> list[dict]:
    return [_to_dict(d) for d in docs]


def post_json(url: str, payload: dict, headers: dict | None = None,
              timeout: float | None = None):
    """POST JSON, return decoded JSON response — the one HTTP helper shared
    by VectorStoreClient and RAGClient."""
    import json
    import urllib.request

    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read().decode())


def get_func_arg_names(func):
    """Positional/keyword parameter names of ``func`` (reference
    ``xpacks/llm/_utils.py:74``); *args/**kwargs placeholders excluded."""
    import inspect

    kinds = (
        inspect.Parameter.POSITIONAL_ONLY,
        inspect.Parameter.POSITIONAL_OR_KEYWORD,
        inspect.Parameter.KEYWORD_ONLY,
    )
    return [
        p.name
        for p in inspect.signature(func).parameters.values()
        if p.kind in kinds
    ]
