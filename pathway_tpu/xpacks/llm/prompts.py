"""Prompt templates for RAG question answering (reference
``xpacks/llm/prompts.py`` — templates re-written, same roles: short QA over
retrieved context, strict-JSON citation variant, summarization).
"""

from __future__ import annotations

import functools
import re
from abc import ABC, abstractmethod

import pathway_tpu as pw
from pathway_tpu.internals.udfs import udf as pw_udf

BASE_PROMPT_TEMPLATE = (
    "Answer the question using only the context below. "
    "Reply with a short answer; if the context does not contain the answer, "
    "reply exactly `No information found.`\n\n"
    "Context:\n{context}\n\nQuestion: {query}\nAnswer:"
)

STRICT_JSON_PROMPT_TEMPLATE = (
    "You answer questions from provided context documents only.\n"
    "Respond with a single JSON object: "
    '{{"answer": "<short answer or `No information found.`>"}}.\n\n'
    "Context:\n{context}\n\nQuestion: {query}\nJSON:"
)

SUMMARIZE_TEMPLATE = (
    "Summarize the following texts into one concise paragraph, keeping the "
    "key facts:\n\n{text}\n\nSummary:"
)


@pw.udf
def prompt_qa(query: str, context: str) -> str:
    """Build the default QA prompt (reference ``prompt_qa``)."""
    return BASE_PROMPT_TEMPLATE.format(context=context, query=query)


@pw.udf
def prompt_short_qa(query: str, context: str) -> str:
    return (
        "Give the shortest possible factual answer (a few words) based only "
        f"on this context:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


@pw.udf
def prompt_citing_qa(query: str, context: str) -> str:
    return (
        "Answer from the context and cite the source file of each fact in "
        f"brackets.\n\nContext:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


@pw.udf
def prompt_summarize(text_list: list[str]) -> str:
    return SUMMARIZE_TEMPLATE.format(text="\n\n".join(text_list))


@pw.udf
def prompt_query_rewrite_hyde(query: str) -> str:
    return (
        "Write a short hypothetical passage that would answer the question "
        f"below (used for retrieval only).\nQuestion: {query}\nPassage:"
    )


@pw.udf
def prompt_query_rewrite(query: str) -> str:
    return (
        "Rewrite the user question as a concise search query, keeping all "
        f"named entities.\nQuestion: {query}\nSearch query:"
    )


# ---------------------------------------------------------------------------
# prompt template classes (reference ``prompts.py:11-99``; implemented
# without pydantic — validation happens in __init__)


class BasePromptTemplate(ABC):
    """A prompt template that can be instantiated as a UDF
    (reference ``prompts.py:11``)."""

    @abstractmethod
    def as_udf(self, **kwargs): ...


class FunctionPromptTemplate(BasePromptTemplate):
    """Wraps a callable or UDF as a prompt template
    (reference ``prompts.py:19``)."""

    def __init__(self, function_template=None, **kwargs):
        if function_template is None:
            function_template = kwargs.pop("template", None)
        if function_template is None:
            raise ValueError("function_template is required")
        self.function_template = function_template

    def as_udf(self, **kwargs):
        from pathway_tpu.internals.udfs import UDF

        if isinstance(self.function_template, UDF):
            return self.function_template
        return pw_udf(functools.partial(self.function_template, **kwargs))


class StringPromptTemplate(BasePromptTemplate):
    """A ``str.format`` template over ``context``/``query`` columns
    (reference ``prompts.py:34``)."""

    def __init__(self, template: str):
        self.template = template

    def format(self, **kwargs) -> str:
        return self.template.format(**kwargs)

    def as_udf(self, **kwargs):
        def udf_formatter(context: str, query: str) -> str:
            return self.format(query=query, context=context, **kwargs)

        return pw_udf(udf_formatter)


class RAGPromptTemplate(StringPromptTemplate):
    """StringPromptTemplate validated to carry exactly ``{context}`` and
    ``{query}`` placeholders (reference ``prompts.py:61``)."""

    def __init__(self, template: str):
        if "{context}" not in template or "{query}" not in template:
            raise ValueError(
                "Template must contain `{context}` and `{query}` placeholders."
            )
        try:
            template.format(context=" ", query=" ")
        except KeyError:
            raise ValueError(
                "RAG prompt template expects `context` and `query` placeholders only."
            )
        super().__init__(template)


class RAGFunctionPromptTemplate(FunctionPromptTemplate):
    """FunctionPromptTemplate validated to accept context/query kwargs
    (reference ``prompts.py:79``)."""

    def __init__(self, function_template=None, **kwargs):
        super().__init__(function_template, **kwargs)
        from pathway_tpu.internals.udfs import UDF

        fn = (
            self.function_template.__wrapped__
            if isinstance(self.function_template, UDF)
            else self.function_template
        )
        import inspect

        try:
            inspect.signature(fn).bind(query=" ", context=" ")
        except TypeError as e:
            raise ValueError(
                "RAG prompt template expects `context` and `query` placeholders "
                "only.\n" + str(e)
            )


def prompt_qa_geometric_rag(
    query: str,
    docs,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
    strict_prompt: bool = False,
) -> str:
    """Citation-style QA prompt over numbered sources (reference
    ``prompts.py:194``); ``strict_prompt`` requests parsable-JSON answers
    for local models."""
    pieces = []
    for i, doc in enumerate(docs, 1):
        text = doc if isinstance(doc, str) else doc["text"]
        pieces.append(f"Source {i}: {text}")
    context_str = "\n".join(pieces)
    if strict_prompt:
        head = (
            "Use the below articles to answer the subsequent question. If the "
            f'answer cannot be found in the articles, write "'
            f'{information_not_found_response}" Do not explain.\n'
            "ONLY RESPOND IN PARSABLE JSON WITH THE ONLY KEY `answer`.\n"
            "When referencing information from a source, cite the appropriate "
            "source(s) using their corresponding numbers. Every answer should "
            "include at least one source citation."
        )
    else:
        head = (
            "Use the below articles to answer the subsequent question. If the "
            f'answer cannot be found in the articles, write "'
            f'{information_not_found_response}" Do not answer in full '
            "sentences.\nWhen referencing information from a source, cite the "
            "appropriate source(s) using their corresponding numbers. Every "
            "answer should include at least one source citation."
        )
    return (
        f"{head}\n{additional_rules}\n"
        f"Sources:\n{context_str}\n"
        f"Query: {query}\nAnswer:"
    )


def parse_cited_response(response_text: str, docs):
    """Split a cited answer into (clean_text, cited_docs); citations are
    ``[n]`` markers resolved against ``docs`` (reference ``prompts.py:316``)."""
    cited_idx = sorted(
        {int(cite[1:-1]) - 1 for cite in re.findall(r"\[\d+\]", response_text)}
    )
    citations = [docs[i] for i in cited_idx if 0 <= i < len(docs)]
    clean = re.sub(r"\s*\[\d+\]", "", response_text).strip()
    return clean, citations


DEFAULT_JSON_TABLE_PARSE_PROMPT = (
    "Explain the given table in JSON format in detail. Do not skip any "
    "information in the table."
)
DEFAULT_MD_TABLE_PARSE_PROMPT = (
    "Explain the given table in markdown format in detail. Do not skip any "
    "information in the table."
)
DEFAULT_IMAGE_PARSE_PROMPT = (
    "Explain the given image in detail. List all the objects and their "
    "attributes you can see."
)
