"""Prompt templates for RAG question answering (reference
``xpacks/llm/prompts.py`` — templates re-written, same roles: short QA over
retrieved context, strict-JSON citation variant, summarization).
"""

from __future__ import annotations

import pathway_tpu as pw

BASE_PROMPT_TEMPLATE = (
    "Answer the question using only the context below. "
    "Reply with a short answer; if the context does not contain the answer, "
    "reply exactly `No information found.`\n\n"
    "Context:\n{context}\n\nQuestion: {query}\nAnswer:"
)

STRICT_JSON_PROMPT_TEMPLATE = (
    "You answer questions from provided context documents only.\n"
    "Respond with a single JSON object: "
    '{{"answer": "<short answer or `No information found.`>"}}.\n\n'
    "Context:\n{context}\n\nQuestion: {query}\nJSON:"
)

SUMMARIZE_TEMPLATE = (
    "Summarize the following texts into one concise paragraph, keeping the "
    "key facts:\n\n{text}\n\nSummary:"
)


@pw.udf
def prompt_qa(query: str, context: str) -> str:
    """Build the default QA prompt (reference ``prompt_qa``)."""
    return BASE_PROMPT_TEMPLATE.format(context=context, query=query)


@pw.udf
def prompt_short_qa(query: str, context: str) -> str:
    return (
        "Give the shortest possible factual answer (a few words) based only "
        f"on this context:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


@pw.udf
def prompt_citing_qa(query: str, context: str) -> str:
    return (
        "Answer from the context and cite the source file of each fact in "
        f"brackets.\n\nContext:\n{context}\n\nQuestion: {query}\nAnswer:"
    )


@pw.udf
def prompt_summarize(text_list: list[str]) -> str:
    return SUMMARIZE_TEMPLATE.format(text="\n\n".join(text_list))


@pw.udf
def prompt_query_rewrite_hyde(query: str) -> str:
    return (
        "Write a short hypothetical passage that would answer the question "
        f"below (used for retrieval only).\nQuestion: {query}\nPassage:"
    )


@pw.udf
def prompt_query_rewrite(query: str) -> str:
    return (
        "Rewrite the user question as a concise search query, keeping all "
        f"named entities.\nQuestion: {query}\nSearch query:"
    )
