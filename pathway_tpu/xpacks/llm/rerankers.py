"""Reranker UDFs (reference ``xpacks/llm/rerankers.py:15-345``).

``CrossEncoderReranker`` is the TPU hot path: in the reference it scores one
(query, doc) pair at a time through a torch CrossEncoder
(``rerankers.py:186-249``); here a whole engine microbatch of pairs is scored
in one jitted XLA call (``pathway_tpu.models.cross_encoder``).
"""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm.llms import BaseChat

# ruff: noqa: E501


@pw.udf
def rerank_topk_filter(
    docs: list[Any], scores: list[float], k: int = 5
) -> tuple[list[Any], list[float]]:
    """Keep the top-``k`` docs by rerank score (reference
    ``rerank_topk_filter``, rerankers.py:15)."""
    if not docs:
        return [], []
    # stable sort with original-index tie-break: the UDF declares
    # deterministic=True, so tied scores must always resolve the same way
    # (plain argsort reversed would also flip the order WITHIN ties)
    order = np.argsort(
        -np.asarray(scores, dtype=np.float64), kind="stable"
    )[:k]
    docs_sorted = [docs[i] for i in order]
    scores_sorted = [float(scores[i]) for i in order]
    return docs_sorted, scores_sorted


class CrossEncoderReranker(pw.UDF):
    """TPU-native cross-encoder reranker (reference ``CrossEncoderReranker``,
    rerankers.py:186-249). Batched: one padded XLA dispatch per microbatch."""

    def __init__(
        self,
        model_name: Any = "minilm-l6",
        *,
        max_batch_size: int | None = 512,
        cache_strategy: udfs.CacheStrategy | None = None,
        custom_kwargs: dict = {},
    ):
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            return_type=float,
        )
        from pathway_tpu.models import CrossEncoderModel, MINILM_L6, MINILM_L12

        presets = {"minilm-l6": MINILM_L6, "minilm-l12": MINILM_L12}
        if isinstance(model_name, CrossEncoderModel):
            self.model = model_name
        else:
            kwargs = dict(custom_kwargs)
            from pathway_tpu.models.checkpoint import has_checkpoint_weights

            if model_name in presets:
                kwargs.setdefault("cfg", presets[model_name])
                self.model = CrossEncoderModel(**kwargs)
            elif isinstance(model_name, str) and has_checkpoint_weights(model_name):
                # local HF cross-encoder checkpoint (ms-marco-MiniLM style)
                self.model = CrossEncoderModel.from_pretrained(
                    model_name, **kwargs
                )
            else:
                self.model = CrossEncoderModel(**kwargs)

    def __wrapped__(self, doc: list[str], query: list[str], **kwargs) -> list[float]:
        pairs = [(q or "", d or "") for q, d in zip(query, doc)]
        scores = self.model.score_batch(pairs)
        return [float(s) for s in scores]

    # two-phase protocol (UDF._call_batched): chunks of an epoch all
    # dispatch, then ONE device drain — per-chunk syncs cost a relay RTT
    def submit_batch(self, doc: list[str], query: list[str], **kwargs):
        pairs = [(q or "", d or "") for q, d in zip(query, doc)]
        return self.model.score_submit(pairs)

    def resolve_batch(self, handles) -> list[list[float]]:
        return [
            [float(s) for s in arr]
            for arr in self.model.score_resolve(handles)
        ]

    def __call__(self, doc, query, **kwargs):
        return super().__call__(doc, query, **kwargs)


class EncoderReranker(pw.UDF):
    """Bi-encoder reranker: cosine of (query, doc) embeddings (reference
    ``EncoderReranker``, rerankers.py:251-317). Batched on TPU."""

    def __init__(
        self,
        model_name: Any = "minilm-l6",
        *,
        max_batch_size: int | None = 1024,
        cache_strategy: udfs.CacheStrategy | None = None,
        custom_kwargs: dict = {},
    ):
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            return_type=float,
        )
        from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

        self.embedder = SentenceTransformerEmbedder(model_name, **custom_kwargs)

    def __wrapped__(self, doc: list[str], query: list[str], **kwargs) -> list[float]:
        model = self.embedder.model
        # embeddings are unit-norm, so dot product == cosine similarity
        q = model.embed_batch([x or "" for x in query])
        d = model.embed_batch([x or "" for x in doc])
        return [float(s) for s in np.sum(q * d, axis=1)]

    # two-phase protocol: both embed dispatches per chunk go out eagerly;
    # the single resolve drains every (query, doc) pair of the epoch
    def submit_batch(self, doc: list[str], query: list[str], **kwargs):
        model = self.embedder.model
        hq = model.embed_submit([x or "" for x in query])
        hd = model.embed_submit([x or "" for x in doc])
        return (hq, hd)

    def resolve_batch(self, handles) -> list[list[float]]:
        model = self.embedder.model
        flat = []
        for hq, hd in handles:
            flat.append(hq)
            flat.append(hd)
        arrs = model.embed_resolve(flat)
        out = []
        for i in range(0, len(arrs), 2):
            q, d = arrs[i], arrs[i + 1]
            out.append([float(s) for s in np.sum(q * d, axis=1)])
        return out


class LLMReranker(pw.UDF):
    """Ask a chat model to rate doc relevance 1-5 (reference ``LLMReranker``,
    rerankers.py:58-184)."""

    prompt_template = (
        "Rate how relevant the document is to the query on a scale 1 to 5. "
        "Reply with a single digit.\n\nQuery: {query}\n\nDocument: {doc}\n\nRating:"
    )

    def __init__(
        self,
        llm: BaseChat,
        *,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        use_logit_bias: bool | None = None,
    ):
        super().__init__(cache_strategy=cache_strategy, return_type=float)
        self.llm = llm
        self.use_logit_bias = use_logit_bias

    def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        from pathway_tpu.xpacks.llm._utils import _coerce_sync

        prompt = self.prompt_template.format(query=query, doc=doc)
        response = _coerce_sync(self.llm.__wrapped__)(
            [{"role": "user", "content": prompt}], **kwargs
        )
        digits = [c for c in str(response) if c.isdigit()]
        if not digits:
            raise ValueError(f"reranker got non-numeric response: {response!r}")
        return float(digits[0])


class FlashRankReranker(pw.UDF):
    """FlashRank listwise reranker (reference ``FlashRankReranker``,
    rerankers.py:319-345). Gated on the ``flashrank`` package."""

    def __init__(
        self,
        model_name: str = "ms-marco-TinyBERT-L-2-v2",
        *,
        cache_strategy: udfs.CacheStrategy | None = None,
        max_length: int = 512,
    ):
        super().__init__(cache_strategy=cache_strategy, return_type=float)
        try:
            from flashrank import Ranker
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "FlashRankReranker requires the `flashrank` package"
            ) from exc
        self.ranker = Ranker(model_name=model_name, max_length=max_length)

    def __wrapped__(self, doc: str, query: str) -> float:
        from flashrank import RerankRequest

        results = self.ranker.rerank(
            RerankRequest(query=query, passages=[{"text": doc}])
        )
        return float(results[0]["score"])


@pw.udf
def unwrap_doc_texts(docs: list[Any]) -> list[str]:
    """Extract text fields from retrieved doc dicts/Jsons."""
    out = []
    for d in docs or []:
        if isinstance(d, Json):
            d = d.value
        if isinstance(d, dict):
            out.append(str(d.get("text", "")))
        else:
            out.append(str(d))
    return out
