"""Reranker UDFs (reference ``xpacks/llm/rerankers.py:15-345``).

``CrossEncoderReranker`` is the TPU hot path: in the reference it scores one
(query, doc) pair at a time through a torch CrossEncoder
(``rerankers.py:186-249``); here a whole engine microbatch of pairs is scored
in one jitted XLA call (``pathway_tpu.models.cross_encoder``).
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json
from pathway_tpu.xpacks.llm.llms import BaseChat, decode_serve_error

# ruff: noqa: E501


@pw.udf
def rerank_topk_filter(
    docs: list[Any], scores: list[float], k: int = 5
) -> tuple[list[Any], list[float]]:
    """Keep the top-``k`` docs by rerank score (reference
    ``rerank_topk_filter``, rerankers.py:15).

    ``k > len(docs)`` returns ALL docs in score order (a slice past the
    end, never an error); ``k <= 0`` returns nothing. Docs beyond the
    score list carry no ranking signal and are dropped rather than
    ordered arbitrarily.
    """
    if not docs or k <= 0:
        return [], []
    docs = docs[: len(scores)]
    # stable sort with original-index tie-break: the UDF declares
    # deterministic=True, so tied scores must always resolve the same way
    # (plain argsort reversed would also flip the order WITHIN ties)
    order = np.argsort(
        -np.asarray(scores[: len(docs)], dtype=np.float64), kind="stable"
    )[:k]
    docs_sorted = [docs[i] for i in order]
    scores_sorted = [float(scores[i]) for i in order]
    return docs_sorted, scores_sorted


class CrossEncoderReranker(pw.UDF):
    """TPU-native cross-encoder reranker (reference ``CrossEncoderReranker``,
    rerankers.py:186-249). Batched: one padded XLA dispatch per microbatch."""

    def __init__(
        self,
        model_name: Any = "minilm-l6",
        *,
        max_batch_size: int | None = 512,
        cache_strategy: udfs.CacheStrategy | None = None,
        custom_kwargs: dict = {},
    ):
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            return_type=float,
        )
        from pathway_tpu.models import CrossEncoderModel, MINILM_L6, MINILM_L12

        presets = {"minilm-l6": MINILM_L6, "minilm-l12": MINILM_L12}
        if isinstance(model_name, CrossEncoderModel):
            self.model = model_name
        else:
            kwargs = dict(custom_kwargs)
            from pathway_tpu.models.checkpoint import has_checkpoint_weights

            if model_name in presets:
                kwargs.setdefault("cfg", presets[model_name])
                self.model = CrossEncoderModel(**kwargs)
            elif isinstance(model_name, str) and has_checkpoint_weights(model_name):
                # local HF cross-encoder checkpoint (ms-marco-MiniLM style)
                self.model = CrossEncoderModel.from_pretrained(
                    model_name, **kwargs
                )
            else:
                self.model = CrossEncoderModel(**kwargs)

    def __wrapped__(self, doc: list[str], query: list[str], **kwargs) -> list[float]:
        pairs = [(q or "", d or "") for q, d in zip(query, doc)]
        scores = self.model.score_batch(pairs)
        return [float(s) for s in scores]

    # two-phase protocol (UDF._call_batched): chunks of an epoch all
    # dispatch, then ONE device drain — per-chunk syncs cost a relay RTT
    def submit_batch(self, doc: list[str], query: list[str], **kwargs):
        pairs = [(q or "", d or "") for q, d in zip(query, doc)]
        return self.model.score_submit(pairs)

    def resolve_batch(self, handles) -> list[list[float]]:
        return [
            [float(s) for s in arr]
            for arr in self.model.score_resolve(handles)
        ]

    def __call__(self, doc, query, **kwargs):
        return super().__call__(doc, query, **kwargs)


class EncoderReranker(pw.UDF):
    """Bi-encoder reranker: cosine of (query, doc) embeddings (reference
    ``EncoderReranker``, rerankers.py:251-317). Batched on TPU."""

    def __init__(
        self,
        model_name: Any = "minilm-l6",
        *,
        max_batch_size: int | None = 1024,
        cache_strategy: udfs.CacheStrategy | None = None,
        custom_kwargs: dict = {},
    ):
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            return_type=float,
        )
        from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

        self.embedder = SentenceTransformerEmbedder(model_name, **custom_kwargs)

    def __wrapped__(self, doc: list[str], query: list[str], **kwargs) -> list[float]:
        # route through the embedder UDF (not model.embed_batch): under
        # PATHWAY_TPU_EMBED_DEDUP the query column repeats the same text
        # for every candidate doc — the embedder's content-keyed dedup
        # collapses those k rows to ONE device dispatch row
        q = np.asarray(self.embedder.__wrapped__(list(query)))
        d = np.asarray(self.embedder.__wrapped__(list(doc)))
        # embeddings are unit-norm, so dot product == cosine similarity
        return [float(s) for s in np.sum(q * d, axis=1)]

    # two-phase protocol: both embed dispatches per chunk go out eagerly;
    # the single resolve drains every (query, doc) pair of the epoch
    def submit_batch(self, doc: list[str], query: list[str], **kwargs):
        hq = self.embedder.submit_batch(list(query))
        hd = self.embedder.submit_batch(list(doc))
        return (hq, hd)

    def resolve_batch(self, handles) -> list[list[float]]:
        flat = []
        for hq, hd in handles:
            flat.append(hq)
            flat.append(hd)
        arrs = self.embedder.resolve_batch(flat)
        out = []
        for i in range(0, len(arrs), 2):
            q = np.asarray(arrs[i])
            d = np.asarray(arrs[i + 1])
            out.append([float(s) for s in np.sum(q * d, axis=1)])
        return out


class LLMReranker(pw.UDF):
    """Ask a chat model to rate doc relevance 1-5 (reference ``LLMReranker``,
    rerankers.py:58-184)."""

    prompt_template = (
        "Rate how relevant the document is to the query on a scale 1 to 5. "
        "Reply with a single digit.\n\nQuery: {query}\n\nDocument: {doc}\n\nRating:"
    )

    def __init__(
        self,
        llm: BaseChat,
        *,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        use_logit_bias: bool | None = None,
    ):
        super().__init__(cache_strategy=cache_strategy, return_type=float)
        self.llm = llm
        self.use_logit_bias = use_logit_bias

    def __wrapped__(self, doc: str, query: str, **kwargs) -> float:
        from pathway_tpu.xpacks.llm._utils import _coerce_sync

        prompt = self.prompt_template.format(query=query, doc=doc)
        messages = [{"role": "user", "content": prompt}]
        if getattr(self.llm, "batch", False):
            # TPU-native decoder chats are batch UDFs — wrap the prompt as
            # a one-row batch (a continuous TPUDecoderChat then serves it
            # through its slot pool instead of a dedicated dispatch)
            response = _coerce_sync(self.llm.__wrapped__)([messages], **kwargs)[0]
        else:
            response = _coerce_sync(self.llm.__wrapped__)(messages, **kwargs)
        digits = [c for c in str(response) if c.isdigit()]
        if not digits:
            raise ValueError(f"reranker got non-numeric response: {response!r}")
        return float(digits[0])


class ListwiseLLMReranker(pw.UDF):
    """RankLLM-style listwise reranker: a sliding window of candidates is
    formatted into ONE prompt and the model answers with a permutation
    (``[2] > [1] > [3]``), instead of scoring each (query, doc) pair in
    isolation like ``LLMReranker``.

    The window slides **bottom-up** with overlap (RankGPT's schedule), so
    a relevant document buried deep in the candidate list can bubble to
    the top across windows. Malformed model output degrades safely: the
    affected window keeps its incoming (cross-encoder) order. With a
    ``TPUDecoderChat(continuous=True)`` the per-round window prompts of a
    whole query batch ride the serving slot pool concurrently via the
    existing submit/tenant machinery; any ``BaseChat`` works as a
    fallback.
    """

    _ID_RE = re.compile(r"\[(\d+)\]")

    def __init__(
        self,
        llm: BaseChat,
        *,
        window: int = 8,
        stride: int = 4,
        max_new_tokens: int | None = None,
        tenant: str = "rerank",
        cache_strategy: udfs.CacheStrategy | None = None,
    ):
        super().__init__(
            deterministic=bool(getattr(llm, "deterministic", False)),
            batch=True,
            cache_strategy=cache_strategy,
        )
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if not 1 <= stride <= window:
            raise ValueError(
                f"stride must be in [1, window({window})], got {stride}"
            )
        self.llm = llm
        self.window = int(window)
        self.stride = int(stride)
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant

    # ---------------------------------------------------- prompt / parse
    def _window_prompt(self, query: str, docs: list[str]) -> str:
        n = len(docs)
        lines = [
            f"I will provide {n} passages, each labeled with an identifier "
            f"like [1]. Rank them by relevance to the query.",
            f"Query: {query}",
        ]
        lines.extend(f"[{i + 1}] {d}" for i, d in enumerate(docs))
        lines.append(
            f"Rank the {n} passages above in descending order of relevance "
            "to the query. Answer ONLY with identifiers separated by >, "
            "for example [2] > [1] > [3]. Do not write anything else."
        )
        return "\n".join(lines)

    def _parse_permutation(self, text: Any, n: int) -> list[int] | None:
        """0-based permutation of ``range(n)`` from a ranking reply, or
        ``None`` for malformed/failed output (the fallback signal)."""
        if not text or decode_serve_error(text) is not None:
            return None
        seen: set[int] = set()
        perm: list[int] = []
        for tok in self._ID_RE.findall(str(text)):
            i = int(tok) - 1
            if 0 <= i < n and i not in seen:
                seen.add(i)
                perm.append(i)
        if not perm:
            return None
        # ids the model dropped keep their incoming relative order, after
        # everything it did rank
        perm.extend(i for i in range(n) if i not in seen)
        return perm

    def _window_starts(self, n: int) -> list[int]:
        """Bottom-up overlapping window start offsets for an n-doc list."""
        if n <= 1:
            return []
        if n <= self.window:
            return [0]
        starts = []
        s = n - self.window
        while s > 0:
            starts.append(s)
            s -= self.stride
        starts.append(0)
        return starts

    # ------------------------------------------------------------- chat
    def _chat_round(self, prompts: list[str], **kwargs) -> list[Any]:
        from pathway_tpu.xpacks.llm._utils import _coerce_sync

        msgs = [[{"role": "user", "content": p}] for p in prompts]
        kw = dict(kwargs)
        if self.max_new_tokens is not None:
            kw.setdefault("max_new_tokens", self.max_new_tokens)
        submit = getattr(self.llm, "submit_batch", None)
        if submit is not None:
            # continuous decoder: all window prompts of this round enter
            # the slot pool together and drain with one resolve
            kw.setdefault("tenant", self.tenant)
            return self.llm.resolve_batch([submit(msgs, **kw)])[0]
        if getattr(self.llm, "batch", False):
            return _coerce_sync(self.llm.__wrapped__)(msgs, **kw)
        return [_coerce_sync(self.llm.__wrapped__)(m, **kw) for m in msgs]

    # ------------------------------------------------------------- core
    def rerank_batch(
        self, queries: list[str], docs_lists: list[list[str]], **kwargs
    ) -> list[list[int]]:
        """Per-query permutation (indices into its doc list, best first).

        Rounds run in lockstep across the batch: round ``r`` collects the
        r-th window of every still-active query into one chat call.
        """
        orders = [list(range(len(d))) for d in docs_lists]
        rounds = [self._window_starts(len(d)) for d in docs_lists]
        n_rounds = max((len(r) for r in rounds), default=0)
        for r in range(n_rounds):
            live = [i for i in range(len(queries)) if r < len(rounds[i])]
            prompts = []
            for i in live:
                s = rounds[i][r]
                w = orders[i][s:s + self.window]
                prompts.append(self._window_prompt(
                    queries[i] or "", [str(docs_lists[i][j]) for j in w]
                ))
            replies = self._chat_round(prompts, **kwargs)
            for i, reply in zip(live, replies):
                s = rounds[i][r]
                w = orders[i][s:s + self.window]
                perm = self._parse_permutation(reply, len(w))
                if perm is not None:
                    orders[i][s:s + self.window] = [w[p] for p in perm]
                # malformed reply: this window stays in its incoming
                # (cross-encoder) order
        return orders

    def __wrapped__(
        self, docs: list[list[Any]], query: list[str], **kwargs
    ) -> list[list[Any]]:
        texts = [
            [_doc_text(d) for d in (row or [])] for row in docs
        ]
        perms = self.rerank_batch(list(query), texts, **kwargs)
        return [
            [row[j] for j in perm]
            for row, perm in zip([list(r or []) for r in docs], perms)
        ]

    def __call__(self, docs, query, **kwargs):
        return super().__call__(docs, query, **kwargs)


def _doc_text(d: Any) -> str:
    """Text payload of a retrieved doc (Json/dict/str)."""
    if isinstance(d, Json):
        d = d.value
    if isinstance(d, dict):
        return str(d.get("text", ""))
    return str(d)


class FlashRankReranker(pw.UDF):
    """FlashRank listwise reranker (reference ``FlashRankReranker``,
    rerankers.py:319-345). Gated on the ``flashrank`` package."""

    def __init__(
        self,
        model_name: str = "ms-marco-TinyBERT-L-2-v2",
        *,
        cache_strategy: udfs.CacheStrategy | None = None,
        max_length: int = 512,
    ):
        super().__init__(cache_strategy=cache_strategy, return_type=float)
        try:
            from flashrank import Ranker
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "FlashRankReranker requires the `flashrank` package"
            ) from exc
        self.ranker = Ranker(model_name=model_name, max_length=max_length)

    def __wrapped__(self, doc: str, query: str) -> float:
        from flashrank import RerankRequest

        results = self.ranker.rerank(
            RerankRequest(query=query, passages=[{"text": doc}])
        )
        return float(results[0]["score"])


@pw.udf
def unwrap_doc_texts(docs: list[Any]) -> list[str]:
    """Extract text fields from retrieved doc dicts/Jsons."""
    return [_doc_text(d) for d in docs or []]
