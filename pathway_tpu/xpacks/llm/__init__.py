"""pathway_tpu.xpacks.llm — the LLM/RAG toolkit (reference
``python/pathway/xpacks/llm/``), TPU-first.

The dense stages of the RAG pipeline — sentence embedding, cross-encoder
reranking, KNN retrieval — run as batched XLA programs on the MXU
(``pathway_tpu.models``, ``pathway_tpu.ops.knn``); API-client components
(OpenAI/LiteLLM/Gemini/Cohere) keep the reference's async-UDF shape.
"""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    splitters,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore, SlidesDocumentStore
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseContextProcessor,
    BaseQuestionAnswerer,
    BaseRAGQuestionAnswerer,
    DeckRetriever,
    SimpleContextProcessor,
    SummaryQuestionAnswerer,
    answer_with_geometric_rag_strategy,
    answer_with_geometric_rag_strategy_from_index,
)
from pathway_tpu.xpacks.llm.servers import (
    BaseRestServer,
    DocumentStoreServer,
    QARestServer,
    QASummaryRestServer,
    serve_callable,
)
from pathway_tpu.ops.fused_query import FusedRAGPipeline
from pathway_tpu.xpacks.llm.vector_store import (
    SlidesVectorStoreServer,
    VectorStoreClient,
    VectorStoreServer,
)

__all__ = [
    "FusedRAGPipeline",
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "splitters",
    "DocumentStore",
    "SlidesDocumentStore",
    "AdaptiveRAGQuestionAnswerer",
    "BaseContextProcessor",
    "BaseQuestionAnswerer",
    "BaseRAGQuestionAnswerer",
    "DeckRetriever",
    "SimpleContextProcessor",
    "SummaryQuestionAnswerer",
    "answer_with_geometric_rag_strategy",
    "answer_with_geometric_rag_strategy_from_index",
    "BaseRestServer",
    "DocumentStoreServer",
    "QARestServer",
    "QASummaryRestServer",
    "serve_callable",
    "SlidesVectorStoreServer",
    "VectorStoreClient",
    "VectorStoreServer",
]
