"""Shared LLM-xpack constants (reference ``xpacks/llm/constants.py``)."""

DEFAULT_VISION_MODEL = "gpt-4o"
