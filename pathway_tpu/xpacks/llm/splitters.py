"""Text splitter UDFs (reference ``xpacks/llm/splitters.py``).

``TokenCountSplitter`` chunks by token count; the reference uses tiktoken —
here the framework tokenizer (``HashTokenizer`` word pieces, or a local HF
tokenizer) supplies the count, so splitting works fully air-gapped.
"""

from __future__ import annotations

import re
import unicodedata

import pathway_tpu as pw
from pathway_tpu.internals.json import Json


@pw.udf
def null_splitter(txt: str) -> list[tuple[str, dict]]:
    """No-op splitter: one chunk per document (reference ``null_splitter``,
    splitters.py:13)."""
    return [(txt, {})]


def _normalize_unicode(text: str) -> str:
    return unicodedata.normalize("NFKC", text)


_SENTENCE_BREAK = re.compile(r"(?<=[.!?])\s+|\n{2,}")


class TokenCountSplitter(pw.UDF):
    """Split text into chunks of ``min_tokens``..``max_tokens`` tokens,
    preferring sentence boundaries (reference ``TokenCountSplitter``,
    splitters.py:34-120, which counts tokens with tiktoken)."""

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
    ):
        super().__init__(deterministic=True)
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name
        self._encoder = None

    def _count_tokens(self, text: str) -> int:
        enc = self._get_encoder()
        if enc is not None:
            return len(enc.encode(text))
        # whitespace-word count approximates wordpiece count closely enough
        # for chunk sizing
        return max(1, len(text.split()))

    def _get_encoder(self):
        if self._encoder is None:
            try:
                import tiktoken

                self._encoder = tiktoken.get_encoding(self.encoding_name)
            except Exception:  # noqa: BLE001 - gated dependency
                self._encoder = False
        return self._encoder or None

    def __wrapped__(self, txt: str, **kwargs) -> list[tuple[str, dict]]:
        text = _normalize_unicode(txt or "")
        if not text.strip():
            return []
        sentences = [s for s in _SENTENCE_BREAK.split(text) if s.strip()]
        chunks: list[tuple[str, dict]] = []
        current: list[str] = []
        current_tokens = 0
        for sentence in sentences:
            stoks = self._count_tokens(sentence)
            if stoks > self.max_tokens:
                # hard-split an oversized sentence by words
                words = sentence.split()
                step = max(1, self.max_tokens)
                for i in range(0, len(words), step):
                    part = " ".join(words[i : i + step])
                    if current:
                        chunks.append((" ".join(current), {}))
                        current, current_tokens = [], 0
                    chunks.append((part, {}))
                continue
            if current_tokens + stoks > self.max_tokens and current_tokens >= self.min_tokens:
                chunks.append((" ".join(current), {}))
                current, current_tokens = [], 0
            current.append(sentence)
            current_tokens += stoks
        if current:
            chunks.append((" ".join(current), {}))
        return chunks


class RecursiveSplitter(pw.UDF):
    """Recursively split on separators until chunks fit ``chunk_size``
    (langchain-style; reference exposes this via langchain adapters)."""

    def __init__(
        self,
        chunk_size: int = 500,
        chunk_overlap: int = 0,
        separators: list[str] | None = None,
    ):
        super().__init__(deterministic=True)
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.separators = separators or ["\n\n", "\n", ". ", " "]

    def _split(self, text: str, seps: list[str]) -> list[str]:
        if len(text.split()) <= self.chunk_size or not seps:
            return [text] if text.strip() else []
        sep, rest = seps[0], seps[1:]
        parts = text.split(sep)
        out: list[str] = []
        buf = ""
        for p in parts:
            candidate = (buf + sep + p) if buf else p
            if len(candidate.split()) > self.chunk_size:
                if buf:
                    out.extend(self._split(buf, rest) if len(buf.split()) > self.chunk_size else [buf])
                buf = p
            else:
                buf = candidate
        if buf:
            out.extend(self._split(buf, rest) if len(buf.split()) > self.chunk_size else [buf])
        return out

    def __wrapped__(self, txt: str, **kwargs) -> list[tuple[str, dict]]:
        chunks = self._split(_normalize_unicode(txt or ""), self.separators)
        if self.chunk_overlap > 0 and len(chunks) > 1:
            # prepend the tail of the previous chunk to each following chunk
            overlapped = [chunks[0]]
            for prev, cur in zip(chunks, chunks[1:]):
                tail = " ".join(prev.split()[-self.chunk_overlap:])
                overlapped.append(f"{tail} {cur}" if tail else cur)
            chunks = overlapped
        return [(c, {}) for c in chunks]


@pw.udf
def chunk_texts(text: str, max_words: int = 200) -> list[str]:
    """Simple word-window chunker used by demos."""
    words = (text or "").split()
    return [
        " ".join(words[i : i + max_words]) for i in range(0, len(words), max_words)
    ] or [""]
