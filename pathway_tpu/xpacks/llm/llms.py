"""Chat-LLM UDFs (reference ``xpacks/llm/llms.py:27-707``).

``BaseChat`` subclasses are UDFs mapping a message list (or ``pw.Json``) to a
completion string. API clients (OpenAI/LiteLLM/Cohere) are async and gated on
their SDKs; ``HFPipelineChat`` runs a local ``transformers`` pipeline (CPU —
chats are not the TPU hot path; the embedder/reranker are).
"""

from __future__ import annotations

import logging
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json

logger = logging.getLogger(__name__)


def _messages_to_list(messages: Any) -> list[dict]:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return [{"role": "user", "content": messages}]
    out = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        out.append(dict(m))
    return out


def _prep_message_log(messages: list[dict], verbose: bool) -> str:
    if verbose:
        return str(messages)
    return str([
        {**m, "content": m.get("content", "")[:100]} for m in messages
    ])


class BaseChat(pw.UDF):
    """Base chat UDF (reference ``BaseChat``, llms.py:27)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)

    def _accepts_call_arg(self, arg_name: str) -> bool:
        """Whether the underlying API accepts ``arg_name`` as a call kwarg."""
        return True


class OpenAIChat(BaseChat):
    """OpenAI chat-completions client (reference ``OpenAIChat``,
    llms.py:84-311)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "gpt-4o-mini",
        verbose: bool = False,
        **openai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(openai_kwargs)
        self.verbose = verbose
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, messages: list[dict] | Json, **kwargs) -> str | None:
        try:
            import openai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("OpenAIChat requires the `openai` package") from exc
        messages = _messages_to_list(messages)
        kwargs = {**self.kwargs, **kwargs}
        logger.info("OpenAIChat: %s", _prep_message_log(messages, self.verbose))
        api_kwargs = {
            k: kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in kwargs
        }
        client = openai.AsyncOpenAI(**api_kwargs)
        ret = await client.chat.completions.create(messages=messages, **kwargs)
        return ret.choices[0].message.content


class LiteLLMChat(BaseChat):
    """LiteLLM multi-provider chat (reference ``LiteLLMChat``,
    llms.py:313-439)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        verbose: bool = False,
        **litellm_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(litellm_kwargs)
        self.verbose = verbose
        if model is not None:
            self.kwargs["model"] = model

    def __wrapped__(self, messages: list[dict] | Json, **kwargs) -> str | None:
        try:
            import litellm
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("LiteLLMChat requires the `litellm` package") from exc
        messages = _messages_to_list(messages)
        ret = litellm.completion(messages=messages, **{**self.kwargs, **kwargs})
        return ret.choices[0]["message"]["content"]


class HFPipelineChat(BaseChat):
    """Local HuggingFace ``transformers`` text-generation pipeline (reference
    ``HFPipelineChat``, llms.py:441-542). Runs host-side."""

    def __init__(
        self,
        model: str | None = None,
        call_kwargs: dict = {},
        device: str = "cpu",
        batch_size: int | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **pipeline_kwargs,
    ):
        super().__init__(cache_strategy=cache_strategy)
        try:
            import transformers
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "HFPipelineChat requires the `transformers` package"
            ) from exc
        self.pipeline = transformers.pipeline(
            "text-generation", model=model, device=device, **pipeline_kwargs
        )
        self.tokenizer = self.pipeline.tokenizer
        self.call_kwargs = dict(call_kwargs)
        if batch_size is not None:
            self.call_kwargs["batch_size"] = batch_size

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        tokens = self.tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
            return self.tokenizer.convert_tokens_to_string(tokens)
        return input_string

    def __wrapped__(self, messages: list[dict] | Json | str, **kwargs) -> str | None:
        if isinstance(messages, (Json, list)):
            messages_decoded: Any = _messages_to_list(messages)
        else:
            messages_decoded = messages
        output = self.pipeline(messages_decoded, **{**self.call_kwargs, **kwargs})
        result = output[0]["generated_text"]
        if isinstance(result, list):  # chat format: last message is the reply
            result = result[-1]["content"]
        return result


class CohereChat(BaseChat):
    """Cohere chat client with RAG-style cited generation (reference
    ``CohereChat``, llms.py:544-684)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "command",
        **cohere_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(cohere_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    def __wrapped__(
        self, messages: list[dict] | Json, documents: list[dict] | Json | None = None,
        **kwargs,
    ) -> tuple[str, list[dict]]:
        try:
            import cohere
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("CohereChat requires the `cohere` package") from exc
        messages = _messages_to_list(messages)
        docs = None
        if documents is not None:
            docs = documents.value if isinstance(documents, Json) else list(documents)
        kwargs = {**self.kwargs, **kwargs}
        client = cohere.Client()
        message = messages[-1]["content"]
        chat_history = messages[:-1]
        ret = client.chat(
            message=message, chat_history=chat_history, documents=docs, **kwargs
        )
        cited_docs = [dict(c.__dict__) for c in (ret.citations or [])]
        return ret.text, cited_docs


@pw.udf
def prompt_chat_single_qa(question: str) -> Json:
    """Wrap a plain question string into a one-message chat (reference
    ``prompt_chat_single_qa``, llms.py:686)."""
    return Json([{"role": "user", "content": question}])
