"""Chat-LLM UDFs (reference ``xpacks/llm/llms.py:27-707``).

``BaseChat`` subclasses are UDFs mapping a message list (or ``pw.Json``) to a
completion string. API clients (OpenAI/LiteLLM/Cohere) are async and gated on
their SDKs; ``HFPipelineChat`` runs a local ``transformers`` pipeline (CPU —
chats are not the TPU hot path; the embedder/reranker are).
"""

from __future__ import annotations

import logging
from typing import Any

import pathway_tpu as pw
from pathway_tpu.analysis.annotations import guarded_by
from pathway_tpu.internals import udfs
from pathway_tpu.internals.json import Json

logger = logging.getLogger(__name__)


def _messages_to_list(messages: Any) -> list[dict]:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return [{"role": "user", "content": messages}]
    out = []
    for m in messages:
        if isinstance(m, Json):
            m = m.value
        out.append(dict(m))
    return out


def _prep_message_log(messages: list[dict], verbose: bool) -> str:
    if verbose:
        return str(messages)
    return str([
        {**m, "content": m.get("content", "")[:100]} for m in messages
    ])


# Serving failures travel the string-typed response channel as a
# reserved-prefix marker (the \x00 prefix cannot appear in decoded
# model output): the continuous server's resolve encodes WHY a request
# failed or was shed, and the REST layer (``xpacks/llm/servers.py``
# ``map_serving_errors``) decodes it into a structured JSON 500/503
# instead of the opaque null body it used to be.
SERVE_ERROR_MARKER = "\x00pathway_tpu:serve_error\x00"


def encode_serve_error(reason: str,
                       retry_after: float | None = None) -> str:
    import json as json_mod

    payload: dict = {"reason": reason}
    if retry_after is not None:
        payload["retry_after"] = retry_after
    return SERVE_ERROR_MARKER + json_mod.dumps(payload)


def decode_serve_error(text: Any) -> dict | None:
    """The structured error a serving response string carries, or None
    for ordinary responses."""
    import json as json_mod

    if not isinstance(text, str) or not text.startswith(SERVE_ERROR_MARKER):
        return None
    try:
        return json_mod.loads(text[len(SERVE_ERROR_MARKER):])
    except ValueError:
        return {"reason": "serve_failed"}


class BaseChat(pw.UDF):
    """Base chat UDF (reference ``BaseChat``, llms.py:27)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)

    def _accepts_call_arg(self, arg_name: str) -> bool:
        """Whether the underlying API accepts ``arg_name`` as a call kwarg."""
        return True


class OpenAIChat(BaseChat):
    """OpenAI chat-completions client (reference ``OpenAIChat``,
    llms.py:84-311)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "gpt-4o-mini",
        verbose: bool = False,
        **openai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(openai_kwargs)
        self.verbose = verbose
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, messages: list[dict] | Json, **kwargs) -> str | None:
        try:
            import openai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("OpenAIChat requires the `openai` package") from exc
        messages = _messages_to_list(messages)
        kwargs = {**self.kwargs, **kwargs}
        logger.info("OpenAIChat: %s", _prep_message_log(messages, self.verbose))
        api_kwargs = {
            k: kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in kwargs
        }
        client = openai.AsyncOpenAI(**api_kwargs)
        ret = await client.chat.completions.create(messages=messages, **kwargs)
        return ret.choices[0].message.content


class LiteLLMChat(BaseChat):
    """LiteLLM multi-provider chat (reference ``LiteLLMChat``,
    llms.py:313-439)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        verbose: bool = False,
        **litellm_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(litellm_kwargs)
        self.verbose = verbose
        if model is not None:
            self.kwargs["model"] = model

    def __wrapped__(self, messages: list[dict] | Json, **kwargs) -> str | None:
        try:
            import litellm
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("LiteLLMChat requires the `litellm` package") from exc
        messages = _messages_to_list(messages)
        ret = litellm.completion(messages=messages, **{**self.kwargs, **kwargs})
        return ret.choices[0]["message"]["content"]


class HFPipelineChat(BaseChat):
    """Local HuggingFace ``transformers`` text-generation pipeline (reference
    ``HFPipelineChat``, llms.py:441-542). Runs host-side."""

    def __init__(
        self,
        model: str | None = None,
        call_kwargs: dict = {},
        device: str = "cpu",
        batch_size: int | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        **pipeline_kwargs,
    ):
        super().__init__(cache_strategy=cache_strategy)
        try:
            import transformers
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "HFPipelineChat requires the `transformers` package"
            ) from exc
        self.pipeline = transformers.pipeline(
            "text-generation", model=model, device=device, **pipeline_kwargs
        )
        self.tokenizer = self.pipeline.tokenizer
        self.call_kwargs = dict(call_kwargs)
        if batch_size is not None:
            self.call_kwargs["batch_size"] = batch_size

    def crop_to_max_length(self, input_string: str, max_prompt_length: int = 500) -> str:
        tokens = self.tokenizer.tokenize(input_string)
        if len(tokens) > max_prompt_length:
            tokens = tokens[-max_prompt_length:]
            return self.tokenizer.convert_tokens_to_string(tokens)
        return input_string

    def __wrapped__(self, messages: list[dict] | Json | str, **kwargs) -> str | None:
        if isinstance(messages, (Json, list)):
            messages_decoded: Any = _messages_to_list(messages)
        else:
            messages_decoded = messages
        output = self.pipeline(messages_decoded, **{**self.call_kwargs, **kwargs})
        result = output[0]["generated_text"]
        if isinstance(result, list):  # chat format: last message is the reply
            result = result[-1]["content"]
        return result


class CohereChat(BaseChat):
    """Cohere chat client with RAG-style cited generation (reference
    ``CohereChat``, llms.py:544-684)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "command",
        **cohere_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(cohere_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    def __wrapped__(
        self, messages: list[dict] | Json, documents: list[dict] | Json | None = None,
        **kwargs,
    ) -> tuple[str, list[dict]]:
        try:
            import cohere
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("CohereChat requires the `cohere` package") from exc
        messages = _messages_to_list(messages)
        docs = None
        if documents is not None:
            docs = documents.value if isinstance(documents, Json) else list(documents)
        kwargs = {**self.kwargs, **kwargs}
        client = cohere.Client()
        message = messages[-1]["content"]
        chat_history = messages[:-1]
        ret = client.chat(
            message=message, chat_history=chat_history, documents=docs, **kwargs
        )
        cited_docs = [dict(c.__dict__) for c in (ret.citations or [])]
        return ret.text, cited_docs


class TPUDecoderChat(BaseChat):
    """TPU-native local chat: a GPT-2-family causal decoder generating ON
    DEVICE (``models/decoder.py``).

    Where the reference's local-LLM option (``HFPipelineChat``, reference
    llms.py:441-542) runs a torch pipeline host-side token by token, this
    UDF compiles prefill + KV-cached decode + sampling into ONE jitted
    call, so an engine microbatch of prompts costs a single dispatch.

    Construct either from a local GPT-2-family checkpoint directory
    (weights + ``vocab.json``/``merges.txt``) or from explicit
    ``params``/``cfg``/``tokenizer`` (any object with ``encode``/``decode``
    and an ``eos_id``)."""

    def __init__(
        self,
        checkpoint_path: str | None = None,
        params: dict | None = None,
        cfg=None,
        tokenizer=None,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        max_prompt_tokens: int = 512,
        seed: int = 0,
        cache_strategy: udfs.CacheStrategy | None = None,
        max_batch_size: int | None = 64,
        continuous: bool = False,
        n_slots: int = 16,
        chunk_steps: int = 16,
        pipeline_depth: int = 4,
        deferred: bool = False,
        chunked_prefill: bool | None = None,
        prefill_chunk: int | None = None,
        eager_refill: bool | None = None,
        prefix_cache: bool | None = None,
        prefix_cache_mb: float | None = None,
        prefix_block: int | None = None,
        spec_decode: bool | None = None,
        spec_draft_layers: int | None = None,
        spec_k: int | None = None,
        kv_quant: str | bool | None = None,
        paged_kv: bool | None = None,
        paged_kv_block: int | None = None,
        paged_kv_blocks: int | None = None,
        paged_kernel: bool | None = None,
        flash_prefill: bool | None = None,
        disagg: bool | None = None,
        disagg_prefill_budget: int | None = None,
        tenant_sched: bool | None = None,
        tenant_budget: int | None = None,
        tenant_weights: str | None = None,
        prefix_t2_mb: float | None = None,
        mesh=None,
        weight_quant: str | bool | None = None,
        wq_kernel: bool | None = None,
    ):
        # continuous=True: requests are served by a persistent slot-pool
        # loop (_ContinuousServer) — new rows admit into the IN-FLIGHT
        # decode at chunk boundaries instead of waiting for the previous
        # batch's full generation. deferred=True additionally runs the
        # UDF on the engine's fully-async path so the pump never blocks
        # on the decode (see SentenceTransformerEmbedder(deferred=...)).
        # Greedy decoding (temperature 0, no top-k/top-p) is deterministic
        # — declaring it lets the engine take the deferred two-phase path
        # (which re-derives values on retraction) instead of the blocking
        # replay-cache path.
        super().__init__(
            batch=True,
            deterministic=(
                float(temperature) == 0.0 and top_k is None and top_p is None
            ),
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            executor=udfs.fully_async_executor() if deferred else None,
        )
        if checkpoint_path is not None:
            from pathway_tpu.models.bpe import BPETokenizer
            from pathway_tpu.models.checkpoint import load_decoder_checkpoint

            params, cfg = load_decoder_checkpoint(checkpoint_path, cfg)
            if tokenizer is None:
                tokenizer = BPETokenizer.from_dir(checkpoint_path)
        if params is None or cfg is None or tokenizer is None:
            raise ValueError(
                "TPUDecoderChat needs checkpoint_path or explicit "
                "params + cfg + tokenizer"
            )
        import jax

        from pathway_tpu.internals import config as _config_mod
        from pathway_tpu.internals.config import pathway_config
        from pathway_tpu.models.decoder import (
            cast_params_for_inference,
            params_device_bytes,
            quantize_params,
        )

        # weight-only int8 (PATHWAY_TPU_WEIGHT_QUANT): the large decoder
        # matrices store as symmetric per-output-channel int8 with f32
        # scales, dequantized inside the matmul read — ~4× fewer weight
        # bytes per decode step on a memory-bound roofline
        wq = pathway_config.weight_quant if weight_quant is None else weight_quant
        wq = "int8" if wq is True else ("" if wq in (False, None) else wq)
        self.weight_quant = _config_mod._parse_weight_quant(str(wq))
        wqk = pathway_config.wq_kernel if wq_kernel is None else bool(wq_kernel)
        self.wq_kernel = bool(self.weight_quant) and bool(wqk)
        if self.wq_kernel:
            # a CONFIG field, not a module global: jit caches built for
            # this server key on it, so a rebuilt server cannot serve
            # stale kernel-less traces
            import dataclasses

            cfg = dataclasses.replace(cfg, wq_kernel=True)
        if self.weight_quant:
            self.params = jax.device_put(quantize_params(params, cfg))
        else:
            # compute-dtype weights: the decode phase reads the full
            # parameter set per step, so bf16 storage halves its HBM bill
            # (no-op for f32 configs)
            self.params = jax.device_put(cast_params_for_inference(params, cfg))
        # HBM ledger: the decoder's physical param footprint (int8
        # payloads + scales when quantized) at placement — the bench
        # quant arm reads its bytes-saved headline from this gauge. The
        # continuous server re-records after mesh sharding with the
        # real per-device split.
        from pathway_tpu.engine.probes import record_hbm

        for dev, nbytes in params_device_bytes(self.params).items():
            record_hbm("weights.decoder", nbytes, device=dev)
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        # clamp the prompt cap so prompt + generation always fits the
        # model's positions (generate() raises on overflow; the cap makes
        # the default usable for any max_position)
        self.max_prompt_tokens = min(
            int(max_prompt_tokens), cfg.max_position - self.max_new_tokens
        )
        if self.max_prompt_tokens <= 0:
            raise ValueError(
                f"max_new_tokens ({self.max_new_tokens}) leaves no room "
                f"for a prompt within max_position ({cfg.max_position})"
            )
        self._seed = seed
        self._calls = 0  # advances the sampling key between calls
        # (rows, prompt_len, max_new, temperature, top_k, top_p) -> jitted
        # generate executable
        self._jitted: dict[tuple, Any] = {}
        self._server: _ContinuousServer | None = None
        if continuous:
            self._server = _ContinuousServer(
                self.params, cfg, tokenizer,
                n_slots=n_slots, chunk_steps=chunk_steps,
                max_prompt_tokens=self.max_prompt_tokens,
                default_max_new=self.max_new_tokens,
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p, seed=seed,
                pipeline_depth=pipeline_depth,
                chunked_prefill=chunked_prefill,
                prefill_chunk=prefill_chunk,
                eager_refill=eager_refill,
                prefix_cache=prefix_cache,
                prefix_cache_mb=prefix_cache_mb,
                prefix_block=prefix_block,
                spec_decode=spec_decode,
                spec_draft_layers=spec_draft_layers,
                spec_k=spec_k,
                kv_quant=kv_quant,
                paged_kv=paged_kv,
                paged_kv_block=paged_kv_block,
                paged_kv_blocks=paged_kv_blocks,
                paged_kernel=paged_kernel,
                flash_prefill=flash_prefill,
                disagg=disagg,
                disagg_prefill_budget=disagg_prefill_budget,
                tenant_sched=tenant_sched,
                tenant_budget=tenant_budget,
                tenant_weights=tenant_weights,
                prefix_t2_mb=prefix_t2_mb,
                mesh=mesh,
                weight_quant=self.weight_quant,
            )
            # the two-phase engine protocol only exists in continuous
            # mode — exposing these as CLASS methods would activate the
            # pipelined path for batch-static instances too
            self.submit_batch = self._submit_batch_continuous
            self.resolve_batch = self._resolve_batch_continuous

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()

    def recent_traces(self, n: int | None = None) -> list[dict]:
        """Completed request spans of the continuous server (empty for
        batch-static instances and under ``PATHWAY_TPU_METRICS=0``)."""
        if self._server is None:
            return []
        return self._server.recent_traces(n=n)

    # two-phase protocol (continuous mode): submit enqueues every row into
    # the serving loop WITHOUT waiting; resolve blocks on the completions.
    # Combined with deferred=True the engine pump overlaps the decode.
    def _submit_batch_continuous(self, messages: list, **kwargs):
        if self._server is None:
            raise TypeError("submit_batch requires continuous=True")
        max_new = int(kwargs.pop("max_new_tokens", self.max_new_tokens))
        priority = int(kwargs.pop("priority", 1))
        tenant = str(kwargs.pop("tenant", "default")) or "default"
        if kwargs:
            # sampling params are compiled into the serving loop; per-call
            # overrides would silently apply to OTHER rows' chunks
            raise TypeError(
                f"continuous TPUDecoderChat cannot vary {sorted(kwargs)} "
                f"per call; set them on the constructor"
            )
        if max_new > self.max_new_tokens:
            # the slot pool's KV cache is sized from the constructor's
            # max_new_tokens; a longer request would clamp-overwrite the
            # last cache slot and return corrupted tokens
            raise ValueError(
                f"continuous TPUDecoderChat serves at most the "
                f"constructor's max_new_tokens ({self.max_new_tokens}) "
                f"per request; got {max_new}"
            )
        prompt_cap = min(
            self.max_prompt_tokens, self.cfg.max_position - max_new
        )
        if prompt_cap <= 0:
            raise ValueError(
                f"max_new_tokens ({max_new}) leaves no room for a prompt "
                f"within max_position ({self.cfg.max_position})"
            )
        reqs = []
        for m in messages:
            ids = self.tokenizer.encode(self._format_prompt(m))[-prompt_cap:]
            reqs.append(self._server.submit(
                ids, max_new, priority=priority, tenant=tenant,
            ))
        return reqs

    def _resolve_batch_continuous(self, handles) -> list:
        out = []
        for reqs in handles:
            texts = []
            for req in reqs:
                req.done.wait()
                if req.text is None:
                    # failed or shed: surface the structured reason
                    # through the string channel instead of a bare null
                    texts.append(encode_serve_error(
                        req.error_reason or "serve_failed",
                        retry_after=req.retry_after,
                    ))
                else:
                    texts.append(req.text)
            out.append(texts)
        return out

    def _format_prompt(self, messages) -> str:
        if isinstance(messages, str):
            return messages
        parts = [
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in _messages_to_list(messages)
        ]
        return "\n".join(parts) + "\nassistant:"

    def _generate_fn(self, rows: int, s: int, max_new: int, temp: float,
                     top_k, top_p):
        cache_key = (rows, s, max_new, temp, top_k, top_p)
        fn = self._jitted.get(cache_key)
        if fn is None:
            import jax

            from pathway_tpu.models import decoder as decoder_mod

            cfg = self.cfg

            def run(params, ids, mask, key):
                return decoder_mod.generate(
                    params, ids, mask, cfg, max_new,
                    temperature=temp, key=key,
                    eos_id=getattr(self.tokenizer, "eos_id", None),
                    top_k=top_k, top_p=top_p,
                )

            fn = jax.jit(run)
            self._jitted[cache_key] = fn
        return fn

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return arg_name in ("max_new_tokens", "temperature", "top_k", "top_p")

    def __wrapped__(self, messages: list, **kwargs) -> list[str | None]:
        import jax
        import numpy as np

        from pathway_tpu.ops import next_pow2

        if self._server is not None:
            # continuous mode: route the blocking path through the same
            # slot pool (submit everything, then wait)
            return self.resolve_batch([self.submit_batch(messages, **kwargs)])[0]

        max_new = int(kwargs.pop("max_new_tokens", self.max_new_tokens))
        temp = float(kwargs.pop("temperature", self.temperature))
        top_k = kwargs.pop("top_k", self.top_k)
        # clamp into [1, vocab_size]: lax.top_k(k > vocab) raises an opaque
        # trace-time error; HF silently clamps to vocab size, so match that
        top_k = (
            None
            if top_k is None
            else min(max(1, int(top_k)), self.cfg.vocab_size)
        )
        top_p = kwargs.pop("top_p", self.top_p)
        top_p = None if top_p is None else float(top_p)
        if kwargs:
            # the sibling chat classes forward call kwargs to their APIs;
            # a compiled decoder has no such sink — reject, don't ignore
            raise TypeError(
                f"TPUDecoderChat got unsupported call kwargs: {sorted(kwargs)}"
            )
        # a per-call max_new_tokens shrinks the prompt budget so the
        # constructor's fit guarantee (prompt + generation <= max_position)
        # holds for every call, not just the default
        prompt_cap = min(
            self.max_prompt_tokens, self.cfg.max_position - max_new
        )
        if prompt_cap <= 0:
            raise ValueError(
                f"max_new_tokens ({max_new}) leaves no room for a prompt "
                f"within max_position ({self.cfg.max_position})"
            )
        prompts = [self._format_prompt(m) for m in messages]
        encoded = [
            self.tokenizer.encode(p)[-prompt_cap:] for p in prompts
        ]
        s = next_pow2(max((len(e) for e in encoded), default=1), 8)
        s = min(s, prompt_cap)
        rows = next_pow2(len(encoded), 1)
        ids = np.zeros((rows, s), np.int32)
        mask = np.zeros((rows, s), np.int32)
        for r, e in enumerate(encoded):  # LEFT-padded (decoder contract)
            e = e[-s:]
            if e:
                ids[r, s - len(e):] = e
                mask[r, s - len(e):] = 1
            else:
                mask[r, -1] = 1  # empty prompt: one live pad slot
        # advance the key per call: temperature>0 must SAMPLE across calls,
        # not replay one fixed draw (greedy decode ignores the key entirely)
        self._calls += 1
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed), self._calls)
        toks = np.asarray(
            self._generate_fn(rows, s, max_new, temp, top_k, top_p)(
                self.params, ids, mask, key
            )
        )
        eos = getattr(self.tokenizer, "eos_id", None)
        out: list[str | None] = []
        for r in range(len(encoded)):
            t = toks[r].tolist()
            if eos is not None and eos in t:
                t = t[: t.index(eos)]
            out.append(self.tokenizer.decode(t))
        return out


class _PendingCompletion:
    """One in-flight continuous-batching request (host-side slot record)."""

    __slots__ = ("ids", "max_new", "tokens", "done", "text", "finished_at",
                 "first_token_at", "span", "retries", "error_reason",
                 "retry_after", "deadline", "priority", "tenant", "seq")

    def __init__(self, ids: list, max_new: int):
        import threading

        from pathway_tpu.engine import tracing

        self.ids = ids
        self.max_new = max_new
        self.tokens: list[int] = []
        self.done = threading.Event()
        self.text: str | None = None
        self.finished_at: float | None = None  # time.perf_counter()
        self.first_token_at: float | None = None  # first token DRAINED
        self.span = tracing.NULL_SPAN  # replaced by submit()
        # fault-tolerance bookkeeping: isolation/restart retry count, the
        # structured failure reason behind a text=None sentinel (resolve
        # encodes it via encode_serve_error), the shed Retry-After hint,
        # the absolute perf_counter deadline, and the admission priority
        # class (level-3 degradation sheds priority <= 0)
        self.retries = 0
        self.error_reason: str | None = None
        self.retry_after: float | None = None
        self.deadline: float | None = None
        self.priority = 1
        # multi-tenant admission class (PATHWAY_TPU_TENANT_SCHED): the
        # weighted-fair pop groups and budgets requests by this tag;
        # seq is the server's admission order (newest-first preemption)
        self.tenant = "default"
        self.seq = 0


@guarded_by(queue="lock", free="lock")
class _ContinuousServer:
    """Slot-pool serving loop for ``TPUDecoderChat(continuous=True)``.

    A background thread owns a ``pool_init`` state of ``n_slots``
    sequences. Requests enqueue at any time; each loop iteration admits
    waiting requests into free slots (one prefill dispatch per
    admission, bucketed by prompt length), advances every busy slot
    ``chunk_steps`` decode steps in ONE dispatch, and frees slots whose
    stream hit EOS or the request's own ``max_new`` budget. A new
    request therefore waits at most one chunk — not a whole batch
    generation (reference ``HFPipelineChat`` is batch-static,
    llms.py:441).

    Occupancy (``stats["steps"] / stats["slot_steps_total"]``, exported
    via :meth:`occupancy`) is kept high two ways, both default-on via
    ``internals/config.py`` env flags:

    * **chunked prefill** (PATHWAY_TPU_CHUNKED_PREFILL) — prompts longer
      than ``prefill_chunk`` admit piece-wise via
      ``pool_prefill_chunk``, one piece per loop tick interleaved with
      decode chunks, so a long prompt never stalls every active lane
      for a whole-prompt prefill dispatch.
    * **eager refill** (PATHWAY_TPU_EAGER_REFILL) — a lane whose
      DISPATCHED steps already cover its budget frees its slot
      immediately (its remaining tokens drain from the in-flight
      snapshots) instead of ``pipeline_depth`` chunks later at
      drain time — the occupancy gap that kept slots idle a whole
      pipeline's depth per request.
    * **prefill/decode overlap** (PATHWAY_TPU_PREFILL_OVERLAP) — each
      tick dispatches the in-flight lanes' decode chunk FIRST, then
      runs admission host work and prefill dispatches while it
      computes; newcomers join the next chunk boundary, which they
      would have waited for anyway (xLLM-style chunk-boundary
      admission, arXiv:2510.14686).
    * **batched admission** (PATHWAY_TPU_BATCH_ADMIT) — same-bucket
      requests that arrive together admit via one ``pool_admit_batch``
      dispatch (pow2 group sizes to bound jit variants) instead of one
      dispatch per request, so an arrival burst costs O(log n)
      dispatches.
    * **chunk-steps autotune** (PATHWAY_TPU_CHUNK_AUTOTUNE) —
      ``chunk_steps`` adapts to observed arrival rate: queue pressure
      shrinks the chunk (earlier boundaries admit sooner and recycle
      slots sooner); an idle queue grows it back toward the
      constructor value (fewer dispatches per token). Candidates are
      halvings of the constructor value, so the KV-cache slack sizing
      stays valid.
    * **self-speculative decode** (PATHWAY_TPU_SPEC_DECODE, greedy
      servers only) — decode chunks become draft/verify/accept cycles:
      the first ``PATHWAY_TPU_SPEC_DECODE_DRAFT_LAYERS`` layers draft
      ``PATHWAY_TPU_SPEC_DECODE_K`` tokens against a depth-prefix of
      the same KV pool and ONE full-model dispatch verifies all of
      them, emitting 1..k+1 byte-identical greedy tokens per lane per
      weight stream (``pool_decode_spec``). The drain keeps an
      acceptance-rate EMA and latches back to plain chunks when the
      drafts stop paying (< 0.25 after 4 drains).
    * **int8 KV** (PATHWAY_TPU_KV_QUANT=int8) — the slot pool and the
      prefix arena store KV as symmetric int8 + f32 per-token scales
      (~2x slots and cached blocks per HBM byte), dequantized on read
      inside attention.
    * **paged KV** (PATHWAY_TPU_PAGED_KV) — slots stop owning dense
      ``cache_len`` KV rows; KV lives in one global pool of fixed-size
      blocks addressed through a per-slot block table, and admission
      allocates only the blocks a request can actually reach (prompt +
      its own ``max_new`` + pipeline slack) from a host
      ``BlockAllocator``. The prefix cache runs in ADOPTED mode: a
      finished prompt's blocks publish into the radix tree zero-copy
      (pin, not ``kv_extract``) and a hit seeds a newcomer by writing
      the shared ids into its block table copy-on-write — no arena
      copies, so the ``prefix_copy_bytes`` ledger stays at zero.
      Stranded bytes surface as the ``kv_fragmentation`` gauge.
      PATHWAY_TPU_PAGED_KERNEL additionally routes plain decode chunks
      through the Pallas paged-attention kernel
      (``models/paged_attention.py``)."""

    def __init__(self, params, cfg, tokenizer, *, n_slots: int,
                 chunk_steps: int, max_prompt_tokens: int,
                 default_max_new: int, temperature: float, top_k, top_p,
                 seed: int, pipeline_depth: int = 4,
                 chunked_prefill: bool | None = None,
                 prefill_chunk: int | None = None,
                 eager_refill: bool | None = None,
                 prefix_cache: bool | None = None,
                 prefix_cache_mb: float | None = None,
                 prefix_block: int | None = None,
                 spec_decode: bool | None = None,
                 spec_draft_layers: int | None = None,
                 spec_k: int | None = None,
                 kv_quant: str | bool | None = None,
                 paged_kv: bool | None = None,
                 paged_kv_block: int | None = None,
                 paged_kv_blocks: int | None = None,
                 paged_kernel: bool | None = None,
                 flash_prefill: bool | None = None,
                 disagg: bool | None = None,
                 disagg_prefill_budget: int | None = None,
                 tenant_sched: bool | None = None,
                 tenant_budget: int | None = None,
                 tenant_weights: str | None = None,
                 prefix_t2_mb: float | None = None,
                 mesh=None,
                 weight_quant: str = ""):
        import threading
        from collections import deque

        import jax

        from pathway_tpu.internals import config as _config_mod
        from pathway_tpu.internals.config import pathway_config
        from pathway_tpu.models import decoder as decoder_mod
        from pathway_tpu.ops import next_pow2

        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.n_slots = n_slots
        self.chunk_steps = chunk_steps
        self.max_prompt_bucket = next_pow2(max_prompt_tokens, 8)
        # the host loop runs ``pipeline_depth`` chunks AHEAD of the token
        # drain: each chunk's token block starts its device->host copy at
        # dispatch and has depth*cycle_time to land before the host reads
        # it (one read otherwise costs a full relay round trip). A lane
        # may overrun its budget until its tokens drain, so give one
        # chunk of cache slack per in-flight chunk plus the current one.
        self.pipeline_depth = max(0, int(pipeline_depth))
        # self-speculative decode (PATHWAY_TPU_SPEC_DECODE): greedy lanes
        # advance via draft/verify/accept cycles — the first
        # spec_draft_layers layers draft spec_k tokens, one full-model
        # dispatch verifies them all (models/decoder.py:pool_decode_spec).
        # Greedy-only by construction (acceptance compares argmaxes), so
        # sampling servers always take the plain chunk path; a 1-layer
        # model has no shallower draft stack, so it does too.
        want_spec = (
            pathway_config.spec_decode
            if spec_decode is None else bool(spec_decode)
        )
        self.spec_decode = bool(
            want_spec and float(temperature) == 0.0
            and top_k is None and top_p is None and cfg.layers >= 2
        )
        d = (
            pathway_config.spec_draft_layers
            if spec_draft_layers is None else int(spec_draft_layers)
        )
        if d <= 0:
            d = max(1, cfg.layers // 4)
        self.spec_draft_layers = max(1, min(d, cfg.layers - 1))
        self.spec_k = max(1, (
            pathway_config.spec_k if spec_k is None else int(spec_k)
        ))
        # adaptive fallback: spec decode must never LOSE throughput, so
        # after a few drained dispatches with the acceptance EMA below
        # threshold the server latches back to plain chunks (safe: both
        # paths emit identical greedy tokens, latching changes cost only)
        self._spec_off = False
        self._spec_drains = 0
        self._accept_ema: float | None = None
        # spec registry counters accumulate here between flushes (one
        # registry call per request completion, not six per drain); the
        # loop thread owns it, so no lock
        self._spec_accum: dict = {}
        # int8 KV (PATHWAY_TPU_KV_QUANT): the slot pool + prefix arena
        # store KV as symmetric int8 with per-(layer, slot, head, token)
        # f32 scales, dequantized on read inside attention
        kvq = pathway_config.kv_quant if kv_quant is None else kv_quant
        kvq = "int8" if kvq is True else ("" if kvq in (False, None) else kvq)
        self.kv_quant = _config_mod._parse_kv_quant(str(kvq))
        # a spec dispatch writes up to n_cycles*(spec_k+1) KV columns per
        # lane — bounded by max(chunk_steps, spec_k+1) — so the per-chunk
        # over-budget slack widens to that bound when spec is on
        slack = max(
            chunk_steps, (self.spec_k + 1) if self.spec_decode else 0
        )
        self._slack = slack
        self.cache_len = (
            self.max_prompt_bucket + default_max_new
            + (self.pipeline_depth + 1) * slack
        )
        self.eos_id = getattr(tokenizer, "eos_id", None)
        self.chunked_prefill = (
            pathway_config.chunked_prefill
            if chunked_prefill is None else bool(chunked_prefill)
        )
        self.prefill_chunk = max(8, next_pow2(
            pathway_config.prefill_chunk
            if prefill_chunk is None else int(prefill_chunk), 8,
        ))
        self.eager_refill = (
            pathway_config.eager_refill
            if eager_refill is None else bool(eager_refill)
        )
        # paged KV (PATHWAY_TPU_PAGED_KV): KV lives in a global pool of
        # fixed-size blocks behind a per-slot block table
        # (models/decoder.py paged_pool_init). The block size is a pow2
        # multiple of the prefill chunk so cached prefixes end on piece
        # boundaries; cache_len rounds UP to a whole number of blocks
        # (table rows address whole blocks). The kill switch
        # (PATHWAY_TPU_PAGED_KV=0) keeps the dense pool byte-identical.
        self.paged_kv = bool(
            pathway_config.paged_kv if paged_kv is None else paged_kv
        )
        self.paged_kernel = bool(self.paged_kv and (
            pathway_config.paged_kernel
            if paged_kernel is None else bool(paged_kernel)
        ))
        # flash prefill (PATHWAY_TPU_FLASH_PREFILL): every whole-prompt
        # admit and every chunked-prefill piece runs the tiled
        # online-softmax kernel (models/flash_attention.py) instead of
        # materializing the (T, C) mask-bias score matrix. Kill switch
        # keeps the dense path byte-identical. Construction-time read:
        # the per-server jit caches below key nothing on it — the closure
        # captures the bool, and a rebuilt server re-traces.
        self.flash_prefill = bool(
            pathway_config.flash_prefill
            if flash_prefill is None else flash_prefill
        )
        if self.flash_prefill:
            from pathway_tpu.models import flash_attention as _fa

            _fa.configure_blocks(pathway_config.flash_block_q,
                                 pathway_config.flash_block_k)
        self.paged_block = 0
        self._paged_blocks_override = 0
        self._allocator = None
        self._total_blocks = 0
        # slot -> list of block ids the slot holds references on (its
        # table row, sentinel-padded on device); slot -> reachable tokens
        # (the fragmentation gauge's "needed" numerator, dense too)
        self._slot_blocks: dict[int, list] = {}
        self._slot_cover: dict[int, int] = {}
        self._kv_frag = 0.0
        self._frag_sum = 0.0
        self._frag_n = 0
        if self.paged_kv:
            pb = (
                pathway_config.paged_kv_block
                if paged_kv_block is None else int(paged_kv_block)
            )
            self.paged_block = next_pow2(
                max(pb, self.prefill_chunk), self.prefill_chunk
            )
            self.cache_len = -(-self.cache_len
                               // self.paged_block) * self.paged_block
            self._paged_blocks_override = max(0, (
                pathway_config.paged_kv_blocks
                if paged_kv_blocks is None else int(paged_kv_blocks)
            ))
        # chunk-admission serving knobs (internals/config.py):
        # * batch_admit — same-bucket arrivals prefill in ONE grouped
        #   pool_admit_batch dispatch instead of one dispatch each;
        # * prefill_overlap — the decode chunk dispatches BEFORE admission
        #   work each tick, so newcomer prefill overlaps in-flight decode;
        # * chunk_autotune — decode-chunk steps shrink (halving, floor 4)
        #   against the observed arrival rate / queue pressure so chunk
        #   boundaries (admission + drain points) come sooner under load.
        self.batch_admit = pathway_config.batch_admit
        self.prefill_overlap = pathway_config.prefill_overlap
        self.chunk_autotune = pathway_config.chunk_autotune
        # disaggregated prefill/decode lanes (PATHWAY_TPU_DISAGG):
        # pending prefills form a prefill LANE that dispatches at most
        # disagg_prefill_budget pieces per tick (round-robin) while any
        # slot decodes, so a decode chunk never queues behind a burst of
        # long-prompt prefill pieces. A finished prefill MIGRATES into
        # the decode lane by block handoff — zero-copy on one chip (the
        # blocks stay put; only lane membership flips), kv_block_export/
        # kv_block_import for the cross-device case. Greedy tokens are
        # schedule-invariant, so the flag is a byte-identical kill
        # switch (tests/test_disagg.py).
        self.disagg = bool(
            pathway_config.disagg if disagg is None else disagg
        )
        self._prefill_budget = max(1, int(
            pathway_config.disagg_prefill_budget
            if disagg_prefill_budget is None else disagg_prefill_budget
        ))
        self._prefill_rr = 0  # round-robin cursor over the prefill lane
        self._lane_counts = {"prefill": 0, "decode": 0}
        # multi-tenant weighted-fair admission (PATHWAY_TPU_TENANT_SCHED):
        # the queue stays ONE deque (watermark, deadline sweep and crash
        # recovery unchanged) — the scheduler is a pure pop POLICY over
        # it, plus per-tenant in-flight token budgets whose enforcement
        # escalates from skip to preemption (_maybe_preempt).
        self._tenants = None
        want_tenants = bool(
            pathway_config.tenant_sched
            if tenant_sched is None else tenant_sched
        )
        if want_tenants:
            from pathway_tpu.engine import slo as slo_mod

            self._tenants = slo_mod.TenantScheduler(
                weights=slo_mod.TenantScheduler.parse_weights(
                    pathway_config.tenant_weights
                    if tenant_weights is None else str(tenant_weights)
                ),
                budget_tokens=int(
                    pathway_config.tenant_budget
                    if tenant_budget is None else tenant_budget
                ),
            )
        # preempted requests' parked KV: req -> (block row, admit cover).
        # Paged mode keeps the allocator refs alive so re-admission
        # reuses the computed prompt KV by table edit; classified apart
        # from fragmentation via the kv_parked_bytes gauge.
        self._parked: dict = {}
        self._parked_blocks = 0
        self._admit_seq = 0  # admission order, newest-first preemption
        # id(req) -> (tenant, charged tokens): the credit must match
        # the charge even after EOS/degradation mutate req.max_new
        self._charged: dict[int, tuple[str, int]] = {}
        # prefix KV cache (PATHWAY_TPU_PREFIX_CACHE): admission matches a
        # prompt's longest block-aligned cached prefix in a host radix
        # tree and SEEDS the slot's KV from a device arena instead of
        # re-prefilling it; only the uncached suffix pays prefill. The
        # cached path rides the chunked-prefill piece machinery (a hit
        # admits right-padded so token i sits at cache column i — the
        # arena layout), so it requires chunked prefill; with the flag
        # off the admission path is byte-identical to before.
        import numpy as _np_mod

        self.prefix = None
        self.prefix_block = 0
        want_prefix = (
            pathway_config.prefix_cache
            if prefix_cache is None else bool(prefix_cache)
        )
        if want_prefix and self.chunked_prefill:
            from pathway_tpu.engine.prefix_cache import PrefixCache

            mb = (
                pathway_config.prefix_cache_mb
                if prefix_cache_mb is None else float(prefix_cache_mb)
            )
            blk = (
                pathway_config.prefix_block
                if prefix_block is None else int(prefix_block)
            )
            # block must be a pow2 multiple of the prefill chunk: cached
            # prefixes then end on piece boundaries, so the right-padded
            # suffix never writes past the prompt's pow2 bucket. Paged
            # mode pins it to the POOL block — a cached block there IS a
            # pool block (adopted zero-copy), so the sizes must agree.
            blk = next_pow2(max(blk, self.prefill_chunk), self.prefill_chunk)
            if self.paged_kv:
                blk = self.paged_block
            itemsize = _np_mod.dtype(cfg.dtype).itemsize
            # int8 KV: each cached head-token costs head_dim int8 bytes
            # plus one f32 scale instead of head_dim full-precision
            # bytes, so the same MB budget holds ~2x the blocks
            per_tok = (
                cfg.head_dim + 4 if self.kv_quant
                else cfg.head_dim * itemsize
            )
            block_bytes = 2 * cfg.layers * cfg.heads * blk * per_tok
            n_blocks = int(mb * (1 << 20) // block_bytes)
            if n_blocks >= 1:
                self.prefix_block = blk
                self._prefix_kwargs = dict(
                    n_blocks=n_blocks, block=blk, block_bytes=block_bytes
                )
                # two-tier cache (PATHWAY_TPU_PREFIX_T2_MB): eviction
                # demotes leaf edges to a host np block store; the
                # export callback device_gets the blocks' KV bytes.
                # Budget 0 is the byte-identical single-tier kill switch
                # (tests/test_prefix_cache.py).
                t2_mb = (
                    pathway_config.prefix_t2_mb
                    if prefix_t2_mb is None else float(prefix_t2_mb)
                )
                t2_blocks = int(t2_mb * (1 << 20) // block_bytes)
                if t2_blocks >= 1:
                    self._prefix_kwargs["tier2_blocks"] = t2_blocks
                    self._prefix_kwargs["export"] = self._export_blocks
                self.prefix = self._make_prefix_cache()
        # request -> radix node whose root-path the request has pinned
        # (released when the request completes)
        self._prefix_nodes: dict = {}
        # tier-2 promotion pipeline: admission-time tier-2 hits stage
        # their host blobs to the device OFF-THREAD on the PR-2 h2d
        # StageWorker; the loop adopts staged blobs into the tree/arena
        # between ticks (_drain_promotions). _t2_pending counts hits not
        # yet adopted, so tests/bench can quiesce (t2_drain).
        self._promote_worker = None
        self._promote_ready: deque = deque()
        self._t2_pending = 0
        self._export_jits: dict = {}
        self._import_jits: dict = {}
        if self.prefix is not None and self.prefix.tier2 is not None:
            from pathway_tpu.engine.async_runtime import StageWorker

            self._promote_worker = StageWorker(
                fn=self._stage_promotion, maxsize=4, name="prefix-t2-h2d"
            )
        # per-block KV device footprint (the kv_parked_bytes gauge's
        # multiplier; paged mode only — dense preemption has no blocks
        # to park)
        per_tok_kv = (
            cfg.head_dim + 4 if self.kv_quant
            else cfg.head_dim * _np_mod.dtype(cfg.dtype).itemsize
        )
        self._block_kv_bytes = (
            2 * cfg.layers * cfg.heads * self.paged_block * per_tok_kv
            if self.paged_kv else 0
        )
        # autotune candidates: halvings of the constructor's chunk_steps
        # down to 4 — all <= chunk_steps, so the cache-slack sizing above
        # stays valid for every candidate
        cands, c = [], chunk_steps
        while c >= 4:
            cands.append(c)
            c //= 2
        self._step_cands = cands or [chunk_steps]
        self._arrival_ema: float | None = None
        self._last_submit_t: float | None = None
        self._step_wall_ema: float | None = None
        self._last_dispatch_t: float | None = None
        self._last_dispatch_steps = 0
        self._D = decoder_mod
        # serving mesh (PATHWAY_TPU_MESH): resolved ONCE here. Params
        # and the pool COMMIT onto the (data, fsdp, tp) mesh with
        # NamedSharding (Megatron tp over heads/ffn/vocab, fsdp over
        # the remainder, the KV pool's head axis over tp); every jitted
        # pool op below then inherits the layout through GSPMD sharding
        # propagation, and donation carries it across dispatches. Off —
        # or on a 1x1x1 mesh — placement degenerates to single-chip and
        # tokens are byte-identical (tests/test_mesh_serving.py).
        from pathway_tpu.parallel.mesh import serving_mesh_from_flags

        self.mesh = mesh if mesh is not None else serving_mesh_from_flags()
        # already-quantized params arrive from TPUDecoderChat; the string
        # is carried for stats/traces only — the format marker on the
        # pytree itself (``wte_scale``) is what the forward paths read
        self.weight_quant = weight_quant
        if self.mesh is not None:
            self.params = decoder_mod.shard_decoder_params(
                self.params, cfg, self.mesh
            )
        self.pool = self._build_pool()
        self.kv_bytes_saved = 0
        if self.kv_quant:
            # ledger the HBM the int8 pool did NOT allocate vs the same
            # pool at full precision (recorded once; bench surfaces it)
            from pathway_tpu.engine.probes import record_spec

            it = _np_mod.dtype(cfg.dtype).itemsize
            base = sum(
                int(self.pool[c].size) * it
                for c in ("k", "v", "kb", "vb", "arena_k", "arena_v")
                if c in self.pool
            )
            self.kv_bytes_saved = base - decoder_mod.pool_bytes(self.pool)
            record_spec("kv_bytes_saved", self.kv_bytes_saved)
        # HBM ledger: per-component, PER-DEVICE footprint of the pool
        # just built (slot caches / dequant scales / prefix arena).
        # Recorded once here — never on the per-token path — feeding
        # `hbm_bytes{component=,device=}` and the per-device high-water.
        # Single-chip everything lands on device "0", which keeps the
        # component-aggregated gauges byte-identical to the PR-9 ledger.
        from pathway_tpu.engine.probes import record_hbm

        for comp, per_dev in decoder_mod.pool_component_device_bytes(
            self.pool
        ).items():
            for dev, nbytes in per_dev.items():
                record_hbm(comp, nbytes, device=dev)
        # the decoder weights component, re-recorded post-shard so the
        # per-device split reflects the actual mesh placement (TPUDecoder
        # Chat recorded the pre-shard single-device view at device_put)
        for dev, nbytes in decoder_mod.params_device_bytes(
            self.params
        ).items():
            record_hbm("weights.decoder", nbytes, device=dev)
        self._admit_fns: dict = {}
        self._admit_batch_fns: dict = {}
        self._prefill_fns: dict = {}
        self._admit_cached_fns: dict = {}
        self._extract_fns: dict = {}
        # paged-mode jitted table editors (block shapes are static, so
        # each is a singleton): admission seed (table row + cached-column
        # mask) and the free-time row clear back to the sentinel block
        self._paged_seed_jit = None
        self._table_clear_jit = None
        # slot -> (remaining prefill pieces, n_prompt); drained one piece
        # per loop tick so prefill interleaves with decode chunks
        self._pending_prefill: dict[int, tuple] = {}
        # per-slot DISPATCHED decode steps since admission (eager refill)
        self._sent = [0] * n_slots
        self._temperature = temperature
        self._top_k = top_k
        self._top_p = top_p
        # n_steps -> jitted decode-chunk executable. The pool is donated:
        # the KV caches are the dominant HBM object and the loop is pure
        # state-in/state-out — without donation every chunk would copy the
        # whole pool and double peak memory.
        self._chunk_fns: dict[int, Any] = {}
        # n_cycles -> jitted spec draft/verify/accept executable
        self._spec_fns: dict[int, Any] = {}
        self._key = jax.random.PRNGKey(seed)
        self._ticks = 0
        from pathway_tpu.analysis.runtime import make_lock

        self.queue: deque = deque()
        self.slots: list = [None] * n_slots
        self.free = list(range(n_slots))
        self.lock = make_lock("decode_server.lock")
        self.wake = threading.Event()
        self._stop = False
        self.failed: BaseException | None = None
        # fault tolerance (all flags read ONCE here, so the serving hot
        # path never touches the environment): supervision gates both
        # per-request isolation and bounded loop restarts; deadlines and
        # the queue watermark shed instead of blocking; the degradation
        # ladder follows the SLO watchdog's alert state. Every default
        # keeps the pre-supervision behavior byte-identical
        # (tests/test_chaos.py pins it).
        self._restart_budget = int(pathway_config.serve_restarts)
        self._supervised = self._restart_budget > 0
        self._retry_budget = int(pathway_config.serve_retries)
        self._deadline_s = float(pathway_config.request_deadline_ms) / 1e3
        self._queue_bound = int(pathway_config.serve_queue)
        self._default_max_new = int(default_max_new)
        self._degradation_level = 0
        self._degrade = None
        if pathway_config.degradation:
            from pathway_tpu.engine import slo as slo_mod

            self._degrade = slo_mod.get_degradation_controller()
        from pathway_tpu.engine import chaos as chaos_mod

        self._chaos_admit = chaos_mod.site("decode.admit")
        self._chaos_dispatch = chaos_mod.site("decode.dispatch")
        self.stats = {
            "chunks": 0, "admitted": 0, "steps": 0,
            "slot_steps_total": 0, "prefill_chunks": 0,
            "admit_dispatches": 0, "prefix_hit_tokens": 0,
            "prefix_miss_tokens": 0, "prefix_hit_requests": 0,
            "prefix_requests": 0, "spec_dispatches": 0,
            "spec_cycles": 0, "spec_drafted": 0, "spec_accepted": 0,
            "spec_emitted": 0, "spec_verify_steps": 0,
            "restarts": 0, "request_failures": 0, "request_retries": 0,
            "shed": 0, "leaked_thread": 0, "paged_oom": 0,
            "preemptions": 0, "kv_migrated_blocks": 0,
            "t2_hit_requests": 0, "t2_promoted_blocks": 0,
        }
        # in-flight chunk records, oldest first; an attribute (not a loop
        # local) so the failure sweep can fail eagerly-freed requests
        # whose tokens never drained
        self._inflight: deque = deque()
        # tags this server's request spans in the global trace ring
        self._trace_tag = f"decode:{id(self):x}"
        self.thread = threading.Thread(
            target=self._run_safe, daemon=True, name="pathway:decoder-serve"
        )
        self.thread.start()

    def recent_traces(self, n: int | None = None) -> list[dict]:
        """Completed per-request spans of THIS server (oldest first),
        from the bounded global trace ring (``PATHWAY_TPU_TRACE_RING``).
        Empty under ``PATHWAY_TPU_METRICS=0``."""
        from pathway_tpu.engine import tracing

        return tracing.recent_traces(server=self._trace_tag, n=n)

    def _build_pool(self):
        """A fresh ``pool_init`` state sized for this server — used at
        construction and again by the supervised restart path (a crash
        mid-dispatch may have invalidated the donated pool buffers).
        Paged mode instead builds ``paged_pool_init`` plus a fresh host
        ``BlockAllocator``; the block count defaults to the dense pool's
        capacity (every slot's full table plus the prefix budget plus
        the sentinel), and ``PATHWAY_TPU_PAGED_KV_BLOCKS`` overrides it
        for oversubscription (allocator raises ``PagedPoolOOM`` when a
        burst doesn't fit — admission parks the request)."""
        if self.paged_kv:
            per_slot = self.cache_len // self.paged_block
            auto = self.n_slots * per_slot + (
                self.prefix.capacity_blocks if self.prefix is not None else 0
            ) + 1
            self._total_blocks = max(2, self._paged_blocks_override or auto)
            self._allocator = self._D.BlockAllocator(self._total_blocks)
            self._slot_blocks = {}
            self._paged_seed_jit = None
            self._table_clear_jit = None
            pool = self._D.paged_pool_init(
                self.params, self.cfg, self.n_slots, self.cache_len,
                n_blocks=self._total_blocks, block=self.paged_block,
                kv_quant=bool(self.kv_quant),
            )
        else:
            pool = self._D.pool_init(
                self.params, self.cfg, self.n_slots, self.cache_len,
                arena_blocks=(
                    self.prefix.capacity_blocks if self.prefix else 0
                ),
                arena_block=self.prefix_block,
                kv_quant=bool(self.kv_quant),
            )
        # commit the pool onto the serving mesh (head axis over tp) —
        # no-op off-mesh; the supervised restart path lands here too,
        # so a rebuilt pool re-shards identically
        return self._D.shard_pool(pool, self.cfg, self.mesh)

    def _make_prefix_cache(self):
        """The prefix tree for this server: arena-backed normally;
        ADOPTED in paged mode — cached ids are global-pool blocks held
        through the allocator's pin/release refcounts (the lambdas
        late-bind ``self._allocator`` so a supervised pool rebuild swaps
        the allocator under the same tree factory)."""
        from pathway_tpu.engine.prefix_cache import PrefixCache

        kw = dict(self._prefix_kwargs)
        if self.paged_kv:
            kw["pin"] = lambda ids: self._allocator.pin(ids)
            kw["unpin"] = lambda ids: self._allocator.release(ids)
        return PrefixCache(**kw)

    def _paged_seed_fn(self):
        """Jitted paged admission seed: install a slot's block-table row
        and its cached-column mask in one donated table edit
        (``paged_admit_cached`` — COW, no KV bytes move)."""
        if self._paged_seed_jit is None:
            import jax

            D = self._D

            def seed(pool, slot, row, n_cached):
                return D.paged_admit_cached(pool, slot, row, n_cached)

            self._paged_seed_jit = jax.jit(seed, donate_argnums=(0,))
        return self._paged_seed_jit

    def _table_clear_fn(self):
        """Jitted free-time row clear: point every entry of a freed
        slot's table row at the sentinel block BEFORE its blocks return
        to the allocator. Without this, a stale row and a new owner's
        row could reference the same physical block and the
        gather-run-scatter round trip would write both copies back in
        nondeterministic order."""
        if self._table_clear_jit is None:
            import jax
            import jax.numpy as jnp

            D = self._D
            M = self.cache_len // self.paged_block

            def clear(pool, slot):
                return D.paged_table_set(
                    pool, slot, jnp.zeros((M,), jnp.int32)
                )

            self._table_clear_jit = jax.jit(clear, donate_argnums=(0,))
        return self._table_clear_jit

    def _release_slot_kv(self, slot: int) -> None:
        """Host-side KV bookkeeping when a slot frees: drop its
        fragmentation cover and, in paged mode, clear its table row and
        release its block references (blocks a prefix node still pins
        stay resident)."""
        self._slot_cover.pop(slot, None)
        if self._allocator is not None:
            row = self._slot_blocks.pop(slot, None)
            if row:
                import numpy as np

                self.pool = self._table_clear_fn()(
                    self.pool, np.int32(slot)
                )
                self._allocator.release(row)
        self._update_fragmentation()

    def _update_fragmentation(self) -> None:
        """Refresh the ``kv_fragmentation`` gauge: 1 - reachable/allocated
        KV bytes over the active slots. A dense slot always allocates the
        full ``cache_len`` row; a paged slot allocates only its table's
        blocks, so the gauge is the direct HBM-stranding comparison the
        bench surfaces (``serving.kv_fragmentation``)."""
        from pathway_tpu.engine.probes import record_kv_fragmentation

        covers = self._slot_cover
        if not covers:
            frag = 0.0
        else:
            needed = sum(covers.values())
            if self.paged_kv:
                alloc = sum(
                    len(self._slot_blocks.get(s, ())) * self.paged_block
                    for s in covers
                )
            else:
                alloc = len(covers) * self.cache_len
            frag = max(0.0, 1.0 - needed / alloc) if alloc else 0.0
            self._frag_sum += frag
            self._frag_n += 1
        self._kv_frag = frag
        record_kv_fragmentation(frag, server=self._trace_tag)

    def kv_fragmentation(self) -> dict:
        """Current and admission-averaged stranded-KV fraction."""
        return {
            "current": float(self._kv_frag),
            "mean": (self._frag_sum / self._frag_n) if self._frag_n else 0.0,
        }

    def _recover_after_crash(self, exc: BaseException) -> None:
        """Reset the server to an admittable state after a loop-scoped
        crash: rebuild the device pool, clear the host slot/prefill/
        in-flight bookkeeping, drop the (now-unbacked) prefix tree, and
        re-queue every interrupted request within its retry budget."""
        from pathway_tpu.engine import probes
        from pathway_tpu.internals.errors import get_global_error_log

        get_global_error_log().log(
            f"decoder serving loop crashed "
            f"({type(exc).__name__}: {exc}); supervised restart"
        )
        probes.REGISTRY.counter_add(
            "serve_restarts", server=self._trace_tag
        )
        victims: list = []
        with self.lock:
            for rec in list(self._inflight):
                victims.extend(r for r in rec[2] if r is not None)
            self._inflight.clear()
            victims.extend(r for r in self.slots if r is not None)
            for i in range(self.n_slots):
                self.slots[i] = None
            self.free = list(range(self.n_slots))
            self.stats["restarts"] += 1
        self._pending_prefill.clear()
        self._sent = [0] * self.n_slots
        self._slot_cover.clear()
        self._slot_blocks.clear()
        # parked rows and staged promotions died with the allocator/
        # pool the rebuild below replaces — drop WITHOUT releasing
        self._parked.clear()
        self._parked_blocks = 0
        self._record_parked()
        self._promote_ready.clear()
        with self.lock:
            self._t2_pending = 0
        self.pool = self._build_pool()
        # the rebuilt pool's prefix arena/allocator is empty: reset the
        # host radix tree to match (prefix_reset also drops the
        # per-request pins). unpin=False — the old tree's block pins
        # died with the allocator _build_pool just replaced, so they
        # must NOT release into the fresh one.
        self.prefix_reset(unpin=False)
        self._update_fragmentation()
        seen: set[int] = set()
        requeue: list = []
        for req in victims:
            if id(req) in seen or req.done.is_set():
                continue
            seen.add(id(req))
            self._tenant_credit(req)  # re-charged at re-admission
            req.retries += 1
            if req.retries <= self._retry_budget:
                # restart re-decodes from the prompt: drop partial output
                req.tokens = []
                req.first_token_at = None
                req.span.event("restart_requeue", attempt=req.retries)
                probes.REGISTRY.counter_add(
                    "requests_isolated", outcome="retried"
                )
                with self.lock:
                    self.stats["request_retries"] += 1
                requeue.append(req)
            else:
                self._fail_request(req, "failed")
        with self.lock:
            for req in reversed(requeue):
                self.queue.appendleft(req)

    def _fail_request(self, req, reason: str) -> None:
        """Terminal failure of ONE request (server keeps serving): the
        text=None sentinel plus a structured reason for the REST layer."""
        from pathway_tpu.engine import probes

        self._discard_parked(req)
        self._tenant_credit(req)
        req.error_reason = reason
        req.text = None
        probes.REGISTRY.counter_add(
            "requests_isolated", outcome="failed"
        )
        with self.lock:
            self.stats["request_failures"] += 1
        req.span.finish(error=True, tokens=len(req.tokens))
        req.done.set()

    def _shed_request(self, req, reason: str) -> None:
        """Admission-control shed (deadline / queue_full / degraded):
        terminal, structured, and counted — REST maps it to 503 +
        Retry-After."""
        from pathway_tpu.engine import probes

        self._discard_parked(req)
        self._tenant_credit(req)
        req.error_reason = f"shed:{reason}"
        req.retry_after = 1.0
        req.text = None
        probes.REGISTRY.counter_add("requests_shed", reason=reason)
        with self.lock:
            self.stats["shed"] += 1
        req.span.finish(error=True, tokens=len(req.tokens))
        req.done.set()

    def _isolate_admission_failure(self, slot: int, req, exc: Exception,
                                   active=None) -> None:
        """Rewind ONE request's admission — slot record, pending prefill
        pieces, prefix pins, lane mask — and re-queue it within its
        retry budget; past the budget it fails alone. The rest of the
        pool keeps serving."""
        from pathway_tpu.internals.errors import get_global_error_log

        self.slots[slot] = None
        self._pending_prefill.pop(slot, None)
        if active is not None:
            active[slot] = False
        self._prefix_release(req)
        self._release_slot_kv(slot)
        self._tenant_credit(req)  # re-charged if the requeue re-admits
        with self.lock:
            self.free.append(int(slot))
        req.retries += 1
        if req.retries <= self._retry_budget:
            from pathway_tpu.engine import probes

            req.span.event("retry", error=type(exc).__name__)
            probes.REGISTRY.counter_add(
                "requests_isolated", outcome="retried"
            )
            with self.lock:
                self.stats["request_retries"] += 1
                self.queue.appendleft(req)
        else:
            get_global_error_log().log(
                f"request failed after {req.retries - 1} retries: "
                f"{type(exc).__name__}: {exc}"
            )
            self._fail_request(req, "failed")

    def _run_safe(self):
        try:
            if self._restart_budget > 0:
                # supervised: a crashed loop recovers and re-enters with
                # exponential backoff, up to the restart budget — then
                # (and only then) the failure latches as before
                from pathway_tpu.internals.udfs.retries import (
                    ExponentialBackoffRetryStrategy,
                )

                def cycle():
                    try:
                        self._loop()
                    except Exception as exc:
                        self._recover_after_crash(exc)
                        raise

                ExponentialBackoffRetryStrategy(
                    max_retries=self._restart_budget, initial_delay=20,
                    backoff_factor=2, jitter_ms=10, max_delay_ms=2000,
                ).invoke_sync(cycle)
            else:
                self._loop()
        except BaseException as exc:  # noqa: BLE001 - never hang waiters
            self.failed = exc
            from pathway_tpu.internals.errors import get_global_error_log

            get_global_error_log().log(
                f"decoder serving loop died: {type(exc).__name__}: {exc}"
            )
        finally:
            # whether the loop died or shutdown() stopped it mid-flight:
            # every request still in a slot or queued completes with the
            # error sentinel — a timeout-less resolve wait must never hang
            with self.lock:
                pending = [r for r in self.slots if r is not None]
                pending.extend(self.queue)
                self.queue.clear()
            # eagerly-freed requests live only in the in-flight snapshots
            # until their tokens drain — sweep those too
            for rec in list(self._inflight):
                pending.extend(r for r in rec[2] if r is not None)
            for req in pending:
                if not req.done.is_set():
                    req.text = None  # error sentinel (UDF rows -> ERROR)
                    req.span.finish(error=True, tokens=len(req.tokens))
                    req.done.set()

    def submit(self, prompt_ids: list, max_new: int, *,
               priority: int = 1,
               tenant: str = "default") -> _PendingCompletion:
        import time as time_mod

        from pathway_tpu.engine import tracing

        req = _PendingCompletion(prompt_ids, max_new)
        req.priority = int(priority)
        req.tenant = str(tenant) or "default"
        req.span = tracing.start_span(
            "decode", server=self._trace_tag,
            prompt_tokens=len(prompt_ids), max_new=max_new,
            tenant=req.tenant,
        )
        now = time_mod.perf_counter()
        if self._deadline_s > 0:
            # monotonic, matching the loop's queue sweep clock
            req.deadline = time_mod.monotonic() + self._deadline_s
        shed_reason = None
        with self.lock:
            # checked under the lock: _run_safe drains the queue under it,
            # so a dead server can never strand a late submit
            if self.failed is not None:
                raise RuntimeError(
                    f"decoder serving loop died: {self.failed!r}"
                )
            if self._stop:
                raise RuntimeError("decoder serving loop is shut down")
            if (self._queue_bound > 0
                    and len(self.queue) >= self._queue_bound):
                # over the watermark: shed NOW instead of blocking the
                # submitter or growing the queue past what deadlines
                # could ever drain
                shed_reason = "queue_full"
            elif self._degradation_level >= 3 and req.priority <= 0:
                shed_reason = "degraded"
            else:
                self.queue.append(req)
                # observed arrival rate feeds the chunk-steps autotuner
                if self._last_submit_t is not None:
                    gap = now - self._last_submit_t
                    self._arrival_ema = (
                        gap if self._arrival_ema is None
                        else 0.8 * self._arrival_ema + 0.2 * gap
                    )
                self._last_submit_t = now
        if shed_reason is not None:
            self._shed_request(req, shed_reason)
            return req
        self.wake.set()
        return req

    def occupancy(self) -> float:
        """Active-slot-steps / total-slot-steps across every decode chunk
        dispatched so far: the fraction of the pool's decode compute that
        served live lanes (1.0 = every lane of every chunk was busy)."""
        return self.stats["steps"] / max(self.stats["slot_steps_total"], 1)

    def _record_attn(self, path: str, n_q: int, n_k: int,
                     batch: int = 1, cached_kv: bool = False) -> None:
        """Charge the attention-bytes ledger for one prefill dispatch
        (accounting model, not a hardware counter — see
        probes.record_attn). ``cached_kv=True`` bills KV reads at the
        pool's storage width (int8 under kv_quant)."""
        import numpy as np

        from pathway_tpu.engine.probes import record_attn
        from pathway_tpu.models.flash_attention import (
            attn_bytes_dense,
            attn_bytes_flash,
        )

        cfg = self.cfg
        dense = cfg.layers * attn_bytes_dense(n_q, n_k, cfg.heads,
                                              batch=batch)
        if self.flash_prefill:
            item = 1 if (cached_kv and self.kv_quant) else (
                np.dtype(cfg.dtype).itemsize)
            fl = cfg.layers * attn_bytes_flash(
                n_q, n_k, cfg.heads, cfg.hidden // cfg.heads,
                batch=batch, itemsize=item,
            )
            record_attn(path, fl, saved=dense - fl)
        else:
            record_attn(path, dense)

    def _admit_fn(self, s: int):
        fn = self._admit_fns.get(s)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg
            fl, msh = self.flash_prefill, self.mesh

            def admit(params_, ids, mask, pool, slot):
                return D.pool_admit(params_, ids, mask, pool, slot, cfgc,
                                    flash=fl, mesh=msh)

            fn = jax.jit(admit, donate_argnums=(3,))
            self._admit_fns[s] = fn
        return fn

    def _admit_batch_fn(self, m: int, s: int):
        fn = self._admit_batch_fns.get((m, s))
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg
            fl, msh = self.flash_prefill, self.mesh

            def admit(params_, ids, mask, pool, slots):
                return D.pool_admit_batch(params_, ids, mask, pool, slots,
                                          cfgc, flash=fl, mesh=msh)

            fn = jax.jit(admit, donate_argnums=(3,))
            self._admit_batch_fns[(m, s)] = fn
        return fn

    def _chunk_fn_for(self, steps: int):
        fn = self._chunk_fns.get(steps)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg
            temp, tk, tp = self._temperature, self._top_k, self._top_p
            pk, msh = self.paged_kernel, self.mesh

            def chunk(params_, pool, active, key):
                return D.pool_decode_chunk(
                    params_, pool, active, key, cfgc, steps,
                    temperature=temp, top_k=tk, top_p=tp,
                    paged_kernel=pk, mesh=msh,
                )

            fn = jax.jit(chunk, donate_argnums=(1,))
            self._chunk_fns[steps] = fn
        return fn

    def _spec_fn_for(self, n_cycles: int):
        fn = self._spec_fns.get(n_cycles)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg
            dl, kk = self.spec_draft_layers, self.spec_k

            def spec(params_, pool, active):
                return D.pool_decode_spec(
                    params_, pool, active, cfgc, n_cycles,
                    draft_layers=dl, n_spec=kk,
                )

            fn = jax.jit(spec, donate_argnums=(1,))
            self._spec_fns[n_cycles] = fn
        return fn

    def spec_acceptance(self) -> float:
        """Drained draft-token acceptance rate of this server (0.0 before
        any speculative dispatch drained)."""
        d = self.stats["spec_drafted"]
        return self.stats["spec_accepted"] / d if d else 0.0

    def tokens_per_dispatch(self) -> float:
        """Tokens emitted per full-model lane-cycle (the unit one plain
        decode lane-step also costs; 1.0 is the plain-decode baseline)."""
        v = self.stats["spec_verify_steps"]
        # a plain chunk emits exactly one token per lane-step
        return self.stats["spec_emitted"] / v if v else 1.0

    def _pick_steps(self, queue_len: int) -> int:
        """Decode-chunk step count for this tick. Under queue pressure the
        SMALLEST candidate wins: the next chunk boundary is both the next
        admission opportunity and (pipeline_depth chunks on) the next
        drain/slot-release point, so shorter chunks recycle slots into a
        waiting queue sooner. With no queue, pick the largest candidate
        whose wall time still fits inside ~one observed inter-arrival gap
        (a newcomer waits about one gap at most); an idle trace with no
        arrival estimate keeps the full constructor chunk."""
        if not self.chunk_autotune or len(self._step_cands) == 1:
            return self.chunk_steps
        if queue_len > 0:
            return self._step_cands[-1]
        ia, sw = self._arrival_ema, self._step_wall_ema
        if ia is None or sw is None or sw <= 0.0:
            return self._step_cands[0]
        for c in self._step_cands:
            if c * sw <= ia:
                return c
        return self._step_cands[-1]

    def _prefill_fn(self, t: int, first: bool, last: bool,
                    with_col: bool = False):
        key = (t, first, last, with_col)
        fn = self._prefill_fns.get(key)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg
            fl, msh = self.flash_prefill, self.mesh

            if with_col:
                # cached-path final piece: the prompt's last real token
                # may sit mid-piece (right-padded layout), so its column
                # arrives traced
                def piece(params_, ids, mask, pos, pool, slot, start,
                          n_prompt, last_col):
                    return D.pool_prefill_chunk(
                        params_, ids, mask, pos, pool, slot, start,
                        n_prompt, cfgc, first=first, last=last,
                        last_col=last_col, flash=fl, mesh=msh,
                    )
            else:
                def piece(params_, ids, mask, pos, pool, slot, start,
                          n_prompt):
                    return D.pool_prefill_chunk(
                        params_, ids, mask, pos, pool, slot, start,
                        n_prompt, cfgc, first=first, last=last,
                        flash=fl, mesh=msh,
                    )

            fn = jax.jit(piece, donate_argnums=(4,))
            self._prefill_fns[key] = fn
        return fn

    def _admit_cached_fn(self, m: int):
        fn = self._admit_cached_fns.get(m)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg

            def seed(pool, slot, idxs):
                return D.pool_admit_cached(pool, slot, idxs, cfgc)

            fn = jax.jit(seed, donate_argnums=(0,))
            self._admit_cached_fns[m] = fn
        return fn

    def _extract_fn(self, n: int):
        fn = self._extract_fns.get(n)
        if fn is None:
            import jax

            D, cfgc = self._D, self.cfg

            def extract(pool, slot, start, idxs):
                return D.kv_extract(pool, slot, start, idxs, cfgc)

            fn = jax.jit(extract, donate_argnums=(0,))
            self._extract_fns[n] = fn
        return fn

    def _prefix_insert(self, slot: int, req, e: list, base: int) -> None:
        """Publish ``slot``'s freshly-prefilled full blocks of prompt
        ``e`` into the radix tree + arena. ``base`` is the cache column
        of token 0 (``s - n`` for a left-padded miss admission, 0 for
        the right-padded cached path). Moves the request's ref to the
        deepest node so the whole prefix stays pinned while it decodes."""
        import numpy as np

        from pathway_tpu.engine import probes

        if self.paged_kv:
            # zero-copy adoption: the slot's OWN blocks (its table row)
            # become the cached prefix — the tree pins them through the
            # allocator, no kv_extract dispatch, no duplicate HBM bytes.
            # Right-padded paged admission puts block i of the prompt in
            # row entry i, so the row prefix IS the block_ids argument.
            row = self._slot_blocks.get(slot)
            if row is None:
                return
            nfull = min(len(e) // self.prefix_block, len(row))
            node, _first_new, _new = self.prefix.insert(
                e, n_blocks=nfull, block_ids=row
            )
        else:
            node, first_new, new_ids = self.prefix.insert(e)
            if new_ids:
                self.pool = self._extract_fn(len(new_ids))(
                    self.pool, np.int32(slot),
                    np.int32(base + first_new * self.prefix_block),
                    np.asarray(new_ids, np.int32),
                )
                probes.record_device_dispatch("prefix_extract")
        old = self._prefix_nodes.get(req)
        self.prefix.acquire(node)
        if old is not None:
            self.prefix.release(old)
        self._prefix_nodes[req] = node

    def _prefix_release(self, req) -> None:
        node = self._prefix_nodes.pop(req, None)
        if node is not None and self.prefix is not None:
            self.prefix.release(node)

    def prefix_reset(self, *, unpin: bool = True) -> None:
        """Drop every cached prefix and zero the per-server prefix
        counters (bench: warm up the executables, then measure a clean
        trace). Only call while no requests are in flight. In paged
        mode the tree's adopted blocks unpin back into the allocator;
        the supervised restart path passes ``unpin=False`` because its
        pool rebuild already replaced the allocator the old pins lived
        in."""
        if self.prefix is None:
            return
        self._prefix_nodes.clear()
        if self.paged_kv and unpin:
            self.prefix.reset()
        else:
            self.prefix = self._make_prefix_cache()
        for k in ("prefix_hit_tokens", "prefix_miss_tokens",
                  "prefix_hit_requests", "prefix_requests"):
            self.stats[k] = 0
        # drop staged-but-unadopted promotions with the tree they
        # targeted; items still inside the StageWorker drain later and
        # re-match against the fresh tree (stale paths skip harmlessly)
        while self._promote_ready:
            self._promote_ready.popleft()
            with self.lock:
                self._t2_pending -= 1

    # -- tier-2 promotion pipeline ------------------------------------

    def _export_blocks(self, ids: list) -> dict:
        """Tier-2 demote callback (``PrefixCache(export=...)``): gather
        the KV bytes of the given arena/pool blocks and device_get them
        as per-channel host ``np`` blobs in the ``kv_block_export``
        layout. Runs on the loop thread inside eviction — one gather
        dispatch per demoted edge, amortized over the edge's lifetime."""
        import jax
        import numpy as np

        if self._export_jits.get("fn") is None:
            D = self._D

            def export(pool, idxs):
                return D.kv_block_export(pool, idxs)

            self._export_jits["fn"] = jax.jit(export)
        blobs = self._export_jits["fn"](
            self.pool, np.asarray(ids, np.int32)
        )
        return {c: np.asarray(v) for c, v in blobs.items()}

    def _import_blocks_fn(self):
        """Jitted promotion scatter: write staged block blobs into the
        pool/arena at the freshly-allocated ids (pool donated — same
        state-in/state-out discipline as every other pool edit)."""
        if self._import_jits.get("fn") is None:
            import jax

            D = self._D

            def imp(pool, idxs, blobs):
                return D.kv_block_import(pool, idxs, blobs)

            self._import_jits["fn"] = jax.jit(imp, donate_argnums=(0,))
        return self._import_jits["fn"]

    def _schedule_promotion(self, tokens, j: int, keys: list,
                            blobs: dict) -> None:
        """Queue a tier-2 hit's host blobs for async h2d staging on the
        PR-2 StageWorker; the loop adopts them between ticks."""
        with self.lock:
            self._t2_pending += 1
        try:
            self._promote_worker.submit(
                (list(tokens), int(j), list(keys), blobs)
            )
        except Exception:  # noqa: BLE001 - closed worker at shutdown
            with self.lock:
                self._t2_pending -= 1

    def _stage_promotion(self, item) -> None:
        """StageWorker fn (worker thread — must be total): move the
        blobs host->device off the serving thread so the adoption tick
        only pays a table/arena scatter, never a PCIe copy."""
        import time as time_mod

        import jax

        from pathway_tpu.engine.probes import record_stage

        tokens, j, keys, blobs = item
        try:
            t0 = time_mod.perf_counter()
            staged = {c: jax.device_put(v) for c, v in blobs.items()}
            for v in staged.values():
                v.block_until_ready()
            record_stage("h2d", time_mod.perf_counter() - t0, len(keys))
            self._promote_ready.append((tokens, j, keys, staged))
        except Exception:  # noqa: BLE001 - drop the hit, keep serving
            with self.lock:
                self._t2_pending -= 1
        self.wake.set()

    def _drain_promotions(self) -> None:
        """Adopt every staged promotion (loop thread, once per tick,
        BEFORE admissions — so a request arriving right behind its
        promotion already sees the tier-1 hit)."""
        if self._promote_worker is None:
            return
        from pathway_tpu.internals.errors import get_global_error_log

        while self._promote_ready:
            tokens, j, keys, staged = self._promote_ready.popleft()
            try:
                self._apply_promotion(tokens, j, keys, staged)
            except Exception as exc:  # noqa: BLE001 - best-effort cache
                get_global_error_log().log(
                    f"tier-2 promotion dropped: "
                    f"{type(exc).__name__}: {exc}"
                )
            finally:
                with self.lock:
                    self._t2_pending -= 1

    def _apply_promotion(self, tokens, j: int, keys: list,
                         staged: dict) -> None:
        """Re-insert a staged tier-2 edge into the radix tree and
        scatter its KV bytes into fresh device blocks. The tree may
        have moved since the admission-time lookup (another request
        prefilled the same head), so re-match and keep only the still-
        missing suffix; a path that diverged entirely is dropped — the
        blobs were popped from tier 2 and promotion owns them."""
        import numpy as np

        from pathway_tpu.engine.probes import record_prefix

        if self.prefix is None:
            return
        B = self.prefix_block
        nb = j + len(keys)
        j2, _ids, _node = self.prefix.match(tokens[: nb * B])
        if j2 != j:
            d = j2 - j
            if d < 0 or d >= len(keys):
                return  # stale: the matched path changed under us
            keys = keys[d:]
            staged = {c: v[d:] for c, v in staged.items()}
            j = j2
            nb = j + len(keys)
        if self.paged_kv:
            try:
                ids = self._allocator.alloc(len(keys))
            except self._D.PagedPoolOOM:
                return  # pool is the scarce tier — decode wins
            _node2, _first, new_ids = self.prefix.insert(
                tokens[: nb * B], n_blocks=nb,
                block_ids=[0] * j + ids,
            )
            if new_ids:
                self.pool = self._import_blocks_fn()(
                    self.pool, np.asarray(new_ids, np.int32),
                    {c: v[: len(new_ids)] for c, v in staged.items()},
                )
            # the tree pinned new_ids (adopting insert): drop our own
            # alloc refs so eviction alone governs their lifetime —
            # and free any tail the tree's budget didn't stretch to
            self._allocator.release(ids)
        else:
            _node2, first_new, new_ids = self.prefix.insert(
                tokens[: nb * B], n_blocks=nb
            )
            if not new_ids:
                return
            d = first_new - j
            if d < 0 or d >= len(keys):
                return
            self.pool = self._import_blocks_fn()(
                self.pool, np.asarray(new_ids, np.int32),
                {c: v[d:d + len(new_ids)] for c, v in staged.items()},
            )
        if new_ids:
            self.stats["t2_promoted_blocks"] += len(new_ids)
            record_prefix("t2_promoted_blocks", len(new_ids))

    def t2_drain(self, timeout: float = 10.0) -> bool:
        """Block until every scheduled tier-2 promotion has been staged
        AND adopted (tests/bench quiesce point); True on success."""
        import time as time_mod

        if self._promote_worker is None:
            return True
        end = time_mod.monotonic() + timeout
        while time_mod.monotonic() < end:
            with self.lock:
                if self._t2_pending <= 0:
                    return True
            self.wake.set()
            time_mod.sleep(0.005)
        return False

    def _t2_probe(self, e: list, n: int, m: int, node) -> None:
        """Admission-time tier-2 lookup past a tier-1 match of ``m``
        blocks. A hit schedules async promotion — THIS request still
        prefills (the blobs are host-side); the NEXT request on the
        same head lands the tier-1 hit."""
        if self.prefix is None or self.prefix.tier2 is None:
            return
        from pathway_tpu.engine.probes import record_prefix

        n_full = (n - 1) // self.prefix_block
        if m >= n_full:
            return
        record_prefix("t2_lookups", 1)
        hit = self.prefix.match_t2(e, n_full, node, m)
        if hit is None:
            return
        keys, blobs = hit
        record_prefix("t2_hits", 1)
        self.stats["t2_hit_requests"] += 1
        self._schedule_promotion(e, m, keys, blobs)

    # -- multi-tenant budgets & preemption ----------------------------

    def _tenant_charge(self, req) -> None:
        """Admission charges the request's full decode budget against
        its tenant; the amount is remembered so the credit matches even
        after EOS/degradation mutate ``req.max_new``."""
        if self._tenants is None:
            return
        amt = int(req.max_new)
        self._tenants.charge(req.tenant, amt)
        self._charged[id(req)] = (req.tenant, amt)

    def _tenant_credit(self, req) -> None:
        if self._tenants is None:
            return
        rec = self._charged.pop(id(req), None)
        if rec is not None:
            self._tenants.credit(rec[0], rec[1])

    def _record_parked(self) -> None:
        """Refresh the ``kv_parked_bytes`` gauge: preempted requests'
        parked blocks are HELD ON PURPOSE, so they are classified apart
        from the fragmentation (stranded-bytes) signal."""
        from pathway_tpu.engine.probes import record_kv_parked

        record_kv_parked(
            self._parked_blocks * self._block_kv_bytes,
            server=self._trace_tag,
        )

    def _discard_parked(self, req) -> None:
        """Release a preempted request's parked blocks (terminal paths:
        fail/shed — the KV will never be re-admitted)."""
        row = self._parked.pop(req, None)
        if row is None:
            return
        self._parked_blocks -= len(row)
        if self._allocator is not None:
            self._allocator.release(row)
        self._record_parked()

    def _preempt_request(self, slot: int, req, active) -> None:
        """Budget preemption: rewind ONE over-budget request's slot via
        the PR-10 isolation machinery, PARK its paged KV blocks (the
        allocator refs stay alive, so re-admission is a table edit plus
        a one-block tail re-prefill — not a full re-prefill), and
        requeue it at the head. Preemption is a scheduling decision,
        not a failure: the request is never shed and never counts
        against its retry budget."""
        import numpy as np

        from pathway_tpu.engine import probes

        req.span.event("preempt", slot=int(slot), tenant=req.tenant)
        self.slots[slot] = None
        self._pending_prefill.pop(slot, None)
        active[slot] = False
        self._sent[slot] = 0
        self._prefix_release(req)
        self._slot_cover.pop(slot, None)
        if self._allocator is not None:
            row = self._slot_blocks.pop(slot, None)
            if row:
                self.pool = self._table_clear_fn()(
                    self.pool, np.int32(slot)
                )
                # refs are KEPT: the blocks park instead of freeing
                self._parked[req] = row
                self._parked_blocks += len(row)
                self._record_parked()
        self._update_fragmentation()
        # null the request out of the in-flight snapshots: tokens from
        # chunks already dispatched must not drain into the rewound
        # stream (re-admission re-decodes them byte-identically)
        for rec in self._inflight:
            snap = rec[2]
            for i, r in enumerate(snap):
                if r is req:
                    snap[i] = None
        req.tokens = []
        req.first_token_at = None
        self._tenant_credit(req)
        probes.REGISTRY.counter_add("preemptions", tenant=req.tenant)
        with self.lock:
            self.stats["preemptions"] += 1
            self.free.append(int(slot))
            self.queue.appendleft(req)

    def _maybe_preempt(self, active) -> None:
        """Escalated budget enforcement: when a queued ELIGIBLE tenant
        would admit but every slot is busy and some tenant is over its
        token budget, preempt that tenant's newest-admitted decode-lane
        request (newest-first keeps the most-finished work running).
        Slots still mid-prefill are never victims — their parked rows
        would hold uncomputed KV."""
        if self._tenants is None or self._tenants.budget_tokens <= 0:
            return
        with self.lock:
            if not self.queue or self.free:
                return
            entries = [(r.tenant, r.max_new) for r in self.queue]
        if self._tenants.select(entries, charge=False) is None:
            return  # every waiter is itself over budget — hold
        victim = None
        for slot, req in enumerate(self.slots):
            if (req is None or req.done.is_set()
                    or slot in self._pending_prefill):
                continue
            if not self._tenants.over_budget(req.tenant):
                continue
            if victim is None or req.seq > self.slots[victim].seq:
                victim = slot
        if victim is not None:
            self._preempt_request(victim, self.slots[victim], active)

    # -- lane / tenant observability ----------------------------------

    def lane_stats(self) -> dict:
        """Per-lane occupancy snapshot: slots mid-prompt (prefill lane)
        vs slots emitting (decode lane)."""
        return dict(self._lane_counts)

    def tenant_depths(self) -> dict:
        """Queued requests per tenant (scrape/panel feed)."""
        with self.lock:
            depth: dict[str, int] = {}
            for r in self.queue:
                depth[r.tenant] = depth.get(r.tenant, 0) + 1
        return depth

    def _admit_one(self, slot: int, req, direct: list,
                   direct_inserts: list) -> None:
        """Admission host work for ONE request — prefix match, cached
        seeding, prompt padding, prefill scheduling. A method (not loop
        body) so supervised serving can isolate a request-scoped fault
        here to this request alone."""
        import numpy as np

        from pathway_tpu.engine.probes import record_prefix
        from pathway_tpu.ops import next_pow2

        e = req.ids[-self.max_prompt_bucket:]
        n = len(e)
        req.span.event("admit", slot=int(slot))
        if self._degradation_level >= 1:
            # ladder level 1+: clamp the answer budget so slots recycle
            # sooner while the SLO alert is firing
            req.max_new = min(
                req.max_new, max(1, self._default_max_new // 2)
            )
        if self.paged_kv:
            self._admit_one_paged(slot, req, e, n)
            return
        # reachable span for the fragmentation gauge: a dense slot pins
        # the whole cache_len row regardless
        self._slot_cover[slot] = min(
            self.cache_len,
            n + req.max_new + (self.pipeline_depth + 1) * self._slack,
        )
        self._update_fragmentation()
        B = self.prefix_block
        # prefix-cache accounting + match. A hit never reuses the
        # prompt's FINAL (partial or last-full) block: at least
        # one suffix token must run through pool_prefill_chunk to
        # produce the first-token logits.
        m_hit, arena_ids, node = 0, [], None
        if self.prefix is not None and n > B:
            m, arena_ids, node = self.prefix.match(e)
            m_hit = min(m, (n - 1) // B)
            hit_t = m_hit * B
            record_prefix("requests", 1)
            record_prefix("hit_tokens", hit_t)
            record_prefix("miss_tokens", n - hit_t)
            if m_hit:
                record_prefix("hit_requests", 1)
                self.stats["prefix_hit_requests"] += 1
            self.stats["prefix_requests"] += 1
            self.stats["prefix_hit_tokens"] += hit_t
            self.stats["prefix_miss_tokens"] += n - hit_t
            req.span.event(
                "prefix_match", hit_blocks=int(m_hit),
                hit_tokens=int(hit_t), miss_tokens=int(n - hit_t),
            )
            # tier-2 continuation past the tier-1 match (uncapped m:
            # the probe extends from the true matched depth)
            self._t2_probe(e, n, m, node)
        if m_hit >= 1:
            # cache hit: pin the matched path, seed the slot's
            # cache columns [0, m_hit*B) straight from the arena
            # (one copy dispatch, no compute), then prefill only
            # the suffix — RIGHT-padded, so token i sits at cache
            # column i exactly like the arena blocks expect.
            self.prefix.acquire(node)
            self._prefix_nodes[req] = node
            self.pool = self._admit_cached_fn(m_hit)(
                self.pool, np.int32(slot),
                np.asarray(arena_ids[:m_hit], np.int32),
            )
            # the seed COPIES arena blocks into the slot row: those KV
            # bytes now exist twice in HBM until the slot frees. The
            # ledger makes the double-count visible (the paged pool's
            # copy-on-write tables drive it to zero).
            record_prefix("copy_bytes", m_hit * self.prefix.block_bytes)
            n_cached = m_hit * B
            P = self.prefill_chunk
            W = n_cached + -((n_cached - n) // P) * P
            r_ids = np.zeros((1, W), np.int32)
            r_mask = np.zeros((1, W), np.int32)
            r_ids[0, :n] = e
            r_mask[0, :n] = 1
            pos = np.minimum(
                np.arange(W), n - 1
            )[None, :].astype(np.int32)
            n_prompt = np.asarray([n], np.int32)
            pieces = [
                (r_ids[:, o:o + P], r_mask[:, o:o + P],
                 pos[:, o:o + P], o)
                for o in range(n_cached, W, P)
            ]
            # the final piece may end on pad columns: the real
            # last token's in-piece column rides along traced
            # (None when it IS the final column — static path)
            lc = (n - 1) - (W - P)
            meta = {
                "last_col": None if lc == P - 1 else lc,
                "insert": (req, e, 0),
            }
            self._pending_prefill[slot] = (pieces, n_prompt, meta)
            self.stats["admitted"] += 1
            return
        ins = (
            (req, e, 0) if self.prefix is not None and n >= B
            else None
        )
        s = max(8, next_pow2(max(len(e), 1), 8))
        ids = np.zeros((1, s), np.int32)
        mask = np.zeros((1, s), np.int32)
        if e:
            ids[0, s - len(e):] = e
            mask[0, s - len(e):] = 1
        else:
            mask[0, -1] = 1
        if ins is not None:
            # left-padded admission: token 0 sits at column s-n
            ins = (req, e, s - n)
        if self.chunked_prefill and s > self.prefill_chunk:
            # split into fixed-size pieces, dispatched ONE per
            # loop tick below — the active lanes keep decoding
            # between pieces instead of stalling for the whole
            # prompt's prefill
            pos = np.clip(
                np.cumsum(mask[0]) - 1, 0, None
            )[None, :].astype(np.int32)
            n_prompt = np.asarray([int(mask.sum())], np.int32)
            P = self.prefill_chunk
            pieces = [
                (ids[:, o:o + P], mask[:, o:o + P], pos[:, o:o + P], o)
                for o in range(0, s, P)
            ]
            meta = {"insert": ins} if ins is not None else None
            self._pending_prefill[slot] = (pieces, n_prompt, meta)
        else:
            direct.append((slot, ids, mask, s))
            if ins is not None:
                direct_inserts.append((slot, ins))
        self.stats["admitted"] += 1

    def _unpark(self, slot: int, req, e: list, n: int,
                row: list) -> bool:
        """Re-admit a preempted request onto its own parked block row:
        the prompt's full blocks still hold their computed KV (the
        refs never dropped), so admission is one table edit plus a
        re-prefill of the final partial block — that last piece is
        what regenerates the first-token logits the rewound stream
        needs. Returns False when the row no longer fits the (possibly
        degradation-clamped) budget."""
        import numpy as np

        B = self.paged_block
        per_slot = self.cache_len // B
        cover = min(
            self.cache_len,
            n + req.max_new + (self.pipeline_depth + 1) * self._slack,
        )
        need = min(per_slot, -(-cover // B))
        if len(row) != need:
            return False
        self._slot_blocks[slot] = row
        self._slot_cover[slot] = cover
        n_cached = ((n - 1) // B) * B
        row_arr = np.zeros((per_slot,), np.int32)
        row_arr[:len(row)] = row
        self.pool = self._paged_seed_fn()(
            self.pool, np.int32(slot), row_arr, np.int32(n_cached)
        )
        req.span.event("unpark", blocks=len(row), cached=int(n_cached))
        P = self.prefill_chunk
        W = n_cached + -((n_cached - n) // P) * P
        r_ids = np.zeros((1, W), np.int32)
        r_mask = np.zeros((1, W), np.int32)
        r_ids[0, :n] = e
        r_mask[0, :n] = 1
        pos = np.minimum(np.arange(W), n - 1)[None, :].astype(np.int32)
        n_prompt = np.asarray([n], np.int32)
        pieces = [
            (r_ids[:, o:o + P], r_mask[:, o:o + P], pos[:, o:o + P], o)
            for o in range(n_cached, W, P)
        ]
        lc = (n - 1) - (W - P)
        meta = {"last_col": None if lc == P - 1 else lc}
        if self.prefix is not None and n >= B:
            meta["insert"] = (req, e, 0)
        self._pending_prefill[slot] = (pieces, n_prompt, meta)
        self.stats["admitted"] += 1
        self._update_fragmentation()
        return True

    def _admit_one_paged(self, slot: int, req, e: list, n: int) -> None:
        """Paged admission: allocate exactly the blocks this request can
        reach, install the slot's block-table row, seed any cached
        prefix by SHARING blocks (copy-on-write pins — no arena copy
        dispatch), and schedule the prompt as right-padded prefill
        pieces. Every paged admission right-pads (token i at cache
        column i): that is the layout invariant that lets a finished
        prompt's blocks publish into the prefix tree zero-copy. On
        ``PagedPoolOOM`` nothing has been written — the request parks
        at the queue head until blocks free up."""
        import numpy as np

        from pathway_tpu.engine.probes import record_prefix

        if not e:
            # degenerate empty prompt: one pad token at column 0 (the
            # dense path's mask-only-last-column admission computes the
            # same single-token attention)
            e, n = [0], 1
        B = self.paged_block
        per_slot = self.cache_len // B
        parked = self._parked.pop(req, None)
        if parked is not None:
            self._parked_blocks -= len(parked)
            self._record_parked()
            if self._unpark(slot, req, e, n, parked):
                return
            # the budget changed under degradation and the row no
            # longer fits the request — fall through to a fresh
            # admission (the parked KV is lost, correctness is not)
            self._allocator.release(parked)
        m_hit, pool_ids, node = 0, [], None
        if self.prefix is not None and n > B:
            m, pool_ids, node = self.prefix.match(e)
            m_hit = min(m, (n - 1) // B)
            hit_t = m_hit * B
            record_prefix("requests", 1)
            record_prefix("hit_tokens", hit_t)
            record_prefix("miss_tokens", n - hit_t)
            if m_hit:
                record_prefix("hit_requests", 1)
                self.stats["prefix_hit_requests"] += 1
            self.stats["prefix_requests"] += 1
            self.stats["prefix_hit_tokens"] += hit_t
            self.stats["prefix_miss_tokens"] += n - hit_t
            req.span.event(
                "prefix_match", hit_blocks=int(m_hit),
                hit_tokens=int(hit_t), miss_tokens=int(n - hit_t),
            )
            self._t2_probe(e, n, m, node)
        # worst-case columns the lane can write: prompt + its own answer
        # budget + one chunk of overrun slack per in-flight chunk (the
        # same bound that sizes the dense cache_len)
        cover = min(
            self.cache_len,
            n + req.max_new + (self.pipeline_depth + 1) * self._slack,
        )
        need = min(per_slot, -(-cover // B))
        try:
            fresh = self._allocator.alloc(need - m_hit)
        except self._D.PagedPoolOOM as oom:
            self.slots[slot] = None
            with self.lock:
                self.free.append(int(slot))
            if need - m_hit > self._total_blocks - 1:
                # can never fit, even against an idle pool
                self._fail_request(req, "paged_oom")
                return
            req.span.event(
                "paged_oom", want=int(oom.want), free=int(oom.free)
            )
            self.stats["paged_oom"] += 1
            with self.lock:
                self.queue.appendleft(req)
            return
        shared = [int(i) for i in pool_ids[:m_hit]]
        if shared:
            # the slot's OWN reference on the shared blocks — balanced
            # by the release in _release_slot_kv, independent of the
            # tree's pin (which the prefix node's refcount protects)
            self._allocator.pin(shared)
        row = shared + fresh
        self._slot_blocks[slot] = row
        self._slot_cover[slot] = cover
        n_cached = m_hit * B
        row_arr = np.zeros((per_slot,), np.int32)
        row_arr[:len(row)] = row
        # one donated table edit installs the row and the cached-column
        # mask (all-zero mask when n_cached == 0); shared KV bytes never
        # move — suffix and decode writes land past the shared run
        self.pool = self._paged_seed_fn()(
            self.pool, np.int32(slot), row_arr, np.int32(n_cached)
        )
        if m_hit:
            self.prefix.acquire(node)
            self._prefix_nodes[req] = node
        P = self.prefill_chunk
        W = n_cached + -((n_cached - n) // P) * P
        r_ids = np.zeros((1, W), np.int32)
        r_mask = np.zeros((1, W), np.int32)
        r_ids[0, :n] = e
        r_mask[0, :n] = 1
        pos = np.minimum(np.arange(W), n - 1)[None, :].astype(np.int32)
        n_prompt = np.asarray([n], np.int32)
        pieces = [
            (r_ids[:, o:o + P], r_mask[:, o:o + P], pos[:, o:o + P], o)
            for o in range(n_cached, W, P)
        ]
        lc = (n - 1) - (W - P)
        meta = {"last_col": None if lc == P - 1 else lc}
        if self.prefix is not None and n >= B:
            meta["insert"] = (req, e, 0)
        self._pending_prefill[slot] = (pieces, n_prompt, meta)
        self.stats["admitted"] += 1
        self._update_fragmentation()

    def _prefill_piece(self, slot: int, active) -> None:
        """Dispatch one pending prefill piece for ``slot`` (a method so
        supervised serving can rewind just this slot on a fault)."""
        import numpy as np

        pieces, n_prompt, meta = self._pending_prefill[slot]
        p_ids, p_mask, p_pos, off = pieces.pop(0)
        first, last = off == 0, not pieces
        lc = meta.get("last_col") if (meta and last) else None
        if lc is None:
            self.pool = self._prefill_fn(p_ids.shape[1], first, last)(
                self.params, p_ids, p_mask, p_pos, self.pool,
                np.int32(slot), np.int32(off), n_prompt,
            )
        else:
            self.pool = self._prefill_fn(
                p_ids.shape[1], first, last, True
            )(
                self.params, p_ids, p_mask, p_pos, self.pool,
                np.int32(slot), np.int32(off), n_prompt,
                np.int32(lc),
            )
        self.stats["prefill_chunks"] += 1
        self._record_attn("chunk", int(p_ids.shape[1]), self.cache_len,
                          cached_kv=True)
        req_p = self.slots[slot]
        if req_p is not None:
            req_p.span.event(
                "prefill_chunk", offset=int(off),
                width=int(p_ids.shape[1]), last=bool(last),
            )
        if last:
            del self._pending_prefill[slot]
            active[slot] = True
            if self.disagg:
                # lane handoff: the finished prompt's KV migrates from
                # the prefill lane into the decode lane by block-table
                # IDENTITY — zero-copy on one chip (the slot's row is
                # the handoff; kv_block_export/import carry the same
                # blobs for the cross-device fleet case). Counted only
                # under the flag so the kill switch stays stats-clean.
                from pathway_tpu.engine import probes

                nb = (
                    len(self._slot_blocks.get(slot, ()))
                    if self.paged_kv
                    else -(-int(n_prompt[0]) // self.prefill_chunk)
                )
                self.stats["kv_migrated_blocks"] += nb
                probes.REGISTRY.counter_add(
                    "kv_migrated_blocks", nb, server=self._trace_tag
                )
                if req_p is not None:
                    req_p.span.event("migrate", blocks=int(nb))
            if meta and meta.get("insert") is not None:
                req_i, e_i, base_i = meta["insert"]
                self._prefix_insert(slot, req_i, e_i, base_i)

    def _loop(self):
        import time as time_mod

        import jax
        import numpy as np

        from pathway_tpu.engine import probes
        from pathway_tpu.engine.probes import record_spec, record_spec_many

        active = np.zeros(self.n_slots, dtype=bool)
        inflight = self._inflight

        def dispatch_decode() -> bool:
            """One decode chunk over the active lanes; False if none."""
            if not active.any():
                return False
            if self._chaos_dispatch is not None:
                # loop-scoped fault: every in-flight lane is affected, so
                # recovery is a supervised restart, not per-request
                self._chaos_dispatch.maybe_fail()
            with self.lock:
                qlen = len(self.queue)
            steps = self._pick_steps(qlen)
            # tick-to-tick wall per dispatched step: in steady state the
            # host loop is paced by the device finishing chunks, so this
            # approximates chunk wall time for the autotuner
            now = time_mod.perf_counter()
            if self._last_dispatch_t is not None and self._last_dispatch_steps:
                per = (now - self._last_dispatch_t) / self._last_dispatch_steps
                self._step_wall_ema = (
                    per if self._step_wall_ema is None
                    else 0.7 * self._step_wall_ema + 0.3 * per
                )
            self._last_dispatch_t = now
            self._ticks += 1
            if (self.spec_decode and not self._spec_off
                    and self._degradation_level < 2):
                # speculative path: a chunk of `steps` plain lane-steps
                # becomes n_cycles draft/verify/accept cycles — each
                # cycle costs ~one full-model stream (the verify) and
                # emits 1..spec_k+1 tokens per lane, so lane budgets
                # and the autotuner account in CYCLES here
                n_cycles = max(1, steps // (self.spec_k + 1))
                self._last_dispatch_steps = n_cycles
                self.pool, toks_dev, emit_dev = self._spec_fn_for(
                    n_cycles
                )(self.params, self.pool, active)
                payload = (toks_dev, emit_dev)
                lane_steps = n_cycles
                self.stats["spec_dispatches"] += 1
                self.stats["spec_cycles"] += n_cycles
            else:
                self._last_dispatch_steps = steps
                key = jax.random.fold_in(self._key, self._ticks)
                self.pool, toks_dev = self._chunk_fn_for(steps)(
                    self.params, self.pool, active, key
                )
                payload = toks_dev
                emit_dev = None
                lane_steps = steps
            try:
                # start the device->host token copy NOW: the block
                # lands while the next pipeline_depth chunks compute,
                # so the eventual read is local instead of a relay
                # round trip (measured ~100ms -> ~1ms per chunk)
                toks_dev.copy_to_host_async()
                if emit_dev is not None:
                    emit_dev.copy_to_host_async()
            except Exception:  # noqa: BLE001 - platform-optional
                pass
            self.stats["chunks"] += 1
            self.stats["slot_steps_total"] += self.n_slots * lane_steps
            # refresh the occupancy gauge on every 8th chunk (and the
            # first): the panel/scrape readers poll at human timescales,
            # and a per-chunk gauge write is measurable overhead on the
            # dispatch hot path
            if (self.stats["chunks"] & 7) == 1:
                probes.REGISTRY.gauge_set(
                    "serving_occupancy", self.occupancy(),
                    server=self._trace_tag,
                )
                probes.REGISTRY.gauge_set(
                    "lane_occupancy", float(len(self._pending_prefill)),
                    server=self._trace_tag, lane="prefill",
                )
                probes.REGISTRY.gauge_set(
                    "lane_occupancy", float(active.sum()),
                    server=self._trace_tag, lane="decode",
                )
                if self._tenants is not None:
                    for t, d in self.tenant_depths().items():
                        probes.REGISTRY.gauge_set(
                            "tenant_queue_depth", float(d),
                            server=self._trace_tag, tenant=t,
                        )
            # snapshot WHICH request each lane served: by the time
            # these tokens drain the slot may have been freed and
            # re-admitted to a different request
            inflight.append((payload, active.copy(), list(self.slots)))
            for slot in np.nonzero(active)[0]:
                req = self.slots[slot]
                if req is None:
                    continue
                # occupancy numerator counts USEFUL slot-steps only:
                # a lane decoding past its budget while its tokens
                # drain is busy but wasted, exactly the idle-by-
                # another-name this metric exists to expose. Spec
                # cycles count conservatively as one step each (a
                # cycle emits AT LEAST one token), so eager refill
                # never frees a lane before its budget is truly
                # covered by dispatched work.
                self.stats["steps"] += min(
                    lane_steps, max(0, req.max_new - self._sent[slot])
                )
                self._sent[slot] += lane_steps
                if self.eager_refill and self._sent[slot] >= req.max_new:
                    # budget exhaustion is host-knowable at DISPATCH
                    # time: no further chunk can add to this lane's
                    # answer, so free the slot NOW — its tokens drain
                    # from the snapshots — instead of pipeline_depth
                    # chunks later. Device stream ordering makes the
                    # next occupant's prefill overwrite safe: it is
                    # enqueued after this chunk.
                    self.slots[slot] = None
                    active[slot] = False
                    self._release_slot_kv(slot)
                    with self.lock:
                        self.free.append(int(slot))
            return True

        def admit_direct(direct) -> None:
            """One-shot (non-chunked) admissions. With batch admission,
            same-bucket arrivals group into pow2-sized
            ``pool_admit_batch`` dispatches (slots are distinct by
            construction); otherwise one ``pool_admit`` each."""
            if self.batch_admit and len(direct) > 1:
                by_s: dict[int, list] = {}
                for slot, ids, mask, s in direct:
                    by_s.setdefault(s, []).append((slot, ids, mask))
                for s, grp in by_s.items():
                    o = 0
                    while o < len(grp):
                        m = 1 << ((len(grp) - o).bit_length() - 1)
                        part = grp[o:o + m]
                        o += m
                        if m == 1:
                            slot, ids, mask = part[0]
                            self.pool = self._admit_fn(s)(
                                self.params, ids, mask, self.pool,
                                np.int32(slot),
                            )
                        else:
                            ids = np.concatenate([p[1] for p in part], axis=0)
                            mask = np.concatenate([p[2] for p in part], axis=0)
                            slots = np.asarray([p[0] for p in part], np.int32)
                            self.pool = self._admit_batch_fn(m, s)(
                                self.params, ids, mask, self.pool, slots
                            )
                        self._record_attn("prefill", s, s, batch=m)
                        self.stats["admit_dispatches"] += 1
                        for p in part:
                            active[p[0]] = True
            else:
                for slot, ids, mask, s in direct:
                    self.pool = self._admit_fn(s)(
                        self.params, ids, mask, self.pool, np.int32(slot)
                    )
                    self._record_attn("prefill", s, s)
                    self.stats["admit_dispatches"] += 1
                    active[slot] = True

        while not self._stop:
            # decode FIRST (PATHWAY_TPU_PREFILL_OVERLAP, default on): the
            # active lanes' next chunk is on the device before any
            # admission work runs, so newcomer tokenized-prompt prep and
            # prefill dispatches OVERLAP the in-flight decode instead of
            # delaying it. Newcomers join the next chunk — they waited one
            # chunk boundary either way; the chunk just starts earlier.
            dispatched = self.prefill_overlap and dispatch_decode()
            if self._degrade is not None:
                # one rate-limited watchdog read per tick; levels are
                # consumed below (clamp / spec gate / shed)
                self._degradation_level = self._degrade.maybe_evaluate()
            # adopt staged tier-2 promotions BEFORE admissions: a
            # request arriving right behind its promotion already
            # lands the tier-1 hit
            self._drain_promotions()
            admissions = []
            shed: list = []
            with self.lock:
                if self._deadline_s > 0.0 and self.queue:
                    # sweep requests whose deadline lapsed while queued:
                    # running them now wastes device time on an answer
                    # the caller already gave up on
                    now_d = time_mod.monotonic()
                    kept = []
                    for r in self.queue:
                        if r.deadline is not None and r.deadline <= now_d:
                            shed.append((r, "deadline"))
                        else:
                            kept.append(r)
                    if shed:
                        self.queue.clear()
                        self.queue.extend(kept)
                now_a = time_mod.monotonic()
                while self.queue and self.free:
                    if self._tenants is not None:
                        # weighted-fair pop (PATHWAY_TPU_TENANT_SCHED):
                        # the queue stays one FIFO deque; the scheduler
                        # only picks WHICH tenant's oldest entry admits
                        # next (None = every waiter is over its token
                        # budget — hold until a slot credits back)
                        entries = [
                            (r.tenant, r.max_new) for r in self.queue
                        ]
                        i = self._tenants.select(entries)
                        if i is None:
                            break
                        req = self.queue[i]
                        del self.queue[i]
                    else:
                        req = self.queue.popleft()
                    if (self._degradation_level >= 3
                            and req.priority <= 0):
                        shed.append((req, "degraded"))
                        continue
                    if (req.deadline is not None
                            and req.deadline <= now_a):
                        # admission-time enforcement: a deadline can
                        # lapse between the sweep above and the pop
                        shed.append((req, "deadline"))
                        continue
                    self._admit_seq += 1
                    req.seq = self._admit_seq
                    self._tenant_charge(req)
                    admissions.append((self.free.pop(), req))
            for req, reason in shed:
                self._shed_request(req, reason)
            direct = []
            direct_inserts = []
            for slot, req in admissions:
                # the slot record goes in FIRST: if the admit dispatch
                # raises, the failure sweep still finds (and fails) this
                # request instead of stranding its waiter
                self.slots[slot] = req
                self._sent[slot] = 0
                try:
                    if self._chaos_admit is not None:
                        # request-scoped fault: only this request's host
                        # bookkeeping is torn, so supervision rewinds the
                        # one slot instead of restarting the loop
                        self._chaos_admit.maybe_fail()
                    self._admit_one(slot, req, direct, direct_inserts)
                except Exception as exc:  # noqa: BLE001 - isolation gate
                    if not self._supervised:
                        raise
                    self._isolate_admission_failure(slot, req, exc, active)
            admit_direct(direct)
            for slot, _ids_d, mask_d, _s_d in direct:
                req_d = self.slots[slot]
                if req_d is not None:
                    req_d.span.event("prefill", tokens=int(mask_d.sum()))
            for slot, (req_i, e_i, base_i) in direct_inserts:
                # after the admit dispatch: the slot's KV now holds the
                # prompt's blocks — publish the new ones into the arena
                self._prefix_insert(slot, req_i, e_i, base_i)
            pend = list(self._pending_prefill)
            if (self.disagg and active.any()
                    and len(pend) > self._prefill_budget):
                # disaggregated lanes (PATHWAY_TPU_DISAGG): the decode
                # lane owns the dispatch stream — at most
                # prefill_budget prompts advance one piece per tick
                # (round-robin, so every pending prompt progresses),
                # instead of EVERY pending prompt queueing a piece
                # ahead of the next decode chunk. With no active
                # decode lane there is nothing to protect and all
                # prompts advance, same as interleaved. Greedy tokens
                # are schedule-invariant, so the flag never changes a
                # stream — only its timing.
                start = self._prefill_rr % len(pend)
                pend = [
                    pend[(start + k) % len(pend)]
                    for k in range(self._prefill_budget)
                ]
                self._prefill_rr += self._prefill_budget
            for slot in pend:
                try:
                    self._prefill_piece(slot, active)
                except Exception as exc:  # noqa: BLE001 - isolation gate
                    req_p = self.slots[slot]
                    if not self._supervised or req_p is None:
                        raise
                    self._isolate_admission_failure(
                        slot, req_p, exc, active
                    )
            self._maybe_preempt(active)
            self._lane_counts["prefill"] = len(self._pending_prefill)
            self._lane_counts["decode"] = int(active.sum())
            if not dispatched:
                # legacy ordering (kill switch off) — or the pool was
                # empty at the top of the tick and admissions just
                # activated lanes: decode them without an idle hop
                dispatched = dispatch_decode()
            if dispatched:
                if len(inflight) <= self.pipeline_depth:
                    continue
            elif not inflight:
                if self._pending_prefill:
                    continue
                self._spec_flush()  # trailing drains past the last finish
                self.wake.clear()
                self.wake.wait(timeout=0.05)
                continue
            prev = inflight.popleft()
            payload, was_active, snap_slots = prev
            spec_rec = isinstance(payload, tuple)
            if spec_rec:
                # (n_cycles, n_slots, spec_k+1) proposed tokens and the
                # (n_cycles, n_slots) per-cycle accepted counts: a
                # lane's stream is each cycle's first n_emit tokens
                toks = np.asarray(payload[0])
                emit = np.asarray(payload[1])
                lanes = np.nonzero(was_active)[0]
                cyc, kk = toks.shape[0], toks.shape[2] - 1
                n_act = len(lanes)
                drafted = cyc * n_act * kk
                emitted = int(emit[:, lanes].sum()) if n_act else 0
                accepted = emitted - cyc * n_act
                # accumulate locally, flush to the registry at request
                # completions (and loop idle): one registry call per
                # request instead of six per spec drain
                acc = self._spec_accum
                for k, v in (
                    ("dispatches", 1), ("verify_steps", cyc * n_act),
                    ("draft_steps", drafted), ("drafted", drafted),
                    ("accepted", accepted), ("emitted", emitted),
                ):
                    acc[k] = acc.get(k, 0) + v
                self.stats["spec_verify_steps"] += cyc * n_act
                self.stats["spec_drafted"] += drafted
                self.stats["spec_accepted"] += accepted
                self.stats["spec_emitted"] += emitted
                if drafted:
                    rate = accepted / drafted
                    self._accept_ema = (
                        rate if self._accept_ema is None
                        else 0.7 * self._accept_ema + 0.3 * rate
                    )
                    self._spec_drains += 1
                    # below ~1/(k+1) acceptance the drafts are noise:
                    # latch back to plain chunks (identical tokens,
                    # none of the draft cost)
                    if (self._spec_drains >= 4
                            and self._accept_ema < 0.25):
                        self._spec_off = True
            else:
                toks = np.asarray(payload)
            for slot in np.nonzero(was_active)[0]:
                req = snap_slots[slot]
                if req is None or req.done.is_set():
                    continue  # freed by an earlier chunk's tail
                if (self._deadline_s > 0.0 and req.deadline is not None
                        and req.deadline <= time_mod.monotonic()):
                    # in-flight enforcement: an admitted-then-stalled
                    # request can't burn its slot past its deadline —
                    # free it NOW instead of decoding an answer the
                    # caller already abandoned
                    if self.slots[slot] is req:
                        self.slots[slot] = None
                        active[slot] = False
                        self._release_slot_kv(slot)
                        with self.lock:
                            self.free.append(int(slot))
                    self._prefix_release(req)
                    self._discard_parked(req)
                    self._tenant_credit(req)
                    self._shed_request(req, "deadline_inflight")
                    continue
                if spec_rec:
                    stream = [
                        int(t) for c in range(toks.shape[0])
                        for t in toks[c, slot, : emit[c, slot]]
                    ]
                    req.span.event(
                        "spec_cycles", cycles=int(cyc),
                        emitted=len(stream), accepted=len(stream) - int(cyc),
                    )
                else:
                    stream = toks[:, slot].tolist()
                    req.span.event("decode_chunk", steps=len(stream))
                for t in stream:
                    if self.eos_id is not None and t == self.eos_id:
                        req.max_new = 0  # stream closed
                        break
                    if not req.tokens:
                        req.first_token_at = time_mod.perf_counter()
                        req.span.event("first_token")
                    req.tokens.append(int(t))
                    if len(req.tokens) >= req.max_new:
                        break
                if req.max_new == 0 or len(req.tokens) >= req.max_new:
                    import time as time_mod

                    req.text = self.tokenizer.decode(req.tokens)
                    req.finished_at = time_mod.perf_counter()
                    # eager refill may have freed (and even re-admitted)
                    # this slot chunks ago — only release it if it still
                    # belongs to the request we just completed
                    if self.slots[slot] is req:
                        self.slots[slot] = None
                        active[slot] = False
                        self._release_slot_kv(slot)
                        with self.lock:
                            self.free.append(int(slot))
                    self._prefix_release(req)
                    self._tenant_credit(req)
                    # flush + finish BEFORE done.set(): a waiter that
                    # wakes on done must find the spec counters and the
                    # span already recorded
                    self._spec_flush()
                    req.span.event("drain")
                    req.span.finish(tokens=len(req.tokens))
                    req.done.set()

    def _spec_flush(self):
        """Flush locally-accumulated spec counters to the registry.
        Called at request completions and loop idle; when the kill
        switch is off the flush discards (record_spec_many no-ops), so
        disabled-window counts never leak into an enabled scrape."""
        acc = self._spec_accum
        if acc:
            self._spec_accum = {}
            from pathway_tpu.engine.probes import record_spec_many

            record_spec_many(**acc)

    def shutdown(self, timeout: float = 10.0):
        self._stop = True
        self.wake.set()
        t = self.thread
        if t is not None and t.is_alive():
            # join so interpreter teardown never kills the thread mid
            # device call (jax runtime aborts on threads dying inside it)
            t.join(timeout=timeout)
            if t.is_alive():
                # a leaked serving thread is a wedged device call or a
                # stuck lock — record it loudly instead of exiting as if
                # the shutdown were clean
                from pathway_tpu.internals.errors import get_global_error_log

                with self.lock:
                    self.stats["leaked_thread"] += 1
                get_global_error_log().log(
                    f"serving loop thread {t.name!r} still alive "
                    f"{timeout}s after shutdown join"
                )
        # getattr: shutdown must also work on a partially-constructed
        # server (init failure cleanup, bare-object harness tests)
        promote = getattr(self, "_promote_worker", None)
        if promote is not None:
            promote.close()
        # the loop thread is down: every span it will ever write has been
        # written, so drain the flight recorder's buffered JSONL lines
        from pathway_tpu.engine import tracing

        tracing.flush_traces()


@pw.udf
def prompt_chat_single_qa(question: str) -> Json:
    """Wrap a plain question string into a one-message chat (reference
    ``prompt_chat_single_qa``, llms.py:686)."""
    return Json([{"role": "user", "content": question}])
