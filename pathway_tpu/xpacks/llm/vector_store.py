"""VectorStoreServer — embed→index→retrieve REST service (reference
``xpacks/llm/vector_store.py:39-769``).

The classic Pathway vector-store surface: document connector tables go
through parse → post-process → split → **TPU embed** (batched XLA calls) →
HBM brute-force KNN; an aiohttp REST endpoint answers
``/v1/retrieve | /v1/statistics | /v1/inputs`` live. ``VectorStoreClient``
is the matching HTTP client.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Iterable

import pathway_tpu as pw
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing import BruteForceKnnFactory, DataIndex
from pathway_tpu.xpacks.llm.document_store import DocumentStore

logger = logging.getLogger(__name__)


class VectorStoreServer:
    """Live vector store with REST endpoints (reference
    ``VectorStoreServer``, vector_store.py:39)."""

    def __init__(
        self,
        *docs: Table,
        embedder: Callable[[str], Any],
        parser: Callable[[bytes], list[tuple[str, dict]]] | None = None,
        splitter: Callable[[str], list[tuple[str, dict]]] | None = None,
        doc_post_processors: list[Callable[[str], str]] | None = None,
        index_factory: Any = None,
    ):
        self.embedder = embedder
        if index_factory is None:
            dim = (
                embedder.get_embedding_dimension()
                if hasattr(embedder, "get_embedding_dimension")
                else None
            )
            index_factory = BruteForceKnnFactory(dimensions=dim, embedder=embedder)
        elif getattr(index_factory, "embedder", None) is None and hasattr(
            index_factory, "embedder"
        ):
            index_factory.embedder = embedder
        self.index_factory = index_factory
        self.document_store = DocumentStore(
            list(docs),
            retriever_factory=index_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
        )
        self._server_thread: threading.Thread | None = None

    @classmethod
    def from_langchain_components(
        cls, *docs, embedder, parser=None, splitter=None, **kwargs
    ):
        """Build from langchain embeddings + text splitter (reference
        ``from_langchain_components``, vector_store.py:93)."""
        try:
            from langchain_core.embeddings import Embeddings  # noqa: F401
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("requires langchain-core") from exc

        @pw.udf
        async def langchain_embedder(x: str):
            import numpy as np

            res = await embedder.aembed_documents([x or "."])
            return np.array(res[0])

        split_fn = None
        if splitter is not None:
            @pw.udf
            def split_fn(text: str) -> list[tuple[str, dict]]:
                return [(chunk, {}) for chunk in splitter.split_text(text)]

        return cls(*docs, embedder=langchain_embedder, parser=parser, splitter=split_fn, **kwargs)

    @classmethod
    def from_llamaindex_components(cls, *docs, transformations, parser=None, **kwargs):
        """Build from llama-index transformations, the last being an embedder
        (reference ``from_llamaindex_components``, vector_store.py:137)."""
        try:
            from llama_index.core.base.embeddings.base import BaseEmbedding
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("requires llama-index-core") from exc
        embedders = [t for t in transformations if isinstance(t, BaseEmbedding)]
        if len(embedders) != 1:
            raise ValueError("expected exactly one embedder in transformations")
        li_embedder = embedders[0]
        transformations = [t for t in transformations if not isinstance(t, BaseEmbedding)]

        @pw.udf
        async def embedder(x: str):
            import numpy as np

            return np.array(await li_embedder.aget_text_embedding(x or "."))

        splitter = None
        if transformations:
            from llama_index.core.ingestion.pipeline import run_transformations
            from llama_index.core.schema import BaseNode, MetadataMode, TextNode

            @pw.udf
            def splitter(text: str) -> list[tuple[str, dict]]:
                nodes: list[BaseNode] = [TextNode(text=text)]
                final = run_transformations(nodes, transformations)
                return [
                    (n.get_content(metadata_mode=MetadataMode.NONE), n.extra_info)
                    for n in final
                ]

        return cls(*docs, embedder=embedder, parser=parser, splitter=splitter, **kwargs)

    # -- query handlers (delegate to the document store) -------------------

    class RetrieveQuerySchema(schema_mod.Schema):
        query: str
        k: int
        metadata_filter: str | None
        filepath_globpattern: str | None

    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        return self.document_store.retrieve_query(retrieval_queries)

    def statistics_query(self, info_queries: Table) -> Table:
        return self.document_store.statistics_query(info_queries)

    def inputs_query(self, input_queries: Table) -> Table:
        return self.document_store.inputs_query(input_queries)

    @property
    def index(self) -> DataIndex:
        return self.document_store.index

    def late_bank_bytes(self) -> int:
        """Current device bytes of the late-interaction doc-token bank
        (the ``late_bank`` HBM-ledger component) behind this store — the
        number ``/v1/statistics`` reports as ``late_bank_bytes``. Falls on
        document retraction, mirroring the IVF row lifecycle."""
        from pathway_tpu.engine.probes import hbm_stats

        return int(hbm_stats()["current_bytes"].get("late_bank", 0))

    def run_server(
        self,
        host: str = "0.0.0.0",  # noqa: S104
        port: int = 8000,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        terminate_on_error: bool = True,
    ):
        """Serve ``/v1/retrieve``, ``/v1/statistics``, ``/v1/inputs``
        (reference ``run_server``, vector_store.py:478)."""
        from pathway_tpu.io.http import PathwayWebserver, rest_connector

        webserver = PathwayWebserver(host, port)

        routes = [
            ("/v1/retrieve", self.RetrieveQuerySchema, self.retrieve_query, ("GET", "POST")),
            ("/v1/statistics", self.StatisticsQuerySchema, self.statistics_query, ("GET", "POST")),
            ("/v1/inputs", self.InputsQuerySchema, self.inputs_query, ("GET", "POST")),
        ]
        for route, schema, handler, methods in routes:
            queries, writer = rest_connector(
                webserver=webserver,
                route=route,
                schema=schema,
                methods=methods,
                delete_completed_queries=True,
            )
            writer(handler(queries))

        def run():
            pw.run(monitoring_level=pw.MonitoringLevel.NONE)

        if threaded:
            t = threading.Thread(target=run, daemon=True, name="VectorStoreServer")
            t.start()
            self._server_thread = t
            return t
        run()

    def __repr__(self):
        return f"VectorStoreServer({self.index_factory!r})"


class SlidesVectorStoreServer(VectorStoreServer):
    """Parity stub for the slides-oriented store (reference
    ``SlidesVectorStoreServer``, vector_store.py:588)."""


class VectorStoreClient:
    """HTTP client for a VectorStoreServer (reference ``VectorStoreClient``,
    vector_store.py:651)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int = 15,
        additional_headers: dict | None = None,
    ):
        if url is None:
            if host is None:
                raise ValueError("either url or host must be given")
            url = f"http://{host}:{port or 80}"
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.additional_headers = additional_headers or {}

    def _post(self, route: str, payload: dict) -> Any:
        from pathway_tpu.xpacks.llm._utils import post_json

        return post_json(
            self.url + route, payload, self.additional_headers, self.timeout
        )

    def query(
        self, query: str, k: int = 3, metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ) -> list[dict]:
        data = self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )
        return data

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(
        self, metadata_filter: str | None = None, filepath_globpattern: str | None = None
    ) -> list:
        return self._post(
            "/v1/inputs",
            {"metadata_filter": metadata_filter, "filepath_globpattern": filepath_globpattern},
        )
