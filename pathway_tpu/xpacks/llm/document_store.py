"""DocumentStore — live parse→split→index pipeline over document sources
(reference ``xpacks/llm/document_store.py:32-529``).

The store consumes one or more connector tables of raw documents
(``data: bytes|str`` + optional ``_metadata: Json``), runs parser →
post-processors → splitter, and maintains a retriever index (TPU brute-force
KNN / BM25 / hybrid) over the chunks. Query tables are answered live:
``retrieve_query`` / ``statistics_query`` / ``inputs_query`` mirror the
reference's REST surface.

Re-ingest cost: when a source file is edited and re-read, the pipeline
re-derives every chunk of that file, but most chunks are byte-identical to
their previous versions. The embedding stage
(``SentenceTransformerEmbedder``) keeps a content-keyed LRU of recent
chunk embeddings (``PATHWAY_TPU_EMBED_DEDUP``), so unchanged chunks reuse
their vector instead of re-dispatching to the device — the ingest-side
analogue of the serving-side KV prefix cache.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json, unwrap_json
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing import DataIndex
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory
from pathway_tpu.xpacks.llm.parsers import ParseUtf8


class _DocSchema(schema_mod.Schema):
    pass


def _ensure_tables(docs: Table | Iterable[Table]) -> list[Table]:
    if isinstance(docs, Table):
        return [docs]
    return list(docs)


class DocumentStore:
    """Builds and serves a live document index (reference ``DocumentStore``,
    document_store.py:32)."""

    class RetrieveQuerySchema(schema_mod.Schema):
        query: str
        k: int
        metadata_filter: str | None
        filepath_globpattern: str | None

    class StatisticsQuerySchema(schema_mod.Schema):
        pass

    class InputsQuerySchema(schema_mod.Schema):
        metadata_filter: str | None
        filepath_globpattern: str | None

    class QueryResultSchema(schema_mod.Schema):
        result: dt.JSON

    def __init__(
        self,
        docs: Table | Iterable[Table],
        retriever_factory: AbstractRetrieverFactory,
        parser: pw.UDF | None = None,
        splitter: pw.UDF | None = None,
        doc_post_processors: list[Callable] | None = None,
    ):
        self.docs = _ensure_tables(docs)
        self.retriever_factory = retriever_factory
        self.parser = parser if parser is not None else ParseUtf8()
        self.splitter = splitter
        self.doc_post_processors = doc_post_processors or []
        self.build_pipeline()

    @classmethod
    def from_langchain_components(
        cls, docs, retriever_factory, parser=None, splitter=None, **kwargs
    ):
        """Use a langchain text splitter (reference
        ``from_langchain_components``, document_store.py:87)."""
        split_fn = None
        if splitter is not None:
            @pw.udf
            def split_fn(text: str) -> list[tuple[str, dict]]:
                return [(chunk, {}) for chunk in splitter.split_text(text)]

        return cls(docs, retriever_factory, parser=parser, splitter=split_fn, **kwargs)

    @classmethod
    def from_llamaindex_components(
        cls, docs, retriever_factory, parser=None, transformations=None, **kwargs
    ):
        """Use llama-index node transformations (reference
        ``from_llamaindex_components``, document_store.py:128)."""
        split_fn = None
        if transformations:
            try:
                from llama_index.core.ingestion.pipeline import run_transformations
                from llama_index.core.schema import BaseNode, MetadataMode, TextNode
            except ImportError as exc:  # pragma: no cover - gated dependency
                raise ImportError(
                    "from_llamaindex_components requires `llama-index-core`"
                ) from exc

            @pw.udf
            def split_fn(text: str) -> list[tuple[str, dict]]:
                starting_node: list[BaseNode] = [TextNode(text=text)]
                final_nodes = run_transformations(starting_node, transformations)
                return [
                    (node.get_content(metadata_mode=MetadataMode.NONE), node.extra_info)
                    for node in final_nodes
                ]

        return cls(docs, retriever_factory, parser=parser, splitter=split_fn, **kwargs)

    # -- pipeline ----------------------------------------------------------

    def parse_documents(self, input_docs: Table) -> Table:
        parser = self.parser

        @pw.udf
        def parse_with_meta(data, metadata) -> list:
            chunks = parser.__wrapped__(data)
            base = unwrap_json(metadata) if metadata is not None else {}
            out = []
            for text, meta in chunks:
                merged = dict(base or {})
                merged.update(meta or {})
                out.append(Json({"text": text, "metadata": merged}))
            return out

        has_meta = "_metadata" in input_docs.column_names()
        meta_col = input_docs._metadata if has_meta else None
        parsed = input_docs.select(
            parts=parse_with_meta(
                input_docs.data,
                meta_col if meta_col is not None else None,
            )
        )
        flat = parsed.flatten(parsed.parts)
        return flat.select(
            text=pw.apply_with_type(lambda p: str(unwrap_json(p).get("text", "")), str, flat.parts),
            metadata=pw.apply_with_type(
                lambda p: Json(unwrap_json(p).get("metadata", {})), dt.JSON, flat.parts
            ),
        )

    def post_process_docs(self, parsed_docs: Table) -> Table:
        processors = self.doc_post_processors
        if not processors:
            return parsed_docs

        @pw.udf
        def post_proc(text: str) -> str:
            for proc in processors:
                text = proc(text)
            return text

        return parsed_docs.with_columns(text=post_proc(parsed_docs.text))

    def split_docs(self, post_processed_docs: Table) -> Table:
        if self.splitter is None:
            return post_processed_docs
        splitter = self.splitter

        @pw.udf
        def split_with_meta(text: str, metadata) -> list:
            chunks = splitter.__wrapped__(text)
            base = unwrap_json(metadata) if metadata is not None else {}
            out = []
            for chunk in chunks:
                if isinstance(chunk, tuple):
                    ctext, cmeta = chunk
                else:
                    ctext, cmeta = chunk, {}
                merged = dict(base or {})
                merged.update(cmeta or {})
                out.append(Json({"text": str(ctext), "metadata": merged}))
            return out

        split = post_processed_docs.select(
            parts=split_with_meta(post_processed_docs.text, post_processed_docs.metadata)
        )
        flat = split.flatten(split.parts)
        return flat.select(
            text=pw.apply_with_type(lambda p: str(unwrap_json(p).get("text", "")), str, flat.parts),
            metadata=pw.apply_with_type(
                lambda p: Json(unwrap_json(p).get("metadata", {})), dt.JSON, flat.parts
            ),
        )

    def build_pipeline(self) -> None:
        docs = self.docs[0] if len(self.docs) == 1 else self.docs[0].concat_reindex(*self.docs[1:])
        self.input_docs = docs
        self.parsed_docs = self.parse_documents(docs)
        processed = self.post_process_docs(self.parsed_docs)
        self.chunked_docs = self.split_docs(processed)
        self._index: DataIndex = self.retriever_factory.build_index(
            self.chunked_docs.text,
            self.chunked_docs,
            metadata_column=self.chunked_docs.metadata,
        )

    @property
    def index(self) -> DataIndex:
        return self._index

    # -- query surfaces ----------------------------------------------------

    @staticmethod
    def merge_filters(queries: Table) -> Table:
        """Combine ``metadata_filter`` and ``filepath_globpattern`` into one
        filter expression (reference ``merge_filters``,
        document_store.py:356)."""

        @pw.udf
        def _merge(metadata_filter, globpattern) -> str | None:
            parts = []
            if metadata_filter:
                parts.append(str(metadata_filter))
            if globpattern:
                parts.append(f"glob(path, '{globpattern}')")
            return " && ".join(parts) if parts else None

        return queries.with_columns(
            metadata_filter=_merge(queries.metadata_filter, queries.filepath_globpattern)
        ).without("filepath_globpattern")

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        """Answer retrieval queries live (reference ``retrieve_query``,
        document_store.py:426)."""
        queries = self.merge_filters(retrieval_queries)
        matches = self._index.query_as_of_now(
            queries.query,
            number_of_matches=queries.k,
            collapse_rows=True,
            with_distances=True,
            metadata_filter=queries.metadata_filter,
        )

        @pw.udf
        def format_docs(texts, metadatas, dists) -> Json:
            docs = []
            for text, meta, dist in zip(texts, metadatas, dists):
                docs.append(
                    {
                        "text": text,
                        "metadata": unwrap_json(meta) if meta is not None else {},
                        "dist": float(dist),
                    }
                )
            return Json(docs)

        return matches.select(
            result=format_docs(matches.text, matches.metadata, matches._pw_dist)
        )

    def statistics_query(self, info_queries: Table) -> Table:
        """Index health statistics (reference ``statistics_query``,
        document_store.py:323)."""
        chunked = self.chunked_docs

        counts = chunked.reduce(count=pw.reducers.count())

        @pw.udf
        def _mtime(meta) -> float:
            m = unwrap_json(meta) or {}
            return float(m.get("modified_at", 0) or 0)

        times = chunked.select(m=_mtime(chunked.metadata)).reduce(
            last_modified=pw.reducers.max(pw.this.m),
            last_indexed=pw.reducers.max(pw.this.m),
        )

        @pw.udf
        def format_stats(count, last_modified, last_indexed) -> Json:
            # late-interaction bank health rides the same statistics
            # surface: current device bytes of the `late_bank` HBM
            # component (0 when PATHWAY_TPU_LATE_INTERACTION never ran).
            # Retraction/compaction lower it live, mirroring the IVF row
            # lifecycle the file_count tracks.
            from pathway_tpu.engine.probes import hbm_stats

            late = hbm_stats()["current_bytes"].get("late_bank", 0)
            return Json(
                {
                    "file_count": int(count or 0),
                    "last_modified": last_modified,
                    "last_indexed": last_indexed,
                    "late_bank_bytes": int(late),
                }
            )

        combined = counts.join(times).select(
            counts.count, times.last_modified, times.last_indexed
        )
        # keep the query-side keys (id=pw.left.id) so REST responses
        # correlate back to their pending requests
        stats = info_queries.join(combined, how="left", id=pw.left.id).select(
            result=format_stats(pw.this.count, pw.this.last_modified, pw.this.last_indexed)
        )
        return stats

    def inputs_query(self, input_queries: Table) -> Table:
        """List indexed source documents (reference ``inputs_query``,
        document_store.py:385)."""
        parsed = self.parsed_docs
        queries = self.merge_filters(input_queries)

        @pw.udf
        def _meta(meta) -> Json:
            return Json(unwrap_json(meta) or {})

        metas = parsed.select(m=_meta(parsed.metadata)).reduce(
            metadatas=pw.reducers.tuple(pw.this.m)
        )

        @pw.udf
        def format_inputs(metadatas, metadata_filter) -> Json:
            from pathway_tpu.engine.operators.external_index import _apply_filter

            seen: dict[str, dict] = {}
            for meta in metadatas or ():
                m = unwrap_json(meta) or {}
                if metadata_filter and not _apply_filter(metadata_filter, m):
                    continue
                path = str(m.get("path", ""))
                seen[path] = m
            return Json(list(seen.values()))

        return queries.join(metas, how="left", id=pw.left.id).select(
            result=format_inputs(pw.this.metadatas, pw.this.metadata_filter)
        )


class SlidesDocumentStore(DocumentStore):
    """DocumentStore variant exposing parsed slide pages (reference
    ``SlidesDocumentStore``, document_store.py:471)."""

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        parsed = self.parsed_docs
        collected = parsed.reduce(
            docs=pw.reducers.tuple(
                pw.apply_with_type(
                    lambda t, m: Json({"text": t, "metadata": unwrap_json(m) or {}}),
                    dt.JSON,
                    parsed.text,
                    parsed.metadata,
                )
            )
        )

        @pw.udf
        def format_inputs(docs) -> Json:
            return Json([unwrap_json(d) for d in (docs or ())])

        return parse_docs_queries.join(collected, how="left", id=pw.left.id).select(
            result=format_inputs(pw.this.docs)
        )
