"""REST servers for document stores and QA pipelines (reference
``xpacks/llm/servers.py:16-291``).

Each endpoint is a ``rest_connector`` route: requests become rows of a query
table, the handler builds the answering sub-graph once at definition time,
and responses resolve through the dataflow.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table

logger = logging.getLogger(__name__)


def map_serving_errors(handler: Callable[[Table], Table]) -> Callable[[Table], Table]:
    """Wrap an endpoint handler so serving failures come back as typed
    HTTP errors instead of a 200 whose body happens to contain an error.

    The continuous decode server ships per-request failures through the
    string-typed response channel as a reserved-prefix marker (see
    ``llms.encode_serve_error``). This wrapper decodes that marker out of
    the handler's ``result`` column and rewrites the row to the
    ``_pw_http_error`` envelope the webserver maps to a real status:
    admission-control sheds (``shed:*`` reasons) become 503 +
    ``Retry-After``; everything else becomes a structured 500."""
    from pathway_tpu.internals.json import Json
    from pathway_tpu.xpacks.llm.llms import decode_serve_error

    def _envelope(err: dict) -> Json:
        reason = err.get("reason", "serve_failed")
        shed = reason.startswith("shed:")
        body: dict = {
            "status": 503 if shed else 500,
            "reason": reason,
            "error": (
                "request shed by admission control; retry later"
                if shed else "model serving failed for this request"
            ),
        }
        if err.get("retry_after") is not None:
            body["retry_after"] = err["retry_after"]
        elif shed:
            body["retry_after"] = 1.0
        return Json({"_pw_http_error": body})

    @pw.udf
    def _rewrite(result):
        value = result.value if isinstance(result, Json) else result
        if isinstance(value, str):
            err = decode_serve_error(value)
            if err is not None:
                return _envelope(err)
        elif isinstance(value, dict):
            resp = value.get("response")
            if isinstance(resp, str):
                err = decode_serve_error(resp)
                if err is not None:
                    return _envelope(err)
        return result

    def wrapped(queries: Table) -> Table:
        out = handler(queries)
        names = list(out.column_names())
        if "result" not in names:
            return out
        return out.select(**{
            c: (_rewrite(out[c]) if c == "result" else out[c])
            for c in names
        })

    return wrapped


class BaseRestServer:
    """Route registry over a shared webserver (reference ``BaseRestServer``,
    servers.py:16)."""

    def __init__(self, host: str, port: int, **rest_kwargs):
        from pathway_tpu.io.http import PathwayWebserver

        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host, port)
        self.rest_kwargs = rest_kwargs
        self._thread: threading.Thread | None = None
        # readiness: set when run() hands control to the pipeline (the
        # dataflow routes register at connector start inside pw.run);
        # /readyz reports 503 until then so probes hold traffic
        self._ready = threading.Event()

    def serve(
        self,
        route: str,
        schema: type,
        handler: Callable[[Table], Table],
        documentation: Any = None,
        **additional_kwargs,
    ) -> None:
        from pathway_tpu.io.http import rest_connector

        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            schema=schema,
            methods=additional_kwargs.pop("methods", ("GET", "POST")),
            delete_completed_queries=True,
        )
        writer(handler(queries))

    def start_observability_endpoints(self) -> None:
        """Register ``GET /metrics`` (OpenMetrics text over the unified
        ``MetricsRegistry``), a registry-JSON ``/v1/statistics`` and the
        opt-in ``GET /debug/profile?ms=N`` device-trace capture on the
        shared webserver. Registered directly (not as dataflow
        routes), so they answer even while the pipeline is compiling or
        stalled; dataflow routes register later — at connector start,
        inside ``pw.run`` — so a server that defines its own
        ``/v1/statistics`` (e.g. :class:`QARestServer`) overrides the
        registry JSON for that route while keeping ``/metrics``."""
        import asyncio
        import functools

        from pathway_tpu.engine import probes
        from pathway_tpu.internals import profiling, run as run_mod
        from pathway_tpu.internals.http_server import openmetrics_text

        async def metrics_handler(_payload):
            return openmetrics_text()

        # the io/http.py dispatch returns this as raw text, not JSON
        metrics_handler._raw_content_type = "text/plain"

        async def statistics_handler(_payload):
            return probes.unified_snapshot(
                getattr(run_mod, "LAST_RUN_STATS", None)
            )

        async def profile_handler(payload):
            # capture in an executor thread: the profiler sleeps for the
            # requested window and the event loop must keep serving
            ms = (payload or {}).get("ms", 100)
            return await asyncio.get_event_loop().run_in_executor(
                None, functools.partial(profiling.capture_trace, ms)
            )

        async def healthz_handler(_payload):
            # liveness: answering at all IS the signal
            return "ok\n"

        healthz_handler._raw_content_type = "text/plain"

        async def readyz_handler(_payload):
            from pathway_tpu.io.http import RestApiError

            if not self._ready.is_set():
                raise RestApiError(
                    503, {"error": "pipeline not started"}, retry_after=1
                )
            return "ready\n"

        readyz_handler._raw_content_type = "text/plain"

        self.webserver._register("/metrics", ["GET"], metrics_handler)
        self.webserver._register(
            "/v1/statistics", ["GET", "POST"], statistics_handler
        )
        self.webserver._register(
            "/debug/profile", ["GET", "POST"], profile_handler
        )
        self.webserver._register("/healthz", ["GET"], healthz_handler)
        self.webserver._register("/readyz", ["GET"], readyz_handler)

    def run(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend=None,
        terminate_on_error: bool = False,
        **kwargs,
    ):
        """Start serving (reference ``run``, servers.py:68)."""
        self.start_observability_endpoints()

        def run_pipeline():
            self._ready.set()  # pipeline start imminent: flip /readyz
            pw.run(
                monitoring_level=pw.MonitoringLevel.NONE,
                terminate_on_error=terminate_on_error,
            )

        if threaded:
            t = threading.Thread(target=run_pipeline, daemon=True, name=f"RestServer:{self.port}")
            t.start()
            self._thread = t
            return t
        run_pipeline()


class DocumentStoreServer(BaseRestServer):
    """Serves a DocumentStore (reference ``DocumentStoreServer``,
    servers.py:92): /v1/retrieve, /v1/statistics, /v1/inputs."""

    def __init__(self, host: str, port: int, document_store, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.serve(
            "/v1/retrieve", document_store.RetrieveQuerySchema,
            document_store.retrieve_query,
        )
        self.serve(
            "/v1/statistics", document_store.StatisticsQuerySchema,
            document_store.statistics_query,
        )
        self.serve(
            "/v1/inputs", document_store.InputsQuerySchema,
            document_store.inputs_query,
        )


class QARestServer(BaseRestServer):
    """Serves a BaseQuestionAnswerer (reference ``QARestServer``,
    servers.py:140): /v1/pw_ai_answer, /v1/retrieve, /v1/statistics,
    /v1/pw_list_documents (+ v2 aliases)."""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.serve(
            "/v1/pw_ai_answer", rag_question_answerer.AnswerQuerySchema,
            map_serving_errors(rag_question_answerer.answer_query),
        )
        self.serve(
            "/v2/answer", rag_question_answerer.AnswerQuerySchema,
            map_serving_errors(rag_question_answerer.answer_query),
        )
        self.serve(
            "/v1/retrieve", rag_question_answerer.RetrieveQuerySchema,
            rag_question_answerer.retrieve,
        )
        self.serve(
            "/v2/retrieve", rag_question_answerer.RetrieveQuerySchema,
            rag_question_answerer.retrieve,
        )
        self.serve(
            "/v1/statistics", rag_question_answerer.StatisticsQuerySchema,
            rag_question_answerer.statistics,
        )
        self.serve(
            "/v1/pw_list_documents", rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
        )
        self.serve(
            "/v2/list_documents", rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
        )


class QASummaryRestServer(QARestServer):
    """QA server plus summarization endpoint (reference
    ``QASummaryRestServer``, servers.py:193)."""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        self.serve(
            "/v1/pw_ai_summary", rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
        )
        self.serve(
            "/v2/summarize", rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
        )


def serve_callable(
    route: str,
    schema: type | None = None,
    host: str = "0.0.0.0",  # noqa: S104
    port: int = 8000,
    **rest_kwargs,
):
    """Expose an ad-hoc (async) function as a REST endpoint inside the
    dataflow (reference ``serve_callable``, servers.py:227)."""

    def decorator(callable_func):
        server = BaseRestServer(host, port, **rest_kwargs)
        nonlocal schema
        if schema is None:
            import inspect

            params = [
                p for p in inspect.signature(callable_func).parameters.values()
                if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
            ]
            schema = schema_mod.schema_from_types(
                **{
                    p.name: (p.annotation if p.annotation is not inspect.Parameter.empty else str)
                    for p in params
                }
            )

        fn_udf = pw.udf(callable_func)

        def handler(queries: Table) -> Table:
            cols = [queries[c] for c in queries.column_names()]
            return queries.select(result=fn_udf(*cols))

        server.serve(route, schema, handler)
        callable_func._pw_server = server
        return callable_func

    return decorator
