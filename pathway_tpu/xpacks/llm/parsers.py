"""Document parser UDFs (reference ``xpacks/llm/parsers.py:53-928``).

Each parser maps raw ``bytes`` to ``list[(text, metadata)]``. ``ParseUtf8``
is dependency-free; rich-format parsers (unstructured / pypdf / openparse /
vision) follow the reference's class surface and are gated on their SDKs.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

import pathway_tpu as pw

logger = logging.getLogger(__name__)


def _default_vision_llm():
    """Lazy ``DEFAULT_VISION_LLM`` (reference ``parsers.py:45``): an
    OpenAIChat on the default vision model with disk cache + backoff; built
    on first use so importing parsers never constructs network clients."""
    from pathway_tpu.internals import udfs
    from pathway_tpu.xpacks.llm import llms
    from pathway_tpu.xpacks.llm.constants import DEFAULT_VISION_MODEL

    return llms.OpenAIChat(
        model=DEFAULT_VISION_MODEL,
        cache_strategy=udfs.DiskCache(),
        retry_strategy=udfs.ExponentialBackoffRetryStrategy(max_retries=4),
        verbose=True,
    )


class _LazyVisionLLM:
    _inner = None

    def _resolve(self):
        if type(self)._inner is None:
            type(self)._inner = _default_vision_llm()
        return type(self)._inner

    def __call__(self, *args, **kwargs):
        return self._resolve()(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._resolve(), name)


DEFAULT_VISION_LLM = _LazyVisionLLM()


async def parse_images(images, llm, parse_prompt: str, *, run_mode: str = "parallel",
                       parse_details: bool = False, detail_parse_schema=None,
                       parse_image_details_fn=None,
                       max_image_size: int = 15 * 1024 * 1024,
                       downsize_horizontal_width: int = 1920):
    """Describe a list of PIL images with a vision LLM (reference
    ``parsers.py:parse_images``): downscale oversized images, base64-encode,
    and fan the prompts out (``run_mode``: "parallel" | "sequential")."""
    import asyncio

    from pathway_tpu.xpacks.llm._parser_utils import (
        img_to_b64,
        maybe_downscale,
        parse,
        parse_image_details,
    )

    if run_mode not in ("parallel", "sequential"):
        raise ValueError(
            f"run_mode must be 'parallel' or 'sequential', got {run_mode!r}"
        )
    b64_images = [
        img_to_b64(maybe_downscale(img, max_image_size, downsize_horizontal_width))
        for img in images
    ]
    if run_mode == "sequential":
        parsed = [await parse(b64, llm, parse_prompt) for b64 in b64_images]
    else:
        parsed = list(
            await asyncio.gather(*(parse(b64, llm, parse_prompt) for b64 in b64_images))
        )
    details: list = []
    if parse_details:
        if detail_parse_schema is None:
            raise ValueError(
                "parse_details=True requires detail_parse_schema"
            )
        detail_fn = parse_image_details_fn or parse_image_details
        if run_mode == "sequential":
            details = [
                await detail_fn(b64, detail_parse_schema) for b64 in b64_images
            ]
        else:
            details = list(
                await asyncio.gather(
                    *(detail_fn(b64, detail_parse_schema) for b64 in b64_images)
                )
            )
    return parsed, details


class ParseUtf8(pw.UDF):
    """Decode UTF-8 text (reference ``ParseUtf8``, parsers.py:53)."""

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        if isinstance(contents, str):
            return [(contents, {})]
        return [(contents.decode("utf-8", errors="replace"), {})]


# the reference renamed ParseUtf8 -> Utf8Parser in newer versions; keep both
Utf8Parser = ParseUtf8


class ParseUnstructured(pw.UDF):
    """Parse any document via the ``unstructured`` library (reference
    ``ParseUnstructured``, parsers.py:79-233). Modes: single / elements /
    paged."""

    def __init__(self, mode: str = "single", post_processors: list[Callable] | None = None, **unstructured_kwargs):
        super().__init__()
        if mode not in ("single", "elements", "paged"):
            raise ValueError(f"mode must be single, elements or paged, got {mode}")
        try:
            import unstructured.partition.auto  # noqa: F401
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "ParseUnstructured requires the `unstructured` package"
            ) from exc
        self.mode = mode
        self.post_processors = post_processors or []
        self.unstructured_kwargs = unstructured_kwargs

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        from unstructured.partition.auto import partition

        elements = partition(file=io.BytesIO(contents), **{**self.unstructured_kwargs, **kwargs})
        for el in elements:
            for post in self.post_processors:
                el.apply(post)
        if self.mode == "elements":
            out = []
            for el in elements:
                meta = el.metadata.to_dict() if getattr(el, "metadata", None) else {}
                meta["category"] = getattr(el, "category", None)
                out.append((str(el), meta))
            return out
        if self.mode == "paged":
            pages: dict[int, list[str]] = {}
            for el in elements:
                page = getattr(getattr(el, "metadata", None), "page_number", 1) or 1
                pages.setdefault(page, []).append(str(el))
            return [
                ("\n\n".join(texts), {"page_number": page})
                for page, texts in sorted(pages.items())
            ]
        return [("\n\n".join(str(el) for el in elements), {})]


UnstructuredParser = ParseUnstructured


class PypdfParser(pw.UDF):
    """PDF text extraction via pypdf (reference ``PypdfParser``,
    parsers.py:746-830)."""

    def __init__(self, apply_text_cleanup: bool = True, cache_strategy=None):
        super().__init__(cache_strategy=cache_strategy)
        try:
            import pypdf  # noqa: F401
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("PypdfParser requires the `pypdf` package") from exc
        self.apply_text_cleanup = apply_text_cleanup

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        import pypdf

        reader = pypdf.PdfReader(io.BytesIO(contents))
        out = []
        for i, page in enumerate(reader.pages):
            text = page.extract_text() or ""
            if self.apply_text_cleanup:
                text = " ".join(text.split())
            out.append((text, {"page_number": i + 1}))
        return out


class OpenParse(pw.UDF):
    """Layout-aware PDF parsing incl. tables (reference ``OpenParse``,
    parsers.py:235-394). Gated on ``openparse``."""

    def __init__(self, table_args: dict | None = None, cache_strategy=None, **kwargs):
        super().__init__(cache_strategy=cache_strategy)
        try:
            import openparse  # noqa: F401
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("OpenParse requires the `openparse` package") from exc
        self.table_args = table_args

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import io

        import openparse

        parser = openparse.DocumentParser(table_args=self.table_args)
        doc = parser.parse(io.BytesIO(contents))
        return [(node.text, {"node_type": str(type(node).__name__)}) for node in doc.nodes]


class ImageParser(pw.UDF):
    """Describe images with a vision LLM (reference ``ImageParser``,
    parsers.py:396-567). Requires a chat with vision support."""

    def __init__(self, llm: Any, parse_prompt: str = "Describe the image contents.", **kwargs):
        super().__init__()
        self.llm = llm
        self.parse_prompt = parse_prompt

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import base64

        from pathway_tpu.xpacks.llm._utils import _coerce_sync

        b64 = base64.b64encode(contents).decode()
        messages = [
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": self.parse_prompt},
                    {
                        "type": "image_url",
                        "image_url": {"url": f"data:image/jpeg;base64,{b64}"},
                    },
                ],
            }
        ]
        response = _coerce_sync(self.llm.__wrapped__)(messages)
        return [(str(response), {})]


class SlideParser(pw.UDF):
    """Parse slide decks page-by-page with a vision LLM (reference
    ``SlideParser``, parsers.py:569-744): render each deck page to an
    image, describe every page with the vision LLM (``parse_images``, the
    same fan-out ImageParser uses), and return one ``(text, metadata)``
    chunk per slide with page numbering.

    Page rendering uses ``pdf2image`` (gated import); ``page_renderer``
    injects any ``bytes -> list[PIL.Image]`` callable instead — offline
    deployments and tests render through it without poppler installed.
    """

    def __init__(self, llm: Any = None,
                 parse_prompt: str = "Describe this slide.",
                 run_mode: str = "parallel",
                 include_page_screenshot: bool = False,
                 intermediate_image_format: str = "jpg",
                 max_image_size: int = 15 * 1024 * 1024,
                 downsize_horizontal_width: int = 1920,
                 cache_strategy=None,
                 page_renderer: Any = None, **kwargs):
        super().__init__(cache_strategy=cache_strategy)
        self.llm = llm if llm is not None else DEFAULT_VISION_LLM
        self.parse_prompt = parse_prompt
        self.run_mode = run_mode
        self.include_page_screenshot = include_page_screenshot
        self.intermediate_image_format = intermediate_image_format
        self.max_image_size = max_image_size
        self.downsize_horizontal_width = downsize_horizontal_width
        self.page_renderer = page_renderer

    def _render_pages(self, contents: bytes):
        if self.page_renderer is not None:
            return self.page_renderer(contents)
        try:
            from pdf2image import convert_from_bytes  # type: ignore
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "SlideParser page rendering requires `pdf2image` (plus "
                "poppler); pass page_renderer=... to supply images another "
                "way, or use PypdfParser for text-only decks"
            ) from exc
        return convert_from_bytes(
            contents, fmt=self.intermediate_image_format
        )

    def __wrapped__(self, contents: bytes, **kwargs) -> list[tuple[str, dict]]:
        import asyncio

        from pathway_tpu.internals.udfs import coerce_async
        from pathway_tpu.xpacks.llm._parser_utils import img_to_b64

        pages = self._render_pages(contents)
        if not pages:
            return []
        llm_fn = (
            self.llm.__wrapped__ if isinstance(self.llm, pw.UDF) else self.llm
        )
        fn = coerce_async(llm_fn)
        # carry the llm's configured model through to parse() (which reads
        # it via getattr and passes it as a call kwarg that would otherwise
        # override the user's choice with the default vision model)
        model = getattr(self.llm, "model", None) or (
            self.llm.kwargs.get("model")
            if hasattr(self.llm, "kwargs")
            else None
        )
        if model is not None:
            fn.model = model
        parsed, _ = asyncio.run(
            parse_images(
                pages,
                fn,
                self.parse_prompt,
                run_mode=self.run_mode,
                max_image_size=self.max_image_size,
                downsize_horizontal_width=self.downsize_horizontal_width,
            )
        )
        out = []
        n = len(pages)
        for i, text in enumerate(parsed):
            meta: dict = {"page_number": i + 1, "page_count": n}
            if self.include_page_screenshot:
                meta["page_screenshot"] = img_to_b64(pages[i])
            out.append((str(text), meta))
        return out
