"""RAG question-answering pipelines (reference
``xpacks/llm/question_answering.py:28-1007``).

``BaseRAGQuestionAnswerer`` wires retrieve (TPU KNN) → context build → chat;
``AdaptiveRAGQuestionAnswerer`` escalates document count geometrically until
the model answers. Answer/summarize/statistics REST endpoints are provided by
``build_server`` (see ``servers.py``).
"""

from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json, unwrap_json
from pathway_tpu.internals.table import Table
from pathway_tpu.xpacks.llm._utils import Doc, _coerce_sync
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.llms import BaseChat
from pathway_tpu.xpacks.llm.prompts import (
    BASE_PROMPT_TEMPLATE,
    SUMMARIZE_TEMPLATE,
)

logger = logging.getLogger(__name__)


def _limit_documents(documents: list[str], k: int) -> list[str]:
    return documents[:k]


def _docs_to_dicts(docs: Any) -> list[dict]:
    docs = unwrap_json(docs)
    out = []
    for d in docs or ():
        d = unwrap_json(d)
        if isinstance(d, dict):
            out.append(d)
        else:
            out.append({"text": str(d)})
    return out


class BaseContextProcessor(ABC):
    """Turns retrieved docs into the prompt context string (reference
    ``BaseContextProcessor``, question_answering.py:221)."""

    def maybe_unwrap_docs(self, docs) -> list[dict]:
        return _docs_to_dicts(docs)

    def apply(self, docs) -> str:
        return self.docs_to_context(self.maybe_unwrap_docs(docs))

    @abstractmethod
    def docs_to_context(self, docs: list[dict] | list[Doc]) -> str: ...

    def as_udf(self) -> pw.UDF:
        processor = self

        @pw.udf
        def context_processor_udf(docs) -> str:
            return processor.apply(docs)

        return context_processor_udf


class SimpleContextProcessor(BaseContextProcessor):
    """Joins doc texts, optionally with selected metadata (reference
    ``SimpleContextProcessor``, question_answering.py:257)."""

    def __init__(self, context_metadata_keys: list[str] | None = None, docs_separator: str = "\n\n"):
        self.context_metadata_keys = context_metadata_keys or ["path"]
        self.docs_separator = docs_separator

    def docs_to_context(self, docs: list[dict] | list[Doc]) -> str:
        parts = []
        for doc in docs:
            text = str(doc.get("text", ""))
            meta = doc.get("metadata") or {}
            meta = unwrap_json(meta) or {}
            tags = ", ".join(
                f"{k}: {meta[k]}" for k in self.context_metadata_keys if k in meta
            )
            parts.append(f"{text} ({tags})" if tags else text)
        return self.docs_separator.join(parts)


class BaseQuestionAnswerer(ABC):
    """REST-servable QA surface (reference ``BaseQuestionAnswerer``,
    question_answering.py:288)."""

    AnswerQuerySchema: type = schema_mod.schema_from_types(prompt=str)
    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    @abstractmethod
    def answer_query(self, pw_ai_queries: Table) -> Table: ...


class SummaryQuestionAnswerer(BaseQuestionAnswerer):
    SummarizeQuerySchema: type = schema_mod.schema_from_types(text_list=dt.ANY)

    @abstractmethod
    def summarize_query(self, summarize_queries: Table) -> Table: ...


class BaseRAGQuestionAnswerer(SummaryQuestionAnswerer):
    """Standard RAG pipeline (reference ``BaseRAGQuestionAnswerer``,
    question_answering.py:314): retrieve k docs → context → prompt → chat."""

    class AnswerQuerySchema(schema_mod.Schema):
        prompt: str
        filters: str | None
        model: str | None
        return_context_docs: bool | None

    class SummarizeQuerySchema(schema_mod.Schema):
        text_list: dt.ANY
        model: str | None

    def __init__(
        self,
        llm: BaseChat,
        indexer: DocumentStore | Any,
        *,
        default_llm_name: str | None = None,
        short_prompt_template: Any = None,
        long_prompt_template: Any = None,
        summarize_template: Any = None,
        search_topk: int = 6,
        prompt_template: str | Any | None = None,
        context_processor: BaseContextProcessor | None = None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.default_llm_name = default_llm_name
        self.search_topk = search_topk
        if prompt_template is None:
            prompt_template = long_prompt_template or short_prompt_template
        if prompt_template is None:
            self.prompt_template: Any = BASE_PROMPT_TEMPLATE
        elif isinstance(prompt_template, str):
            if "{context}" not in prompt_template or "{query}" not in prompt_template:
                raise ValueError(
                    "prompt_template must contain {context} and {query} placeholders"
                )
            self.prompt_template = prompt_template
        elif callable(prompt_template) or isinstance(prompt_template, pw.UDF):
            self.prompt_template = prompt_template
        else:
            raise TypeError(
                f"prompt_template must be a str, callable or UDF, got {prompt_template!r}"
            )
        self.summarize_template = summarize_template or SUMMARIZE_TEMPLATE
        self.context_processor = context_processor or SimpleContextProcessor()
        self.server = None
        self._pending_endpoints: list = []

    # -- the pipeline ------------------------------------------------------

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """Answer queries against the live index (reference ``answer_query``,
        question_answering.py:451)."""
        queries = pw_ai_queries.select(
            query=pw.this.prompt,
            k=self.search_topk,
            metadata_filter=pw.this.filters,
            filepath_globpattern=None,
            prompt=pw.this.prompt,
            return_context_docs=pw.this.return_context_docs,
        )
        retrieved = self.indexer.retrieve_query(
            queries.select(
                query=pw.this.query,
                k=pw.this.k,
                metadata_filter=pw.this.metadata_filter,
                filepath_globpattern=pw.this.filepath_globpattern,
            )
        )
        with_docs = queries.with_columns(
            docs=retrieved.promise_universes_are_equal(queries).result,
        )
        context_udf = self.context_processor.as_udf()
        template = self.prompt_template
        if isinstance(template, pw.UDF):
            prompt_expr = template(pw.this.prompt, context_udf(pw.this.docs))
        else:
            build = (
                (lambda context, query: template.format(context=context, query=query))
                if isinstance(template, str)
                else template
            )

            @pw.udf
            def build_prompt(query: str, context: str) -> str:
                return build(context=context, query=query)

            prompt_expr = build_prompt(pw.this.prompt, context_udf(pw.this.docs))

        prompts = with_docs.with_columns(rag_prompt=prompt_expr)
        llm = self.llm

        answers = prompts.with_columns(
            response=llm(
                pw.apply_with_type(
                    lambda p: Json([{"role": "user", "content": p}]),
                    dt.JSON,
                    pw.this.rag_prompt,
                )
            )
        )

        @pw.udf
        def format_answer(response, docs, return_context_docs) -> Json:
            out: dict = {"response": response}
            if return_context_docs:
                out["context_docs"] = _docs_to_dicts(docs)
            return Json(out)

        return answers.select(
            result=format_answer(pw.this.response, pw.this.docs, pw.this.return_context_docs)
        )

    def summarize_query(self, summarize_queries: Table) -> Table:
        """Summarize a list of texts (reference ``summarize_query``,
        question_answering.py:491)."""
        llm = self.llm
        template = self.summarize_template

        @pw.udf
        def build_prompt(text_list) -> Json:
            texts = [str(t) for t in unwrap_json(text_list) or ()]
            prompt = template.format(text="\n\n".join(texts))
            return Json([{"role": "user", "content": prompt}])

        answers = summarize_queries.with_columns(
            response=llm(build_prompt(pw.this.text_list))
        )
        return answers.select(
            result=pw.apply_with_type(lambda r: Json({"response": r}), dt.JSON, pw.this.response)
        )

    def retrieve(self, retrieve_queries: Table) -> Table:
        return self.indexer.retrieve_query(retrieve_queries)

    def statistics(self, statistics_queries: Table) -> Table:
        return self.indexer.statistics_query(statistics_queries)

    def list_documents(self, list_documents_queries: Table) -> Table:
        return self.indexer.inputs_query(list_documents_queries)

    # -- serving -----------------------------------------------------------

    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        """Create the QA REST server (reference ``build_server``,
        question_answering.py:527)."""
        from pathway_tpu.xpacks.llm.servers import QASummaryRestServer

        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)

    def run_server(self, *args, **kwargs):
        if self.server is None:
            raise ValueError("call build_server first")
        return self.server.run(*args, **kwargs)


def answer_with_geometric_rag_strategy(
    questions: Table | Any,
    documents: Any,
    llm: BaseChat,
    prompt_template: str,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    strict_prompt: bool = False,
) -> Any:
    """Ask with n docs, retry with factor*n docs while the answer is
    "no information" (reference ``answer_with_geometric_rag_strategy``,
    question_answering.py:97). Host-side loop over the chat callable."""
    chat = _coerce_sync(llm.__wrapped__)

    def answer_one(question: str, docs: list[str]) -> str:
        n = n_starting_documents
        for _ in range(max_iterations):
            context = "\n\n".join(_limit_documents(docs, n))
            prompt = prompt_template.format(context=context, query=question)
            response = chat([{"role": "user", "content": prompt}])
            if response and "no information" not in str(response).lower():
                return str(response)
            n *= factor
        return "No information found."

    @pw.udf
    def geometric_udf(question: str, docs) -> str:
        doc_texts = [
            str(d.get("text", "") if isinstance(d, dict) else d)
            for d in (_docs_to_dicts(docs))
        ]
        return answer_one(question, doc_texts)

    if isinstance(questions, Table):
        return questions.select(
            result=geometric_udf(pw.this.prompt, pw.this.docs)
        )
    return answer_one(questions, documents)


def answer_with_geometric_rag_strategy_from_index(
    questions: Table,
    index,
    documents_column,
    llm: BaseChat,
    prompt_template: str,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    strict_prompt: bool = False,
) -> Table:
    """Geometric strategy fed straight from a DataIndex (reference
    ``answer_with_geometric_rag_strategy_from_index``,
    question_answering.py:162)."""
    max_docs = n_starting_documents * factor ** (max_iterations - 1)
    matches = index.query_as_of_now(
        questions.prompt, number_of_matches=max_docs, collapse_rows=True
    )
    col = documents_column if isinstance(documents_column, str) else documents_column._name
    with_docs = questions.with_columns(
        docs=matches.promise_universes_are_equal(questions)[col]
    )
    return answer_with_geometric_rag_strategy(
        with_docs, None, llm, prompt_template, n_starting_documents, factor,
        max_iterations, strict_prompt,
    )


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Adaptive RAG: geometric document-count escalation (reference
    ``AdaptiveRAGQuestionAnswerer``, question_answering.py:620)."""

    def __init__(
        self,
        llm: BaseChat,
        indexer: DocumentStore | Any,
        *,
        default_llm_name: str | None = None,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, default_llm_name=default_llm_name, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """reference ``answer_query``, question_answering.py:709"""
        max_docs = self.n_starting_documents * self.factor ** (self.max_iterations - 1)
        queries = pw_ai_queries.select(
            query=pw.this.prompt,
            k=max_docs,
            metadata_filter=pw.this.filters,
            filepath_globpattern=None,
            prompt=pw.this.prompt,
        )
        retrieved = self.indexer.retrieve_query(
            queries.select(
                query=pw.this.query,
                k=pw.this.k,
                metadata_filter=pw.this.metadata_filter,
                filepath_globpattern=pw.this.filepath_globpattern,
            )
        )
        with_docs = queries.with_columns(
            docs=retrieved.promise_universes_are_equal(queries).result
        )
        template = (
            self.prompt_template
            if isinstance(self.prompt_template, str)
            else BASE_PROMPT_TEMPLATE
        )
        answered = answer_with_geometric_rag_strategy(
            with_docs,
            None,
            self.llm,
            template,
            self.n_starting_documents,
            self.factor,
            self.max_iterations,
            self.strict_prompt,
        )
        return answered.select(
            result=pw.apply_with_type(
                lambda r: Json({"response": r}), dt.JSON, pw.this.result
            )
        )


class DeckRetriever(BaseQuestionAnswerer):
    """Slide-deck retriever app (reference ``DeckRetriever``,
    question_answering.py:736)."""

    excluded_response_metadata = ["b64_image"]

    def __init__(self, indexer, *, search_topk: int = 6):
        self.indexer = indexer
        self.search_topk = search_topk
        self.server = None

    def answer_query(self, pw_ai_queries: Table) -> Table:
        queries = pw_ai_queries.select(
            query=pw.this.prompt,
            k=self.search_topk,
            metadata_filter=None,
            filepath_globpattern=None,
        )
        return self.indexer.retrieve_query(queries)


def send_post_request(url: str, data: dict, headers: dict | None = None,
                      timeout: int | None = None):
    """POST JSON and return the decoded JSON response (reference
    ``question_answering.py:send_post_request``)."""
    from pathway_tpu.xpacks.llm._utils import post_json

    return post_json(url, data, headers, timeout)


class RAGClient:
    """HTTP client for RAG apps served by ``QARestServer`` /
    ``QASummaryRestServer`` (reference ``question_answering.py:854``)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int | None = 90,
        additional_headers: dict | None = None,
    ):
        from pathway_tpu.xpacks.llm.vector_store import VectorStoreClient

        err = "Either (`host` and `port`) or `url` must be provided, but not both."
        if url is not None:
            if host is not None or port is not None:
                raise ValueError(err)
            self.url = url
        else:
            if host is None:
                raise ValueError(err)
            port = port or 80
            protocol = "https" if port == 443 else "http"
            self.url = f"{protocol}://{host}:{port}"

        self.timeout = timeout
        self.additional_headers = additional_headers or {}
        self.index_client = VectorStoreClient(
            url=self.url,
            timeout=self.timeout,
            additional_headers=self.additional_headers,
        )

    def retrieve(self, query: str, k: int = 3, metadata_filter: str | None = None,
                 filepath_globpattern: str | None = None):
        """Closest documents from the store for ``query``."""
        return self.index_client.query(
            query=query, k=k, metadata_filter=metadata_filter,
            filepath_globpattern=filepath_globpattern,
        )

    def statistics(self):
        """Index statistics."""
        return self.index_client.get_vectorstore_statistics()

    def pw_ai_answer(self, prompt: str, filters: str | None = None,
                     model: str | None = None):
        """RAG answer for ``prompt`` with optional metadata ``filters``."""
        payload: dict = {"prompt": prompt}
        if filters:
            payload["filters"] = filters
        if model:
            payload["model"] = model
        return send_post_request(
            f"{self.url}/v1/pw_ai_answer", payload, self.additional_headers,
            timeout=self.timeout,
        )

    answer = pw_ai_answer

    def pw_ai_summary(self, text_list, model: str | None = None):
        """Summarize ``text_list`` server-side."""
        payload: dict = {"text_list": list(text_list)}
        if model:
            payload["model"] = model
        return send_post_request(
            f"{self.url}/v1/pw_ai_summary", payload, self.additional_headers,
            timeout=self.timeout,
        )

    summarize = pw_ai_summary

    def pw_list_documents(self, filters: str | None = None, keys=("path",)):
        """List indexed documents, projecting metadata to ``keys``."""
        payload: dict = {}
        if filters:
            payload["metadata_filter"] = filters
        response = send_post_request(
            f"{self.url}/v1/pw_list_documents", payload, self.additional_headers,
            timeout=self.timeout,
        )
        if not response:
            return []
        if keys:
            return [{k: v for k, v in dc.items() if k in keys} for dc in response]
        return response
