"""Embedder UDFs (reference ``xpacks/llm/embedders.py:64-413``).

The flagship is ``SentenceTransformerEmbedder`` — in the reference it calls
torch ``model.encode`` per row on CPU/GPU (``embedders.py:270-313``); here it
is a **batched TPU UDF**: each engine microbatch is tokenized host-side,
padded into pow2 buckets and embedded in one jitted XLA call on the MXU
(``pathway_tpu.models.embedder``). API-client embedders (OpenAI / LiteLLM /
Gemini) keep the reference's async-UDF shape and are gated on their SDKs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.expression import ColumnExpression


class BaseEmbedder(pw.UDF):
    """Base embedder UDF (reference ``BaseEmbedder``, embedders.py:64).

    ``__call__`` on a string column returns an embedding-vector column;
    ``get_embedding_dimension`` embeds a probe string to discover the dim.
    """

    def get_embedding_dimension(self, **kwargs) -> int:
        return len(self._embed_sync(".", **kwargs))

    def _embed_sync(self, text: str, **kwargs):
        import asyncio
        import inspect

        fun = self.__wrapped__
        if inspect.iscoroutinefunction(fun):
            return asyncio.run(fun(text, **kwargs))
        if self.batch:
            return fun([text], **{k: [v] for k, v in kwargs.items()})[0]
        return fun(text, **kwargs)


class SentenceTransformerEmbedder(BaseEmbedder):
    """TPU-native sentence embedder (reference
    ``SentenceTransformerEmbedder``, embedders.py:270-313).

    Instead of delegating to the sentence-transformers torch stack, the model
    is a pure-JAX MiniLM-class encoder; a whole engine microbatch is embedded
    per XLA dispatch. ``model`` may be a preset name (``"minilm-l6"``,
    ``"minilm-l12"``, ``"bge-small"``), a path to a local HuggingFace
    tokenizer+weights dir, or a ready ``SentenceEmbedderModel``.
    """

    def __init__(
        self,
        model: Any = "minilm-l6",
        call_kwargs: dict = {},
        device: str = "tpu",
        *,
        max_batch_size: int | None = 1024,
        cache_strategy: udfs.CacheStrategy | None = None,
        deferred: bool = False,
        **init_kwargs,
    ):
        # deferred=True: fully-async streaming mode — the engine epoch
        # dispatches the embed chunks and moves on; results are injected
        # at a later engine time, overlapping host dataflow with the TPU
        # (opt-in because derived tables see the vectors slightly later
        # than the raw rows, exactly like the reference's fully-async
        # UDFs)
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            executor=udfs.fully_async_executor() if deferred else None,
        )
        from pathway_tpu.models import (
            BGE_SMALL,
            MINILM_L6,
            MINILM_L12,
            SentenceEmbedderModel,
        )

        presets = {
            "minilm-l6": MINILM_L6,
            "minilm-l12": MINILM_L12,
            "bge-small": BGE_SMALL,
        }
        if isinstance(model, SentenceEmbedderModel):
            self.model = model
        elif isinstance(model, str) and model in presets:
            self.model = SentenceEmbedderModel(cfg=presets[model], **init_kwargs)
        elif isinstance(model, str):
            # local HF-format directory: load real pretrained weights
            # (all-MiniLM etc.) when the dir has a checkpoint, else just the
            # tokenizer (air-gapped deployments with only tokenizer files)
            from pathway_tpu.models.checkpoint import has_checkpoint_weights

            if has_checkpoint_weights(model):
                self.model = SentenceEmbedderModel.from_pretrained(
                    model, **init_kwargs
                )
            else:
                self.model = SentenceEmbedderModel.from_local(model, **init_kwargs)
        else:
            raise TypeError(f"unsupported model spec: {model!r}")
        self.device = device
        self.kwargs = dict(call_kwargs)

    def __wrapped__(self, input: list[str], **kwargs) -> list[np.ndarray]:
        vecs = self.model.embed_batch([t if t is not None else "" for t in input])
        return list(vecs)

    # two-phase protocol (picked up by UDF._call_batched): an epoch's chunks
    # are all dispatched, then drained with one device round trip
    def submit_batch(self, input: list[str], **kwargs):
        return self.model.embed_submit(
            [t if t is not None else "" for t in input]
        )

    def resolve_batch(self, handles) -> list[list[np.ndarray]]:
        return [list(vecs) for vecs in self.model.embed_resolve(handles)]

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.model.dim

    def __call__(self, input: ColumnExpression, **kwargs) -> ColumnExpression:
        return super().__call__(input, **kwargs)


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI embeddings API client UDF (reference ``OpenAIEmbedder``,
    embedders.py:85-178). Async, retried/capacity-limited via executor."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "text-embedding-3-small",
        **openai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(openai_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import openai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("OpenAIEmbedder requires the `openai` package") from exc
        kwargs = {**self.kwargs, **kwargs}
        api_kwargs = {
            k: kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in kwargs
        }
        client = openai.AsyncOpenAI(**api_kwargs)
        ret = await client.embeddings.create(input=[input or "."], **kwargs)
        return np.array(ret.data[0].embedding)


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM multi-provider embedder (reference ``LiteLLMEmbedder``,
    embedders.py:180-268)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        **llmlite_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(llmlite_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import litellm
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("LiteLLMEmbedder requires the `litellm` package") from exc
        ret = await litellm.aembedding(input=[input or "."], **{**self.kwargs, **kwargs})
        return np.array(ret.data[0]["embedding"])


class GeminiEmbedder(BaseEmbedder):
    """Google Gemini embeddings client (reference ``GeminiEmbedder``,
    embedders.py:330-413)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "models/text-embedding-004",
        **genai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(genai_kwargs)
        self.model = model

    def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import google.generativeai as genai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "GeminiEmbedder requires the `google-generativeai` package"
            ) from exc
        response = genai.embed_content(
            model=self.model, content=input or ".", **{**self.kwargs, **kwargs}
        )
        return np.array(response["embedding"])
