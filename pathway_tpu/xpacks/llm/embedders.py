"""Embedder UDFs (reference ``xpacks/llm/embedders.py:64-413``).

The flagship is ``SentenceTransformerEmbedder`` — in the reference it calls
torch ``model.encode`` per row on CPU/GPU (``embedders.py:270-313``); here it
is a **batched TPU UDF**: each engine microbatch is tokenized host-side,
padded into pow2 buckets and embedded in one jitted XLA call on the MXU
(``pathway_tpu.models.embedder``). API-client embedders (OpenAI / LiteLLM /
Gemini) keep the reference's async-UDF shape and are gated on their SDKs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals import udfs
from pathway_tpu.internals.expression import ColumnExpression


# entries kept in the per-embedder dedup LRU (text -> embedding); at
# MiniLM dims that is ~12 MB of host memory at the bound
_DEDUP_MAX = 8192


def _dedup_on() -> bool:
    from pathway_tpu.internals.config import pathway_config

    return pathway_config.embed_dedup


class BaseEmbedder(pw.UDF):
    """Base embedder UDF (reference ``BaseEmbedder``, embedders.py:64).

    ``__call__`` on a string column returns an embedding-vector column;
    ``get_embedding_dimension`` embeds a probe string to discover the dim.
    """

    def get_embedding_dimension(self, **kwargs) -> int:
        return len(self._embed_sync(".", **kwargs))

    def _embed_sync(self, text: str, **kwargs):
        import asyncio
        import inspect

        fun = self.__wrapped__
        if inspect.iscoroutinefunction(fun):
            return asyncio.run(fun(text, **kwargs))
        if self.batch:
            return fun([text], **{k: [v] for k, v in kwargs.items()})[0]
        return fun(text, **kwargs)


class SentenceTransformerEmbedder(BaseEmbedder):
    """TPU-native sentence embedder (reference
    ``SentenceTransformerEmbedder``, embedders.py:270-313).

    Instead of delegating to the sentence-transformers torch stack, the model
    is a pure-JAX MiniLM-class encoder; a whole engine microbatch is embedded
    per XLA dispatch. ``model`` may be a preset name (``"minilm-l6"``,
    ``"minilm-l12"``, ``"bge-small"``), a path to a local HuggingFace
    tokenizer+weights dir, or a ready ``SentenceEmbedderModel``.
    """

    def __init__(
        self,
        model: Any = "minilm-l6",
        call_kwargs: dict = {},
        device: str = "tpu",
        *,
        max_batch_size: int | None = 1024,
        cache_strategy: udfs.CacheStrategy | None = None,
        deferred: bool = False,
        **init_kwargs,
    ):
        # deferred=True: fully-async streaming mode — the engine epoch
        # dispatches the embed chunks and moves on; results are injected
        # at a later engine time, overlapping host dataflow with the TPU
        # (opt-in because derived tables see the vectors slightly later
        # than the raw rows, exactly like the reference's fully-async
        # UDFs)
        super().__init__(
            deterministic=True,
            batch=True,
            max_batch_size=max_batch_size,
            cache_strategy=cache_strategy,
            executor=udfs.fully_async_executor() if deferred else None,
        )
        from pathway_tpu.models import (
            BGE_SMALL,
            MINILM_L6,
            MINILM_L12,
            SentenceEmbedderModel,
        )

        presets = {
            "minilm-l6": MINILM_L6,
            "minilm-l12": MINILM_L12,
            "bge-small": BGE_SMALL,
        }
        if isinstance(model, SentenceEmbedderModel):
            self.model = model
        elif isinstance(model, str) and model in presets:
            self.model = SentenceEmbedderModel(cfg=presets[model], **init_kwargs)
        elif isinstance(model, str):
            # local HF-format directory: load real pretrained weights
            # (all-MiniLM etc.) when the dir has a checkpoint, else just the
            # tokenizer (air-gapped deployments with only tokenizer files)
            from pathway_tpu.models.checkpoint import has_checkpoint_weights

            if has_checkpoint_weights(model):
                self.model = SentenceEmbedderModel.from_pretrained(
                    model, **init_kwargs
                )
            else:
                self.model = SentenceEmbedderModel.from_local(model, **init_kwargs)
        else:
            raise TypeError(f"unsupported model spec: {model!r}")
        self.device = device
        self.kwargs = dict(call_kwargs)
        # content-keyed dedup (PATHWAY_TPU_EMBED_DEDUP): re-ingesting a file
        # re-embeds mostly-unchanged chunks; byte-identical texts reuse their
        # vector instead of re-dispatching — the ingest analogue of the
        # serving-side prefix cache
        from collections import OrderedDict

        self._dedup: OrderedDict[str, np.ndarray] = OrderedDict()
        self.dedup_stats = {"hits": 0, "misses": 0}

    def _dedup_plan(self, texts: list[str]):
        """Split a batch into cached rows and unique misses.

        Returns ``(plan, miss_texts)`` where each plan entry is
        ``("h", vec)`` for an LRU hit or ``("m", i)`` indexing into
        ``miss_texts``; duplicate texts within the batch share one miss.
        """
        plan: list[tuple[str, Any]] = []
        miss_texts: list[str] = []
        pos: dict[str, int] = {}
        for t in texts:
            v = self._dedup.get(t)
            if v is not None:
                self._dedup.move_to_end(t)
                self.dedup_stats["hits"] += 1
                plan.append(("h", v))
                continue
            p = pos.get(t)
            if p is None:
                p = pos[t] = len(miss_texts)
                miss_texts.append(t)
                self.dedup_stats["misses"] += 1
            else:
                self.dedup_stats["hits"] += 1
            plan.append(("m", p))
        return plan, miss_texts

    def _dedup_fill(self, plan, miss_texts, miss_vecs) -> list[np.ndarray]:
        for t, v in zip(miss_texts, miss_vecs):
            self._dedup[t] = np.asarray(v)
            if len(self._dedup) > _DEDUP_MAX:
                self._dedup.popitem(last=False)
        out: list[np.ndarray] = []
        for kind, x in plan:
            v = x if kind == "h" else np.asarray(miss_vecs[x])
            out.append(np.array(v, copy=True))
        return out

    def __wrapped__(self, input: list[str], **kwargs) -> list[np.ndarray]:
        texts = [t if t is not None else "" for t in input]
        if not _dedup_on():
            return list(self.model.embed_batch(texts))
        plan, miss_texts = self._dedup_plan(texts)
        miss_vecs = self.model.embed_batch(miss_texts) if miss_texts else []
        return self._dedup_fill(plan, miss_texts, miss_vecs)

    # two-phase protocol (picked up by UDF._call_batched): an epoch's chunks
    # are all dispatched, then drained with one device round trip
    def submit_batch(self, input: list[str], **kwargs):
        texts = [t if t is not None else "" for t in input]
        if not _dedup_on():
            return ("raw", self.model.embed_submit(texts))
        plan, miss_texts = self._dedup_plan(texts)
        # an all-hit batch never touches the device
        h = self.model.embed_submit(miss_texts) if miss_texts else None
        return ("dedup", h, plan, miss_texts)

    def resolve_batch(self, handles) -> list[list[np.ndarray]]:
        model_handles = [h[1] for h in handles if h[1] is not None]
        resolved = iter(
            self.model.embed_resolve(model_handles) if model_handles else []
        )
        out: list[list[np.ndarray]] = []
        for h in handles:
            if h[0] == "raw":
                out.append(list(next(resolved)))
                continue
            _, mh, plan, miss_texts = h
            miss_vecs = list(next(resolved)) if mh is not None else []
            out.append(self._dedup_fill(plan, miss_texts, miss_vecs))
        return out

    # token-level submit path (late-interaction ingest): compressed
    # per-token states for the doc bank, encoded ONCE per document on the
    # same StageWorker pipeline as the pooled path. Not a UDF column —
    # the bank consumer (FusedRAGPipeline / DocumentStore) drives these
    # directly, two-phase like embed_submit/resolve.
    def embed_tokens_submit(self, input: list[str], dc: int | None = None):
        texts = [t if t is not None else "" for t in input]
        return self.model.token_bank_submit(texts, dc=dc)

    def embed_tokens_resolve(self, handles):
        """-> ``[(payload int8 (n, S, dc), scale f32 (n, S, 1))]`` per
        submitted handle."""
        return self.model.token_bank_resolve(handles)

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.model.dim

    def __call__(self, input: ColumnExpression, **kwargs) -> ColumnExpression:
        return super().__call__(input, **kwargs)


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI embeddings API client UDF (reference ``OpenAIEmbedder``,
    embedders.py:85-178). Async, retried/capacity-limited via executor."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "text-embedding-3-small",
        **openai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(openai_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import openai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("OpenAIEmbedder requires the `openai` package") from exc
        kwargs = {**self.kwargs, **kwargs}
        api_kwargs = {
            k: kwargs.pop(k)
            for k in ("api_key", "base_url", "organization")
            if k in kwargs
        }
        client = openai.AsyncOpenAI(**api_kwargs)
        ret = await client.embeddings.create(input=[input or "."], **kwargs)
        return np.array(ret.data[0].embedding)


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM multi-provider embedder (reference ``LiteLLMEmbedder``,
    embedders.py:180-268)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = None,
        **llmlite_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(llmlite_kwargs)
        if model is not None:
            self.kwargs["model"] = model

    async def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import litellm
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError("LiteLLMEmbedder requires the `litellm` package") from exc
        ret = await litellm.aembedding(input=[input or "."], **{**self.kwargs, **kwargs})
        return np.array(ret.data[0]["embedding"])


class GeminiEmbedder(BaseEmbedder):
    """Google Gemini embeddings client (reference ``GeminiEmbedder``,
    embedders.py:330-413)."""

    def __init__(
        self,
        capacity: int | None = None,
        retry_strategy: udfs.AsyncRetryStrategy | None = None,
        cache_strategy: udfs.CacheStrategy | None = None,
        model: str | None = "models/text-embedding-004",
        **genai_kwargs,
    ):
        executor = udfs.async_executor(capacity=capacity, retry_strategy=retry_strategy)
        super().__init__(executor=executor, cache_strategy=cache_strategy)
        self.kwargs = dict(genai_kwargs)
        self.model = model

    def __wrapped__(self, input: str, **kwargs) -> np.ndarray:
        try:
            import google.generativeai as genai
        except ImportError as exc:  # pragma: no cover - gated dependency
            raise ImportError(
                "GeminiEmbedder requires the `google-generativeai` package"
            ) from exc
        response = genai.embed_content(
            model=self.model, content=input or ".", **{**self.kwargs, **kwargs}
        )
        return np.array(response["embedding"])
