"""Image parsing helpers for vision-LLM document pipelines (reference
``xpacks/llm/_parser_utils.py``)."""

from __future__ import annotations

import base64
import io
import logging

from pathway_tpu.xpacks.llm.constants import DEFAULT_VISION_MODEL

logger = logging.getLogger(__name__)


def img_to_b64(img) -> str:
    """PNG-encode a PIL image to a base64 string (reference ``:18``)."""
    buffer = io.BytesIO()
    img.save(buffer, format="PNG")
    return base64.b64encode(buffer.getbuffer()).decode("utf-8")


def maybe_downscale(img, max_image_size: int, downsize_horizontal_width: int):
    """Downscale an image keeping aspect ratio if its raw RGB size exceeds
    ``max_image_size`` bytes (reference ``:25``)."""
    img_size = img.size[0] * img.size[1] * 3
    if img_size > max_image_size:
        logger.info(
            "Image size %.1fMB exceeds the limit; resizing.",
            img_size / (1024 * 1024),
        )
        ratio = img.size[1] / img.size[0]
        img = img.resize(
            (downsize_horizontal_width, int(downsize_horizontal_width * ratio))
        )
    return img


async def parse(b_64_img, llm, prompt: str, model: str | None = None, **kwargs) -> str:
    """Describe a base64 image with a vision LLM (reference ``:49``);
    falls back to the LLM's default model, then ``DEFAULT_VISION_MODEL``."""
    if model is None:
        model = getattr(llm, "model", None) or DEFAULT_VISION_MODEL
    content = [
        {"type": "text", "text": prompt},
        {
            "type": "image_url",
            "image_url": {"url": f"data:image/png;base64,{b_64_img}"},
        },
    ]
    messages = [{"role": "user", "content": content}]
    fn = getattr(llm, "__wrapped__", llm)
    import inspect

    response = fn(messages, model=model, **kwargs)
    if inspect.isawaitable(response):
        response = await response
    return response


async def parse_image_details(b_64_img, parse_schema, model: str = DEFAULT_VISION_MODEL,
                              openai_client_args: dict | None = None, **kwargs):
    """Parse a structured schema from an image via an OpenAI-compatible
    vision endpoint (reference ``:96``); needs network + the `instructor`
    package, both absent here — gated accordingly."""
    from pathway_tpu.optional_import import optional_imports

    with optional_imports("xpack-llm"):
        import instructor  # noqa: F401
        import openai  # noqa: F401
    raise NotImplementedError("structured image parsing requires network access")
