"""``pw.debug`` — table literals, compute-and-print, pandas interop.

Parity with reference ``python/pathway/debug/__init__.py``:
``table_from_markdown``, ``table_from_pandas``, ``table_from_rows``,
``compute_and_print``, ``compute_and_print_update_stream``,
``table_to_pandas``, ``table_from_csv`` / ``table_to_csv``.
"""

from __future__ import annotations

import re
from typing import Any, Iterable

import numpy as np
import pandas as pd

from pathway_tpu.engine.batch import Batch
from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import ERROR, Pointer, hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.run import capture_table
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

__all__ = [
    "table_from_markdown",
    "table_from_pandas",
    "table_from_rows",
    "table_from_parquet",
    "table_to_parquet",
    "table_from_csv",
    "table_to_csv",
    "table_to_pandas",
    "compute_and_print",
    "compute_and_print_update_stream",
    "table_to_dicts",
    "StreamGenerator",
]


def _parse_value(raw: str):
    raw = raw.strip()
    if raw in ("", "None"):
        return None
    if raw == "True":
        return True
    if raw == "False":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
        return raw[1:-1]
    return raw


def table_from_markdown(
    table_def: str,
    id_from: list[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Any | None = None,
    _stream: bool = False,
) -> Table:
    """Build a static table from a markdown/ascii table literal."""
    lines = [
        ln.strip()
        for ln in table_def.strip().splitlines()
        if ln.strip() and not set(ln.strip()) <= {"-", "|", "+", " ", "="}
    ]
    header = [h.strip() for h in lines[0].split("|")]
    if header and header[-1] == "" and not lines[0].rstrip().endswith("| "):
        # allow trailing pipe style; empty LEADING header cell means id column
        pass
    while len(header) > 1 and header[-1] == "" and all(
        ln.rstrip().endswith("|") for ln in lines
    ):
        header = header[:-1]
    rows_raw = []
    for ln in lines[1:]:
        cells = [c.strip() for c in ln.split("|")]
        # pad/truncate to header length
        cells += [""] * (len(header) - len(cells))
        rows_raw.append(cells[: len(header)])
    has_id = header and header[0] in ("", "id")
    special = {"__time__", "__diff__"}
    value_cols = [
        h for h in (header[1:] if has_id else header) if h not in special
    ]
    parsed_rows = []
    for cells in rows_raw:
        record = dict(zip(header, cells))
        values = {c: _parse_value(record[c]) for c in value_cols}
        rid = record.get(header[0]) if has_id else None
        time = int(record["__time__"]) if "__time__" in record else 0
        diff = int(record["__diff__"]) if "__diff__" in record else 1
        parsed_rows.append((rid, values, time, diff))
    # schema inference
    if schema is not None:
        sch = schema
        col_dtypes = {n: c.dtype for n, c in sch.__columns__.items()}
        value_cols = [c for c in value_cols if c in sch.__columns__]
    else:
        col_dtypes = {}
        for c in value_cols:
            vals = [r[1][c] for r in parsed_rows if r[1][c] is not None]
            col_dtypes[c] = (
                dt.lub(*[dt.dtype_of_value(v) for v in vals]) if vals else dt.ANY
            )
        defs = {
            c: schema_mod.ColumnDefinition(dtype=col_dtypes[c], name=c)
            for c in value_cols
        }
        sch = schema_mod.schema_builder_from_definitions(defs)
    id_from = id_from or sch.primary_key_columns()
    if (
        id_from is None
        and not has_id
        and any(diff != 1 for _r, _v, _t, diff in parsed_rows)
    ):
        # update-stream literal: key by row content so retractions match
        id_from = value_cols

    rows: list[tuple[int, tuple, int, int]] = []  # (key, row, time, diff)
    for i, (rid, values, time, diff) in enumerate(parsed_rows):
        coerced = tuple(
            dt.coerce_value(values[c], col_dtypes[c]) for c in value_cols
        )
        if id_from is not None:
            key = hash_values(*[values[c] for c in id_from])
        elif rid is not None and str(rid) != "":
            key = (
                int(rid) if unsafe_trusted_ids and str(rid).isdigit() else hash_values(str(rid))
            )
        else:
            key = hash_values(i)
        rows.append((key, coerced, time, diff))
    return _static_table_from_keyed_rows(value_cols, sch, rows, stream=_stream)


parse_to_table = table_from_markdown


def _static_table_from_keyed_rows(
    value_cols: list[str],
    sch,
    rows: list[tuple[int, tuple, int, int]],
    stream: bool = False,
) -> Table:
    node = InputNode(G.engine_graph, value_cols, name="StaticTable")
    if stream or any(t != 0 for _k, _r, t, _d in rows):
        from pathway_tpu.io._streams import StaticStreamConnector

        conn = StaticStreamConnector(node, rows, value_cols)
        G.register_connector(conn)
    else:
        batch = Batch.from_rows(value_cols, [(k, r, d) for k, r, _t, d in rows])
        G.register_static_source(node, lambda b=batch: b)
    return Table(node, sch, Universe())


def table_from_rows(
    schema: Any,
    rows: list[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    cols = list(schema.column_names())
    pk = schema.primary_key_columns()
    out = []
    seen: dict = {}
    for row in rows:
        if is_stream:
            *vals, time, diff = row
        else:
            vals, time, diff = list(row), 0, 1
        values = dict(zip(cols, vals))
        if pk:
            key = hash_values(*[values[c] for c in pk])
        else:
            key = hash_values(*vals)
            if not is_stream:
                # duplicate static rows are distinct rows: salt repeats with
                # their occurrence index (first occurrence keeps the plain
                # content hash for backward-compatible keys)
                n = seen.get(key, 0)
                seen[key] = n + 1
                if n:
                    key = hash_values(*vals, n)
        out.append((key, tuple(vals), time, diff))
    return _static_table_from_keyed_rows(cols, schema, out, stream=is_stream)


def table_from_pandas(
    df: pd.DataFrame,
    id_from: list[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Any | None = None,
) -> Table:
    if schema is None:
        schema = schema_mod.schema_from_pandas(df, id_from=id_from)
    cols = [c for c in schema.column_names()]
    rows = []
    pk = id_from or schema.primary_key_columns()
    dtypes = {n: c.dtype for n, c in schema.__columns__.items()}
    for i, (idx, row) in enumerate(df.iterrows()):
        values = {}
        for c in cols:
            v = row[c]
            if isinstance(v, float) and pd.isna(v):
                v = None
            elif v is pd.NaT:
                v = None
            elif isinstance(v, np.integer):
                v = int(v)
            elif isinstance(v, np.floating):
                v = float(v)
            elif isinstance(v, np.bool_):
                v = bool(v)
            values[c] = dt.coerce_value(v, dtypes[c])
        if pk:
            key = hash_values(*[values[c] for c in pk])
        else:
            key = hash_values(idx if not isinstance(idx, int) else i)
        rows.append((key, tuple(values[c] for c in cols), 0, 1))
    return _static_table_from_keyed_rows(cols, schema, rows)


def table_from_csv(path: str, **kwargs) -> Table:
    return table_from_pandas(pd.read_csv(path), **kwargs)


def table_from_parquet(path: str, **kwargs) -> Table:
    return table_from_pandas(pd.read_parquet(path), **kwargs)


def _format_value(v) -> str:
    if v is None:
        return "None"
    if v is ERROR:
        return "Error"
    if isinstance(v, str):
        return v
    return repr(v) if isinstance(v, (bytes,)) else str(v)


def table_to_pandas(table: Table, *, include_id: bool = True) -> pd.DataFrame:
    cap = capture_table(table)
    cols = cap.column_names
    keys = []
    data: dict[str, list] = {c: [] for c in cols}
    for k, row in sorted(cap.state.rows.items()):
        keys.append(Pointer(k))
        for c, v in zip(cols, row):
            data[c].append(v)
    df = pd.DataFrame(data, columns=cols)
    if include_id:
        df.index = pd.Index(keys, name="id")
    return df


def table_to_csv(table: Table, path: str, **kwargs) -> None:
    table_to_pandas(table, include_id=False).to_csv(path, index=False, **kwargs)


def table_to_parquet(table: Table, path: str, **kwargs) -> None:
    table_to_pandas(table, include_id=False).to_parquet(path, index=False)


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    squash_updates: bool = True,
) -> None:
    cap = capture_table(table)
    cols = cap.column_names
    items = sorted(
        cap.state.rows.items(), key=lambda kv: tuple(map(_sort_key, kv[1]))
    )
    if n_rows is not None:
        items = items[:n_rows]
    header = (["id"] if include_id else []) + ["|"] + cols if include_id else cols
    out_rows = []
    for k, row in items:
        cells = ([repr(Pointer(k))] if include_id else []) + (
            ["|"] if include_id else []
        ) + [_format_value(v) for v in row]
        out_rows.append(cells)
    widths = [
        max([len(h) for h in [str(x)]] + [len(r[i]) for r in out_rows])
        for i, x in enumerate(header)
    ] if out_rows else [len(str(h)) for h in header]
    print(" ".join(str(h).ljust(w) for h, w in zip(header, widths)).rstrip())
    for r in out_rows:
        print(" ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


def _sort_key(v):
    if v is None:
        return (0, "")
    if v is ERROR:
        return (3, "")
    try:
        return (1, float(v))
    except (TypeError, ValueError):
        return (2, str(v))


def compute_and_print_update_stream(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
) -> None:
    cap = capture_table(table)
    cols = list(cap.column_names)
    print("\t".join((["id"] if include_id else []) + cols + ["__time__", "__diff__"]))
    count = 0
    for time, batch in cap.updates:
        for k, row, diff in batch.rows():
            if n_rows is not None and count >= n_rows:
                return
            cells = ([repr(Pointer(k))] if include_id else []) + [
                _format_value(v) for v in row
            ] + [str(time), str(diff)]
            print("\t".join(cells))
            count += 1


def table_to_dicts(table: Table, **kwargs):
    """Return ``(keys, {column: {key: value}})`` for a computed table
    (reference ``debug/__init__.py:61``)."""
    cap = capture_table(table)
    keys = list(cap.state.rows.keys())
    names = list(cap.column_names)
    columns = {
        name: {key: cap.state.rows[key][i] for key in keys}
        for i, name in enumerate(names)
    }
    return keys, columns


class StreamGenerator:
    """Builds artificial streaming tables batch by batch (reference
    ``debug/__init__.py:496``).  Single-process: worker ids are accepted for
    API parity and ignored; batches become consecutive engine epochs."""

    def table_from_list_of_batches(self, batches, schema):
        """Each batch is a list of ``{column: value}`` rows; batch ``i``
        arrives at engine time ``2*(i+1)``."""
        cols = list(schema.column_names())
        rows = []
        for i, batch in enumerate(batches):
            t = 2 * (i + 1)
            for values in batch:
                rows.append(tuple(values[c] for c in cols) + (t, 1))
        return table_from_rows(schema, rows, is_stream=True)

    def table_from_list_of_batches_by_workers(self, batches, schema):
        """Each batch maps worker id → rows; workers are collapsed."""
        flat = [
            [values for rows in batch.values() for values in rows]
            for batch in batches
        ]
        return self.table_from_list_of_batches(flat, schema)

    def table_from_pandas(self, df, id_from=None, unsafe_trusted_ids=False,
                          schema=None):
        """Honors ``_time`` / ``_diff`` columns (``_worker`` ignored)."""
        df = df.copy()
        if "_time" not in df:
            df["_time"] = 2
        if "_diff" not in df:
            df["_diff"] = 1
        value_cols = [c for c in df.columns if c not in ("_time", "_diff", "_worker")]
        if schema is None:
            from pathway_tpu.internals.schema import schema_from_types

            schema = schema_from_types(
                **{c: _dtype_from_pandas(df[c]) for c in value_cols}
            )
        # per-column extraction: iterrows() would upcast mixed-dtype rows
        # (int columns silently becoming float64)
        col_values = {c: df[c].tolist() for c in value_cols}
        times = df["_time"].tolist()
        diffs = df["_diff"].tolist()
        rows = [
            tuple(col_values[c][i] for c in value_cols)
            + (int(times[i]), int(diffs[i]))
            for i in range(len(df))
        ]
        return table_from_rows(schema, rows, is_stream=True)


def _dtype_from_pandas(series) -> type:
    import pandas as pd

    if pd.api.types.is_integer_dtype(series):
        return int
    if pd.api.types.is_float_dtype(series):
        return float
    if pd.api.types.is_bool_dtype(series):
        return bool
    return str
