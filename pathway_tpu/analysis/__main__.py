"""``python -m pathway_tpu.analysis`` — the graft-lint CLI.

    python -m pathway_tpu.analysis check                 # text, exit 1 on findings
    python -m pathway_tpu.analysis check --format json   # machine output
    python -m pathway_tpu.analysis check --update-baseline
    python -m pathway_tpu.analysis check --rules GL201,GL401
    python -m pathway_tpu.analysis --list-rules          # README rule table

Exit status: 0 when every finding is baselined (or none), 1 otherwise —
``tests/test_static_analysis.py`` runs the same :func:`core.check` +
baseline split in-process, so CI and the CLI cannot disagree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from pathway_tpu.analysis import core


def _repo_root() -> str:
    # package lives at <root>/pathway_tpu/analysis/
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pathway_tpu.analysis",
        description="graft-lint: static checks for this repo's invariants",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table (markdown) and exit",
    )
    sub = parser.add_subparsers(dest="cmd")
    chk = sub.add_parser("check", help="run all passes over the repo")
    chk.add_argument("--root", default=None,
                     help="repo root (default: auto-detected)")
    chk.add_argument("--format", choices=("text", "json"), default="text")
    chk.add_argument("--baseline", default=None,
                     help="baseline file (default: analysis/baseline.json)")
    chk.add_argument("--update-baseline", action="store_true",
                     help="rewrite the baseline with current findings")
    chk.add_argument("--rules", default=None,
                     help="comma-separated rule ids to run (default: all)")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(core.render_rules_table())
        return 0
    if args.cmd != "check":
        parser.print_help()
        return 2

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(core.RULES)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    root = args.root or _repo_root()
    findings = core.check(root, rules)

    if args.update_baseline:
        path = core.save_baseline(findings, args.baseline)
        print(f"baseline updated: {path} ({len(findings)} finding(s))")
        return 0

    baseline = core.load_baseline(args.baseline)
    new, old = core.split_baselined(findings, baseline)

    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.to_dict() for f in new],
                "baselined": [f.to_dict() for f in old],
                "ok": not new,
            },
            indent=2,
        ))
    else:
        for f in new:
            print(f.render())
        if old:
            print(f"({len(old)} baselined finding(s) suppressed)")
        if new:
            print(f"{len(new)} finding(s).")
        else:
            print("clean.")
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
