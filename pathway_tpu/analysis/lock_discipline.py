"""GL4xx — lock-discipline pass.

Enforces the ``guarded_by`` declarations from
``pathway_tpu/analysis/annotations.py``:

* classes — ``@guarded_by(_counters="_lock")`` requires every
  ``self._counters`` access in the class body to sit lexically inside
  ``with self._lock:`` (**GL401**). ``__init__`` is exempt
  (construction precedes publication); a method decorated
  ``@assumes_held("_lock")`` is exempt for that lock's fields — the
  contract moves to its callers, which the pass still checks.
* modules — a top-level ``_GUARDED_BY = {"_ring": "_ring_lock"}`` dict
  declares module globals the same way; accesses inside functions must
  sit inside ``with _ring_lock:``; top-level statements (import-time
  construction) are exempt.
* **GL402** — a declaration naming a lock the class never assigns
  (``self.<lock> = ...`` nowhere) or the module never binds: the guard
  cannot exist, the declaration is a typo.

The check is lexical on purpose: aliasing the lock
(``c = self._cond; with c:``) defeats it and earns a finding — write
the ``with`` on the attribute, or pragma with a reason. The *dynamic*
complement (lock-order inversions, writes through setattr paths the
AST never sees) is ``analysis/runtime.py``'s job.
"""

from __future__ import annotations

import ast

from pathway_tpu.analysis.core import Finding, ModuleSource, PackageCtx


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _decorator_call(dec: ast.AST, suffix: str) -> ast.Call | None:
    if isinstance(dec, ast.Call):
        d = _dotted(dec.func)
        if d and (d == suffix or d.endswith("." + suffix)):
            return dec
    return None


def _guarded_decl(cls: ast.ClassDef) -> dict[str, str]:
    out: dict[str, str] = {}
    for dec in cls.decorator_list:
        call = _decorator_call(dec, "guarded_by")
        if call is None:
            continue
        for kw in call.keywords:
            if kw.arg and isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                out[kw.arg] = kw.value.value
    return out


def _assumes_held(fn: ast.FunctionDef) -> set[str]:
    held: set[str] = set()
    for dec in fn.decorator_list:
        call = _decorator_call(dec, "assumes_held")
        if call and call.args and isinstance(call.args[0], ast.Constant):
            held.add(str(call.args[0].value))
    return held


def _module_guarded(src: ModuleSource) -> tuple[dict[str, str], int]:
    """Top-level ``_GUARDED_BY = {...}`` declaration -> (mapping, line)."""
    for node in src.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "_GUARDED_BY"
            and isinstance(node.value, ast.Dict)
        ):
            out: dict[str, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    out[k.value] = v.value
            return out, node.lineno
    return {}, 0


def _visit_with_locks(
    node: ast.AST, active: frozenset, cb, _root: bool = True
) -> None:
    """Pre-order walk threading the set of lexically-held lock
    expressions (dotted strings) through ``with`` blocks. Does not
    descend into nested def/class bodies — those run later, when the
    lock is no longer held (each function is visited on its own)."""
    cb(node, active)
    if not _root and isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
    ):
        return
    if isinstance(node, ast.With):
        acquired = set()
        for item in node.items:
            d = _dotted(item.context_expr)
            if d:
                acquired.add(d)
        active = active | acquired
    for child in ast.iter_child_nodes(node):
        _visit_with_locks(child, active, cb, _root=False)


def _self_assigns(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Store)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            out.add(node.attr)
    return out


def run(ctx: PackageCtx) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.modules:
        _check_classes(findings, src)
        _check_module_globals(findings, src)
    return findings


def _check_classes(out: list[Finding], src: ModuleSource) -> None:
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _guarded_decl(cls)
        if not guarded:
            continue
        assigned = _self_assigns(cls)
        for lock_attr in sorted(set(guarded.values())):
            if lock_attr not in assigned:
                src.emit(
                    out, "GL402", cls,
                    f"guarded_by names lock `self.{lock_attr}` which "
                    f"`{cls.name}` never assigns",
                    cls.name,
                )
        # walk ALL function defs in the class — nested closures run
        # later, outside any lock their definition site held, and each
        # is visited as its own root with an empty held set
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue
            exempt = _assumes_held(fn)

            def cb(node, active, fn=fn, exempt=exempt):
                if not (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guarded
                ):
                    return
                lock_attr = guarded[node.attr]
                if lock_attr in exempt:
                    return
                if f"self.{lock_attr}" in active:
                    return
                src.emit(
                    out, "GL401", node,
                    f"`self.{node.attr}` accessed outside `with "
                    f"self.{lock_attr}:` in `{cls.name}.{fn.name}`",
                    f"{cls.name}.{fn.name}", fn.lineno,
                )

            _visit_with_locks(fn, frozenset(), cb)


def _check_module_globals(out: list[Finding], src: ModuleSource) -> None:
    guarded, decl_line = _module_guarded(src)
    if not guarded:
        return
    top_assigned = {
        t.id
        for node in src.tree.body
        if isinstance(node, ast.Assign)
        for t in node.targets
        if isinstance(t, ast.Name)
    }
    for lock_name in sorted(set(guarded.values())):
        if lock_name not in top_assigned:
            anchor = ast.Constant(value=lock_name)
            anchor.lineno = decl_line
            src.emit(
                out, "GL402", anchor,
                f"_GUARDED_BY names lock `{lock_name}` which {src.path} "
                "never binds at module level",
                lock_name,
            )
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        exempt = _assumes_held(fn) if isinstance(fn, ast.FunctionDef) else set()

        def cb(node, active, fn=fn, exempt=exempt):
            if not (
                isinstance(node, ast.Name)
                and node.id in guarded
                and isinstance(node.ctx, (ast.Load, ast.Store, ast.Del))
            ):
                return
            lock_name = guarded[node.id]
            if lock_name in exempt or lock_name in active:
                return
            src.emit(
                out, "GL401", node,
                f"module global `{node.id}` accessed outside `with "
                f"{lock_name}:` in `{fn.name}`",
                fn.name, fn.lineno,
            )

        _visit_with_locks(fn, frozenset(), cb)
