"""graft-lint: the repo's own invariants as machine-checkable passes.

Seven PRs accreted the conventions that keep this stack correct — every
knob declared once in ``FLAG_REGISTRY``, every perf feature's kill
switch pinned byte-identical by a test, no host-side effects inside
jitted hot paths, lock-guarded shared state in the threaded serving
components. This package turns each convention into an AST pass with a
stable rule id (``python -m pathway_tpu.analysis check``), plus a
runtime lock sanitizer (:mod:`pathway_tpu.analysis.runtime`,
``PATHWAY_TPU_LOCK_SANITIZER``) that records held-lock sets per thread
under the existing threaded tests and reports lock-order inversions and
unguarded guarded-field writes.

Import surface is deliberately lazy: ``annotations`` (the
``guarded_by`` / ``assumes_held`` decorators) and ``runtime``
(``make_lock``) are imported by hot modules at package import time, so
this ``__init__`` must never pull the AST passes in.
"""

from __future__ import annotations

__all__ = ["check", "analyze_source", "RULES", "Finding"]


def __getattr__(name):
    if name in __all__:
        from pathway_tpu.analysis import core

        return getattr(core, name)
    raise AttributeError(name)
