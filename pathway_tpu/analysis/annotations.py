"""Lock-discipline annotations (clang ``GUARDED_BY`` for this repo).

:func:`guarded_by` declares, on a class, which attributes are protected
by which lock attribute::

    @guarded_by(_counters="_lock", _gauges="_lock")
    class MetricsRegistry: ...

The declaration is enforced twice:

* **statically** — the ``lock-unguarded-access`` pass
  (``pathway_tpu/analysis/lock_discipline.py``) verifies every
  ``self.<field>`` access in the class body sits lexically inside a
  ``with self.<lock>:`` block (``__init__`` is exempt — construction
  precedes publication; a helper the caller must hold the lock for is
  marked :func:`assumes_held`);
* **at runtime** — ``analysis/runtime.py``'s sanitizer, when enabled,
  patches ``__setattr__`` on every registered class and reports writes
  to a guarded field while the declared lock is not held by the writing
  thread.

Module-level globals use the same convention without a decorator: a
module dict ``_GUARDED_BY = {"_ring": "_ring_lock"}`` declares its own
globals, and the static pass checks ``Name`` accesses the same way.

The decorators are metadata-only at runtime (no wrapping, no slots
interference): zero cost on instances unless the sanitizer is enabled.
"""

from __future__ import annotations

# classes carrying a __graft_guarded_by__ declaration, in registration
# order — the runtime sanitizer walks this to install its write checks
GUARDED_CLASSES: list[type] = []


def guarded_by(**fields: str):
    """Class decorator: ``field_name="lock_attr"`` pairs declaring which
    instance attributes must only be touched under which lock."""

    def deco(cls: type) -> type:
        merged = dict(getattr(cls, "__graft_guarded_by__", {}))
        merged.update(fields)
        cls.__graft_guarded_by__ = merged
        GUARDED_CLASSES.append(cls)
        return cls

    return deco


def assumes_held(lock: str):
    """Method decorator: the CALLER must already hold ``self.<lock>``.

    Exempts the method from the static with-block requirement (and
    documents the contract where it is easiest to miss)."""

    def deco(fn):
        held = set(getattr(fn, "__graft_assumes_held__", ()))
        held.add(lock)
        fn.__graft_assumes_held__ = frozenset(held)
        return fn

    return deco
