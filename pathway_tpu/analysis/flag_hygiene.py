"""GL2xx — flag-hygiene pass.

The repo's contract since PR 2: every environment knob is declared once
in ``internals/config.py``'s ``FLAG_REGISTRY`` and read through
``pathway_config``. This pass makes the contract total:

* **GL201** — a *literal* ``PATHWAY*`` env read anywhere outside
  ``internals/config.py`` (``os.environ["PATHWAY_TPU_X"]``,
  ``os.environ.get(...)``, ``os.getenv(...)``, including
  ``from os import environ`` aliases) is an error: the knob bypasses
  registration, typing, clamping, and the README tables.
* **GL202** — any *other* ``os.environ`` / ``os.getenv`` use outside
  ``internals/config.py`` (dynamic keys, ``in os.environ`` membership,
  whole-environment copies for subprocesses). These go through the
  audited choke points ``config.env_interpolate`` /
  ``config.environ_snapshot`` instead, so "who reads the environment"
  stays a one-file question.
* **GL203** — a ``FLAG_REGISTRY`` entry nobody reads: its ``attr`` is
  never accessed in the package (outside config.py) and its env name
  never appears in package/bench/tests sources. Dead flags are lies in
  the docs; delete them or wire them up.
* **GL204** — a flag carrying a ``tunable`` search spec whose space is
  broken: missing/non-finite bounds, an inverted range, a non-positive
  step, an empty or single-rung candidate ladder, or a default outside
  the declared space. The autotuner trusts these specs; a malformed one
  would search garbage (or nothing).

GL203/GL204 are registry-wide, so they only fire on full-package runs
(they need ``internals/config.py`` in the scanned set); unit tests
exercise :func:`check_dead_flags` / :func:`check_tunable_bounds`
directly with synthetic registries.
"""

from __future__ import annotations

import ast
import os
import re

from pathway_tpu.analysis.core import Finding, ModuleSource, PackageCtx

CONFIG_PATH = "pathway_tpu/internals/config.py"


def _env_aliases(src: ModuleSource) -> tuple[set[str], set[str], set[str]]:
    """(os-module aliases, `environ` aliases, `getenv` aliases)."""
    os_names: set[str] = set()
    environ_names: set[str] = set()
    getenv_names: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os":
                    os_names.add(a.asname or "os")
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            for a in node.names:
                if a.name == "environ":
                    environ_names.add(a.asname or "environ")
                elif a.name == "getenv":
                    getenv_names.add(a.asname or "getenv")
    return os_names, environ_names, getenv_names


def _literal_pathway_key(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith("PATHWAY"):
            return node.value
    return None


def run(ctx: PackageCtx) -> list[Finding]:
    findings: list[Finding] = []
    for src in ctx.modules:
        if src.path == CONFIG_PATH:
            continue
        _check_module(findings, src)

    config = ctx.module(CONFIG_PATH)
    if config is not None and ctx.registry_checks:
        findings.extend(_dead_flags_on_repo(ctx, config))
        findings.extend(_tunable_bounds_on_repo(config))
    return findings


def _check_module(out: list[Finding], src: ModuleSource) -> None:
    os_names, environ_names, getenv_names = _env_aliases(src)
    if not (os_names or environ_names or getenv_names):
        return

    def is_environ(node: ast.AST) -> bool:
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_names
        ):
            return True
        return isinstance(node, ast.Name) and node.id in environ_names

    def is_getenv(node: ast.AST) -> bool:
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "getenv"
            and isinstance(node.value, ast.Name)
            and node.value.id in os_names
        ):
            return True
        return isinstance(node, ast.Name) and node.id in getenv_names

    flagged: set[int] = set()  # id() of environ nodes already reported

    def emit(rule: str, node: ast.AST, detail: str) -> None:
        src.emit(out, rule, node, detail)

    for node in ast.walk(src.tree):
        # os.environ[KEY] / environ.get(KEY) / os.getenv(KEY)
        if isinstance(node, ast.Subscript) and is_environ(node.value):
            flagged.add(id(node.value))
            key = _literal_pathway_key(node.slice)
            if key:
                emit("GL201", node,
                     f"literal env read `{key}` outside internals/config.py "
                     "— declare it in FLAG_REGISTRY and read "
                     "`pathway_config`")
            else:
                emit("GL202", node,
                     "dynamic `os.environ[...]` outside internals/config.py "
                     "— use `config.env_interpolate`")
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("get", "setdefault", "pop")
                and is_environ(f.value)
            ):
                flagged.add(id(f.value))
                key = node.args and _literal_pathway_key(node.args[0]) or None
                if key:
                    emit("GL201", node,
                         f"literal env read `{key}` outside "
                         "internals/config.py — declare it in FLAG_REGISTRY "
                         "and read `pathway_config`")
                else:
                    emit("GL202", node,
                         f"`os.environ.{f.attr}(...)` outside "
                         "internals/config.py — use `config.env_interpolate`")
            elif is_getenv(f):
                key = node.args and _literal_pathway_key(node.args[0]) or None
                if key:
                    emit("GL201", node,
                         f"literal env read `{key}` outside "
                         "internals/config.py — declare it in FLAG_REGISTRY "
                         "and read `pathway_config`")
                else:
                    emit("GL202", node,
                         "`os.getenv(...)` outside internals/config.py — "
                         "use `config.env_interpolate`")

    # bare os.environ touches not covered above (copies, membership,
    # iteration, passing the mapping around)
    for node in ast.walk(src.tree):
        if is_environ(node) and id(node) not in flagged:
            # skip the inner `os.environ` of already-flagged parents:
            # only Attribute/Name nodes reach here
            emit("GL202", node,
                 "`os.environ` used outside internals/config.py — use "
                 "`config.environ_snapshot` / `config.env_interpolate`")


# --------------------------------------------------------------------- #
# GL203 dead flags


def check_dead_flags(flags, texts) -> list[tuple[str, str | None]]:
    """Registry entries with no reader. ``flags`` is an iterable with
    ``.env`` / ``.attr``; ``texts`` is ``[(path, source_text), ...]`` of
    everything that may legitimately read a flag (package minus
    config.py, bench.py, tests/). Returns ``[(env, attr), ...]`` dead."""
    dead: list[tuple[str, str | None]] = []
    for flag in flags:
        attr_re = (
            re.compile(r"\." + re.escape(flag.attr) + r"\b")
            if getattr(flag, "attr", None)
            else None
        )
        live = False
        for _path, text in texts:
            if flag.env in text:
                live = True
                break
            if attr_re is not None and attr_re.search(text):
                live = True
                break
        if not live:
            dead.append((flag.env, getattr(flag, "attr", None)))
    return dead


def _registry_line(config: ModuleSource, env: str) -> int:
    needle = f'"{env}"'
    for i, line in enumerate(config.lines, start=1):
        if needle in line:
            return i
    return 1


def _dead_flags_on_repo(
    ctx: PackageCtx, config: ModuleSource
) -> list[Finding]:
    from pathway_tpu.internals.config import FLAG_REGISTRY

    texts: list[tuple[str, str]] = [
        (m.path, m.text) for m in ctx.modules if m.path != CONFIG_PATH
    ]
    for extra in ("bench.py",):
        full = os.path.join(ctx.repo_root, extra)
        if os.path.exists(full):
            with open(full, encoding="utf-8") as f:
                texts.append((extra, f.read()))
    tests_dir = os.path.join(ctx.repo_root, "tests")
    if os.path.isdir(tests_dir):
        for fn in sorted(os.listdir(tests_dir)):
            if fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn), encoding="utf-8") as f:
                    texts.append((f"tests/{fn}", f.read()))

    findings: list[Finding] = []
    for env, attr in check_dead_flags(FLAG_REGISTRY, texts):
        line = _registry_line(config, env)
        node = ast.Constant(value=env)
        node.lineno = line
        config.emit(
            findings, "GL203", node,
            f"flag `{env}` (attr `{attr}`) is never read by package, bench, "
            "or tests — delete it or wire it up",
            env,
        )
    return findings


# --------------------------------------------------------------------- #
# GL204 tunable bounds


def check_tunable_bounds(flags) -> list[tuple[str, str]]:
    """Malformed ``Tunable`` search specs. ``flags`` is an iterable with
    ``.env`` / ``.tunable`` (``None`` = not tunable) where a spec has
    ``.kind`` / ``.lo`` / ``.hi`` / ``.step`` / ``.log`` / ``.choices``
    / ``.candidates()``, and the flag parses raw values via
    ``.parse_raw`` and renders its default via ``.render_default``.
    Returns ``[(env, problem), ...]``."""
    import math

    bad: list[tuple[str, str]] = []
    for flag in flags:
        spec = getattr(flag, "tunable", None)
        if spec is None:
            continue
        env = flag.env

        def problem(msg: str, env=env) -> None:
            bad.append((env, msg))

        if spec.kind == "choice":
            if len(spec.choices) < 2:
                problem("choice spec needs >= 2 choices")
                continue
        elif spec.kind in ("int", "float"):
            if spec.lo is None or spec.hi is None:
                problem(f"{spec.kind} spec must declare lo and hi")
                continue
            lo, hi = float(spec.lo), float(spec.hi)
            if not (math.isfinite(lo) and math.isfinite(hi)):
                problem("bounds must be finite")
                continue
            if lo >= hi:
                problem(f"inverted/empty range [{lo}, {hi}]")
                continue
            if spec.log:
                if lo <= 0:
                    problem("log ladder needs lo > 0")
                    continue
            elif spec.step is not None and float(spec.step) <= 0:
                problem(f"non-positive step {spec.step}")
                continue
        else:
            problem(f"unknown tunable kind {spec.kind!r}")
            continue

        try:
            cands = spec.candidates()
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            problem(f"candidates() raised {type(exc).__name__}: {exc}")
            continue
        if len(cands) < 2:
            problem(f"degenerate candidate ladder ({len(cands)} rung)")
            continue
        # every rung must round-trip through the flag's own parser
        try:
            parsed = [flag.parse_raw(c) for c in cands]
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            problem(f"candidate fails flag parser: {exc}")
            continue
        # the default must live inside the declared space (compare in
        # parsed units: choice "0" on a float flag means 0.0)
        default = flag.parse_raw(flag.render_default())
        if spec.kind == "choice":
            if default not in parsed:
                problem(
                    f"default {default!r} is not one of the choices"
                )
        else:
            lo, hi = float(spec.lo), float(spec.hi)
            try:
                dv = float(default)
            except (TypeError, ValueError):
                problem(
                    f"non-numeric default {default!r} on a {spec.kind} range"
                )
                continue
            if not (lo <= dv <= hi):
                problem(f"default {dv} outside [{lo}, {hi}]")
    return bad


def _tunable_bounds_on_repo(config: ModuleSource) -> list[Finding]:
    from pathway_tpu.internals.config import FLAG_REGISTRY

    findings: list[Finding] = []
    for env, msg in check_tunable_bounds(FLAG_REGISTRY):
        node = ast.Constant(value=env)
        node.lineno = _registry_line(config, env)
        config.emit(
            findings, "GL204", node,
            f"flag `{env}` has a malformed tunable spec: {msg}",
            env,
        )
    return findings
