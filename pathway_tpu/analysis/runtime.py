"""Runtime lock sanitizer (``PATHWAY_TPU_LOCK_SANITIZER``).

The static lock pass proves lexical discipline; this module catches what
lexical analysis cannot — the *dynamic* ordering of lock acquisitions
across threads, and writes that reach a guarded field through code paths
the AST pass does not see (setattr, exec'd helpers, subclasses).

Design constraints, in order:

1. **Compiled out when off.** :func:`make_lock` reads the flag once at
   lock construction and returns a plain ``threading.Lock`` / ``RLock``
   when the sanitizer is disabled — the serving hot paths pay zero
   wrapper cost by default (``tests/test_perf_guard.py`` pins the <=3%
   budget for the ON arm, mirroring the metrics guard).
2. **Observe, never interfere.** A sanitized lock blocks exactly like
   the lock it wraps; reports land in a bounded in-process list
   (:func:`reports`), they never raise into the instrumented thread.
3. **Condition-compatible.** ``threading.Condition`` probes its lock for
   ``_release_save`` / ``_acquire_restore`` / ``_is_owned``;
   :class:`SanitizedLock` implements all three with held-set
   bookkeeping, so ``Condition(make_lock(...))`` traces ``wait()``'s
   release/reacquire correctly.

What it detects:

* **lock-order inversion** — a global order graph keyed by lock *name*
  (one name per lock role, e.g. ``decode_server.lock``); acquiring B
  while holding A records the edge A->B, and a thread later acquiring A
  while holding B reports ``order-inversion`` (the classic potential
  deadlock, caught even when the timing never actually deadlocks).
* **unguarded guarded-field write** — :func:`enable` patches
  ``__setattr__`` on every ``@guarded_by`` class
  (``analysis/annotations.py``): assigning a guarded field while the
  declared lock is not held by the writing thread reports
  ``unguarded-write``. Reads and in-place container mutation are the
  static pass's job. The FIRST assignment of a field is initialization
  (construction precedes publication) and exempt; so are instances
  whose lock is a plain stdlib lock (sanitizer-off construction).
"""

from __future__ import annotations

import threading

from pathway_tpu.analysis.annotations import GUARDED_CLASSES

# plain stdlib lock: the sanitizer's own state must never be sanitized
_state_lock = threading.Lock()
_MAX_REPORTS = 1000
_reports: list[dict] = []
# directed acquisition-order edges between lock NAMES:
# (held_name, acquired_name) -> (thread_name, stack-free evidence str)
_order_edges: dict[tuple[str, str], str] = {}
_tls = threading.local()


def enabled() -> bool:
    from pathway_tpu.internals.config import pathway_config

    return bool(pathway_config.lock_sanitizer)


def make_lock(name: str, *, rlock: bool = False):
    """THE lock constructor for the threaded components. Plain
    ``threading.Lock()`` / ``RLock()`` when the sanitizer flag is off
    (read once, at construction); a :class:`SanitizedLock` wrapping the
    same when on. ``name`` identifies the lock's role (not instance) in
    the order graph — e.g. every decode server's admission lock shares
    ``decode_server.lock``."""
    inner = threading.RLock() if rlock else threading.Lock()
    if not enabled():
        return inner
    return SanitizedLock(name, inner)


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def report(kind: str, **detail) -> None:
    """Append one sanitizer finding (bounded; never raises)."""
    with _state_lock:
        if len(_reports) < _MAX_REPORTS:
            _reports.append(
                {"kind": kind, "thread": threading.current_thread().name,
                 **detail}
            )


def reports(kind: str | None = None) -> list[dict]:
    with _state_lock:
        out = list(_reports)
    if kind is not None:
        out = [r for r in out if r["kind"] == kind]
    return out


def reset() -> None:
    """Clear reports AND the accumulated order graph (tests isolate
    scenarios with this)."""
    with _state_lock:
        _reports.clear()
        _order_edges.clear()


class SanitizedLock:
    """Lock wrapper recording per-thread held sets and acquisition-order
    edges. Delegates blocking semantics to the wrapped lock."""

    __slots__ = ("name", "_inner", "_owner", "_count")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner
        self._owner: int | None = None  # thread ident; None = unheld
        self._count = 0  # re-entrant depth (RLock inner)

    # ------------------------------------------------------- bookkeeping
    def _check_order(self) -> None:
        me = threading.current_thread().name
        for held in _held():
            if held is self:
                return  # re-entrant acquire: no new edge
            edge = (held.name, self.name)
            rev = (self.name, held.name)
            with _state_lock:
                first = _order_edges.setdefault(edge, me)
                rev_holder = _order_edges.get(rev)
            if rev_holder is not None and held.name != self.name:
                report(
                    "order-inversion",
                    first=held.name, second=self.name,
                    reverse_seen_in=rev_holder,
                )

    def _note_acquire(self) -> None:
        ident = threading.get_ident()
        if self._owner == ident:
            self._count += 1
        else:
            self._owner = ident
            self._count = 1
        _held().append(self)

    def _note_release(self) -> None:
        stack = _held()
        if self in stack:
            # remove the innermost occurrence (re-entrant stacks)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is self:
                    del stack[i]
                    break
        if self._owner == threading.get_ident():
            self._count -= 1
            if self._count <= 0:
                self._owner = None
                self._count = 0

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    # ---------------------------------------------------- lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquire()
        return got

    def release(self) -> None:
        self._note_release()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # ------------------------------------- threading.Condition protocol
    def _release_save(self):
        self._note_release()
        if hasattr(self._inner, "_release_save"):
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        self._check_order()
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._note_acquire()

    def _is_owned(self) -> bool:
        return self.held_by_current_thread()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} wrapping {self._inner!r}>"


def _resolve_lock(obj, lock_attr: str):
    """The lock object guarding ``obj``'s fields: the attribute itself,
    or — when the attribute is a ``Condition`` — its underlying lock."""
    lock = getattr(obj, lock_attr, None)
    inner = getattr(lock, "_lock", None)  # threading.Condition wraps
    if inner is not None and not isinstance(lock, SanitizedLock):
        return inner
    return lock


_patched: dict[type, object] = {}


def enable() -> None:
    """Install the guarded-field write check on every ``@guarded_by``
    class registered so far. Idempotent; :func:`disable` undoes it.
    Locks must additionally be built through :func:`make_lock` with the
    flag on for held-set tracking to exist."""
    for cls in GUARDED_CLASSES:
        if cls in _patched:
            continue
        guarded = cls.__graft_guarded_by__
        orig = cls.__setattr__

        def checked_setattr(self, attr, value, _g=guarded, _orig=orig):
            lock_attr = _g.get(attr)
            # first assignment of a field is initialization (typically
            # __init__, possibly after the lock attribute already
            # exists) — only RE-assignment of a published field must
            # hold the lock
            if lock_attr is not None and attr in getattr(self, "__dict__", ()):
                lock = _resolve_lock(self, lock_attr)
                # a missing or un-sanitized lock means construction (or
                # a sanitizer-off instance) — only live SanitizedLocks
                # can prove "not held"
                if (
                    isinstance(lock, SanitizedLock)
                    and not lock.held_by_current_thread()
                ):
                    report(
                        "unguarded-write",
                        cls=type(self).__name__, field=attr,
                        lock=lock.name,
                    )
            _orig(self, attr, value)

        cls.__setattr__ = checked_setattr
        _patched[cls] = orig


def disable() -> None:
    """Remove the write checks installed by :func:`enable`."""
    for cls, orig in _patched.items():
        cls.__setattr__ = orig
    _patched.clear()
