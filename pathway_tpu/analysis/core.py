"""graft-lint core: findings, rule registry, pragmas, baseline.

Stdlib-only on purpose (``ast`` + ``hashlib``): the analyzer must run in
any environment the package imports in, including CI images without an
accelerator, and must stay fast enough to live in tier-1
(``tests/test_static_analysis.py`` runs :func:`check` over the whole
package in-process).

Vocabulary:

* a **rule** is one enforced invariant with a stable id (``GL1xx``
  jit-purity, ``GL2xx`` flag hygiene, ``GL3xx`` kill-switch coverage,
  ``GL4xx`` lock discipline);
* a **finding** is one violation at a (file, line); its
  :attr:`Finding.fingerprint` hashes rule + file + symbol + message but
  NOT the line number, so baselines survive unrelated edits;
* a **pragma** — ``# graft-lint: allow[rule-id] <reason>`` on the
  offending line or on the enclosing ``def``/``class`` line —
  suppresses a finding in place, for the rare access that is correct
  for reasons the AST cannot see (the suppression is visible in the
  diff, unlike a baseline entry);
* the **baseline** (``pathway_tpu/analysis/baseline.json``) grandfathers
  findings by fingerprint; ``check`` fails only on non-baselined
  findings and ``--update-baseline`` rewrites it. The repo's checked-in
  baseline is EMPTY — every real finding the four passes surfaced was
  fixed, not grandfathered.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re

# --------------------------------------------------------------------- #
# rules


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str


RULES: dict[str, Rule] = {
    r.id: r
    for r in [
        Rule(
            "GL101", "jit-host-effect",
            "Host-side effect (print, `time.*`, `os.environ`, config "
            "read, probes/registry call) inside a function reachable "
            "from a `jax.jit` boundary — executes at trace time, "
            "silently frozen or repeated per retrace.",
        ),
        Rule(
            "GL102", "jit-numpy-traced",
            "`np.*` call on a traced function parameter inside a "
            "jit-reachable function — forces a host sync or fails under "
            "tracing; parameters named in `static_argnames` are exempt.",
        ),
        Rule(
            "GL103", "jit-mutable-capture",
            "Jit-reachable function closes over a module-level mutable "
            "that the module also mutates — the traced value is frozen "
            "at first trace, later mutation is silently ignored (or "
            "forces a retrace when used as a shape).",
        ),
        Rule(
            "GL201", "flag-env-literal",
            "Literal `PATHWAY*` environment read outside "
            "`internals/config.py` — every knob is declared once in "
            "`FLAG_REGISTRY`; read it through `pathway_config`.",
        ),
        Rule(
            "GL202", "flag-env-indirect",
            "Dynamic-key `os.environ` read outside "
            "`internals/config.py` — route through the choke points in "
            "`internals/config.py` (`env_interpolate`, "
            "`environ_snapshot`) so flag reads stay auditable.",
        ),
        Rule(
            "GL203", "flag-dead",
            "`FLAG_REGISTRY` entry read nowhere (attr never accessed, "
            "env never referenced by package/bench/tests) — delete the "
            "flag or wire it up.",
        ),
        Rule(
            "GL204", "tunable-bounds",
            "Registry flag with a `tunable` search spec whose bounds are "
            "missing/non-finite, whose candidate ladder is empty or "
            "degenerate, or whose default falls outside the declared "
            "range — the autotuner would search a broken space.",
        ),
        Rule(
            "GL301", "kill-switch-unpinned",
            "Registry flag marked `kill_switch=True` without a live "
            "byte-equality pinning test: `pinned_by` must name an "
            "existing test file that references the env var.",
        ),
        Rule(
            "GL302", "kill-switch-pin-prose-only",
            "Kill switch's `pinned_by` test mentions the env var only in "
            "docstrings/comments — the test must use the literal in code "
            "(a setenv argument, parametrize entry, env dict key), or "
            "the pin is prose, not a test.",
        ),
        Rule(
            "GL401", "lock-unguarded-access",
            "Access to a `guarded_by`-declared field outside a `with "
            "<lock>:` block (and not in `__init__` or an "
            "`@assumes_held` method).",
        ),
        Rule(
            "GL402", "lock-undeclared",
            "`guarded_by` declaration names a lock attribute the class "
            "(or module) never assigns — the guard cannot exist.",
        ),
    ]
}


# --------------------------------------------------------------------- #
# findings


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    symbol: str = ""  # function / class / flag the finding anchors to

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.symbol}|{self.message}".encode()
        ).hexdigest()
        return h[:12]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "name": RULES[self.rule].name,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (
            f"{self.path}:{self.line}: {self.rule} "
            f"({RULES[self.rule].name}){sym} {self.message}"
        )


# --------------------------------------------------------------------- #
# sources + pragmas

_PRAGMA_RE = re.compile(r"graft-lint:\s*allow\[([A-Za-z0-9_*,\- ]+)\]")


class ModuleSource:
    """One parsed package module: AST + per-line pragma index."""

    def __init__(self, path: str, text: str):
        self.path = path  # repo-relative
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.lines = text.splitlines()
        self.allow: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                # accept rule ids and rule names alike
                names = {r.name: r.id for r in RULES.values()}
                self.allow[i] = {names.get(s, s) for s in ids}

    def allowed(self, rule: str, *linenos: int) -> bool:
        for ln in linenos:
            ids = self.allow.get(ln)
            if ids and (rule in ids or "*" in ids):
                return True
        return False

    def emit(
        self,
        out: list[Finding],
        rule: str,
        node: ast.AST,
        message: str,
        symbol: str = "",
        scope_line: int | None = None,
    ) -> None:
        """Append a finding unless a pragma on the node's line (or its
        enclosing definition's line) allows the rule."""
        line = getattr(node, "lineno", 0)
        scopes = (line,) if scope_line is None else (line, scope_line)
        if not self.allowed(rule, *scopes):
            out.append(Finding(rule, self.path, line, message, symbol))


@dataclasses.dataclass
class PackageCtx:
    """Everything a pass may look at: the parsed package, plus the repo
    root for cross-referencing bench.py and tests/."""

    repo_root: str
    modules: list[ModuleSource]
    # False on single-snippet runs (analyze_source): the registry-wide
    # checks (GL203 dead flags, GL301 kill switches) compare the LIVE
    # FLAG_REGISTRY against the scanned sources, which is meaningless
    # when the "package" is one synthetic module
    registry_checks: bool = True

    def module(self, relpath: str) -> ModuleSource | None:
        for m in self.modules:
            if m.path == relpath:
                return m
        return None


def collect_package(repo_root: str, package: str = "pathway_tpu") -> PackageCtx:
    modules: list[ModuleSource] = []
    pkg_root = os.path.join(repo_root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = sorted(
            d for d in dirnames if d != "__pycache__"
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, repo_root).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                modules.append(ModuleSource(rel, f.read()))
    return PackageCtx(repo_root=repo_root, modules=modules)


# --------------------------------------------------------------------- #
# running


def _passes():
    from pathway_tpu.analysis import (
        flag_hygiene,
        jit_purity,
        kill_switch,
        lock_discipline,
    )

    return {
        "GL1": jit_purity.run,
        "GL2": flag_hygiene.run,
        "GL3": kill_switch.run,
        "GL4": lock_discipline.run,
    }


def check(repo_root: str, rules: set[str] | None = None) -> list[Finding]:
    """Run every pass (or the ones owning ids in ``rules``) over the
    package at ``repo_root``; findings sorted by (path, line, rule)."""
    ctx = collect_package(repo_root)
    findings: list[Finding] = []
    for prefix, run in _passes().items():
        if rules is not None and not any(r.startswith(prefix) for r in rules):
            continue
        findings.extend(run(ctx))
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def analyze_source(
    src: str, path: str = "pathway_tpu/_synthetic.py",
    rules: set[str] | None = None, repo_root: str | None = None,
) -> list[Finding]:
    """Run the AST passes over one synthetic module — the unit-test
    entry point (``tests/test_static_analysis.py`` feeds each rule a
    good and a bad snippet through this)."""
    ctx = PackageCtx(
        repo_root=repo_root or os.getcwd(),
        modules=[ModuleSource(path, src)],
        registry_checks=False,
    )
    findings: list[Finding] = []
    for prefix, run in _passes().items():
        if prefix == "GL3" and repo_root is None:
            continue  # registry-wide pass is meaningless on one snippet
        if rules is not None and not any(r.startswith(prefix) for r in rules):
            continue
        findings.extend(run(ctx))
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# --------------------------------------------------------------------- #
# baseline

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(__file__), "baseline.json"
)


def load_baseline(path: str | None = None) -> set[str]:
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    return {e["fingerprint"] for e in entries}


def save_baseline(findings: list[Finding], path: str | None = None) -> str:
    path = path or DEFAULT_BASELINE
    entries = [f.to_dict() for f in findings]
    for e in entries:
        e.pop("line", None)  # lines churn; fingerprints don't
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def split_baselined(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], list[Finding]]:
    """(new, grandfathered) partition of ``findings``."""
    new = [f for f in findings if f.fingerprint not in baseline]
    old = [f for f in findings if f.fingerprint in baseline]
    return new, old


# --------------------------------------------------------------------- #
# docs


def render_rules_table() -> str:
    """The README rule table (pinned by ``tests/test_static_analysis.py``
    the same way the flag tables are pinned)."""
    lines = [
        "| Rule | Name | Enforces |",
        "|---|---|---|",
    ]
    for r in RULES.values():
        lines.append(f"| `{r.id}` | `{r.name}` | {r.summary} |")
    return "\n".join(lines)
