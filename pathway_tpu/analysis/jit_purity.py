"""GL1xx — jit-purity pass.

Finds every function reachable from a ``jax.jit`` boundary — decorator
forms (``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``) and call
forms (``jax.jit(f)``, ``jax.jit(partial(mod.f, ...))``) — plus
``pl.pallas_call(kernel, ...)`` boundaries (a Pallas kernel body is
traced exactly like a jitted function, so host effects inside it are
the same bug), ``pl.BlockSpec(shape, index_map)`` index-map functions
(an index map runs at trace/grid-resolution time inside the Pallas
machinery — the flash/paged kernels name theirs as top-level functions
precisely so this pass can see them) and ``shard_map`` /
``compat_shard_map`` boundaries (the serving mesh's paged-attention
seam: the mapped function traces under the SPMD per-shard view) —
then walks the call graph across modules
(import-alias resolution, absolute and relative) and flags, inside the
reachable set:

* **GL101** host-side effects: ``print``, ``time.*``, ``os.environ`` /
  ``os.getenv``, ``pathway_config.*`` reads, and calls into the
  observability modules (``engine.probes`` / ``engine.tracing`` /
  ``analysis.runtime``). All of these run at *trace* time, not run
  time: the value is frozen into the jaxpr, or the side effect fires
  once per retrace instead of once per call.
* **GL102** ``np.*`` calls on a traced parameter of the jit entry
  function itself (parameters named in ``static_argnames`` are
  concrete and exempt). NumPy on a tracer either fails or forces a
  host round-trip.
* **GL103** closure capture of a module-level mutable that the module
  also mutates — the traced snapshot silently diverges from the live
  object.

Reachability is intraprocedural-per-function / interprocedural-by-name:
top-level functions only, resolved through ``import x as y`` and
``from x import f as g``. Method calls and dynamic dispatch are out of
scope — the repo's jitted kernels are top-level functions by
convention, which this pass now enforces de facto.
"""

from __future__ import annotations

import ast

from pathway_tpu.analysis.core import Finding, ModuleSource, PackageCtx

_TIME_FNS = {
    "time", "perf_counter", "perf_counter_ns", "monotonic", "sleep",
    "process_time", "time_ns", "monotonic_ns",
}
_NUMPY_MODULES = {"numpy"}
_EFFECT_MODULES = (
    "pathway_tpu.engine.probes",
    "pathway_tpu.engine.tracing",
    "pathway_tpu.analysis.runtime",
)
_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
}


def _module_name(path: str) -> str:
    # "pathway_tpu/ops/knn.py" -> "pathway_tpu.ops.knn";
    # ".../__init__.py" -> package name
    mod = path[:-3] if path.endswith(".py") else path
    parts = mod.split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _Imports:
    """Per-module import resolution: local alias -> what it names."""

    def __init__(self, src: ModuleSource):
        self.mod_alias: dict[str, str] = {}  # name -> imported module
        self.from_names: dict[str, tuple[str, str]] = {}  # name -> (mod, orig)
        pkg_parts = _module_name(src.path).split(".")
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.mod_alias[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative: drop the module's own name + (level-1) more
                    anchor = pkg_parts[: len(pkg_parts) - node.level]
                    base = ".".join(anchor + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    self.from_names[local] = (base, a.name)
                    # `from pkg import submodule` also binds a module
                    self.mod_alias.setdefault(local, f"{base}.{a.name}")

    def module_of(self, name: str) -> str | None:
        if name in self.from_names:
            return self.from_names[name][0]
        return self.mod_alias.get(name)


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST, imps: _Imports) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    if tail == "jit" and imps.mod_alias.get(head) == "jax":
        return True
    if not tail and imps.from_names.get(head) == ("jax", "jit"):
        return True
    return False


def _is_pallas_call(node: ast.AST, imps: _Imports) -> bool:
    """``pl.pallas_call`` / ``pallas.pallas_call`` / a bare
    ``pallas_call`` from-import — the kernel argument is a trace
    boundary exactly like ``jax.jit``'s."""
    d = _dotted(node)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    if tail == "pallas_call" and (
        imps.mod_alias.get(head) in ("jax.experimental.pallas",
                                     "jax.experimental.pallas.tpu")
    ):
        return True
    if not tail and imps.from_names.get(head, ("", ""))[1] == "pallas_call":
        return True
    return False


def _is_block_spec(node: ast.AST, imps: _Imports) -> bool:
    """``pl.BlockSpec`` / ``pallas.BlockSpec`` / a bare ``BlockSpec``
    from-import — its index-map argument runs under Pallas tracing, so
    it is a GL1xx root exactly like a kernel body."""
    d = _dotted(node)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    if tail == "BlockSpec" and (
        imps.mod_alias.get(head) in ("jax.experimental.pallas",
                                     "jax.experimental.pallas.tpu")
    ):
        return True
    if not tail and imps.from_names.get(head, ("", ""))[1] == "BlockSpec":
        return True
    return False


def _block_spec_index_map(call: ast.Call) -> ast.AST | None:
    """The index-map operand of a BlockSpec call: 2nd positional arg or
    the ``index_map=`` keyword."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "index_map":
            return kw.value
    return None


def _is_shard_map(node: ast.AST, imps: _Imports) -> bool:
    """``jax.shard_map`` / ``jax.experimental.shard_map.shard_map`` /
    the repo's ``compat_shard_map`` version shim (any from-import
    alias) — the mapped function is a trace boundary exactly like
    ``jax.jit``'s argument, and it additionally runs under the SPMD
    per-shard view, so the GL1xx purity rules apply to its body (the
    serving mesh routes paged attention through this seam)."""
    d = _dotted(node)
    if d is None:
        return False
    head, _, tail = d.partition(".")
    if tail == "shard_map" and imps.mod_alias.get(head) in (
        "jax", "jax.experimental.shard_map"
    ):
        return True
    if tail == "compat_shard_map" and imps.module_of(head):
        return True
    if not tail:
        orig = imps.from_names.get(head, ("", ""))[1]
        return orig in ("shard_map", "compat_shard_map")
    return False


def _is_partial(node: ast.AST, imps: _Imports) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    if d == "partial" and imps.from_names.get("partial", ("", ""))[1] == "partial":
        return True
    head, _, tail = d.partition(".")
    return tail == "partial" and imps.mod_alias.get(head) == "functools"


def _static_argnames(call: ast.Call | None) -> set[str]:
    names: set[str] = set()
    if call is None:
        return names
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    names.add(sub.value)
    return names


class _FuncRef:
    __slots__ = ("src", "node", "entry", "static")

    def __init__(self, src: ModuleSource, node: ast.FunctionDef):
        self.src = src
        self.node = node
        self.entry = False  # directly wrapped by jax.jit
        self.static: set[str] = set()  # static_argnames at the boundary


def _target_of_jit_arg(
    arg: ast.AST, imps: _Imports, defs: dict[str, ast.FunctionDef],
) -> tuple[str | None, str | None, ast.Call | None]:
    """Resolve `jax.jit(ARG)` to (module, func_name, partial_call)."""
    pcall = None
    if isinstance(arg, ast.Call) and _is_partial(arg.func, imps) and arg.args:
        pcall = arg
        arg = arg.args[0]
    if isinstance(arg, ast.Name):
        if arg.id in defs:
            return None, arg.id, pcall  # local
        if arg.id in imps.from_names:
            mod, orig = imps.from_names[arg.id]
            return mod, orig, pcall
        return None, None, pcall
    if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name):
        mod = imps.module_of(arg.value.id)
        if mod:
            return mod, arg.attr, pcall
    return None, None, pcall


def _call_edges(
    fn: ast.FunctionDef, imps: _Imports, defs: dict[str, ast.FunctionDef],
):
    """(module|None, name) pairs for every resolvable call in fn."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in defs:
                yield None, f.id
            elif f.id in imps.from_names:
                mod, orig = imps.from_names[f.id]
                yield mod, orig
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            mod = imps.module_of(f.value.id)
            if mod:
                yield mod, f.attr


def _module_mutated_names(src: ModuleSource) -> set[str]:
    """Module-level names the module itself mutates somewhere."""
    out: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATOR_METHODS
                and isinstance(f.value, ast.Name)
            ):
                out.add(f.value.id)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AugAssign)
                else node.targets
            )
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ):
                    out.add(t.value.id)
        elif isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _module_mutable_globals(src: ModuleSource) -> dict[str, int]:
    """Top-level names bound to mutable literals -> lineno."""
    out: dict[str, int] = {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            val = node.value
            mutable = isinstance(
                val, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                      ast.SetComp)
            ) or (
                isinstance(val, ast.Call)
                and isinstance(val.func, ast.Name)
                and val.func.id in ("list", "dict", "set", "bytearray",
                                    "defaultdict", "deque")
            )
            if mutable:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.lineno
    return out


def _local_names(fn: ast.FunctionDef) -> set[str]:
    """Names bound inside fn (params, assignments, comprehensions,...)."""
    bound: set[str] = set()
    a = fn.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
    ):
        bound.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                bound.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    return bound


def _collect_roots(
    by_name: dict[str, dict[str, _FuncRef]],
    imports: dict[str, _Imports],
    sources: dict[str, ModuleSource],
) -> list[_FuncRef]:
    roots: list[_FuncRef] = []
    for mod, src in sources.items():
        imps = imports[mod]
        defs = {n: r.node for n, r in by_name.get(mod, {}).items()}
        # decorator form
        for name, ref in by_name.get(mod, {}).items():
            for dec in ref.node.decorator_list:
                call = dec if isinstance(dec, ast.Call) else None
                target = call.func if call else dec
                if _is_jax_jit(target, imps):
                    ref.entry = True
                    ref.static |= _static_argnames(call)
                    roots.append(ref)
                elif call is not None and _is_partial(target, imps):
                    if call.args and _is_jax_jit(call.args[0], imps):
                        ref.entry = True
                        ref.static |= _static_argnames(call)
                        roots.append(ref)
        # call form: jax.jit(f) / jax.jit(partial(mod.f, ...)) /
        # pl.pallas_call(kernel, ...) / shard_map(f, mesh=..., ...) /
        # pl.BlockSpec(shape, index_map)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_block_spec(node.func, imps):
                arg = _block_spec_index_map(node)
            elif (_is_jax_jit(node.func, imps)
                  or _is_pallas_call(node.func, imps)
                  or _is_shard_map(node.func, imps)):
                arg = node.args[0] if node.args else None
            else:
                continue
            if arg is None:
                continue
            tmod, fname, _pcall = _target_of_jit_arg(arg, imps, defs)
            owner = tmod or mod
            ref = by_name.get(owner, {}).get(fname or "")
            if ref is not None:
                ref.entry = True
                ref.static |= _static_argnames(node)
                roots.append(ref)
    return roots


def run(ctx: PackageCtx) -> list[Finding]:
    sources = {_module_name(m.path): m for m in ctx.modules}
    imports = {mod: _Imports(src) for mod, src in sources.items()}
    by_name: dict[str, dict[str, _FuncRef]] = {}
    for mod, src in sources.items():
        by_name[mod] = {
            node.name: _FuncRef(src, node)
            for node in src.tree.body
            if isinstance(node, ast.FunctionDef)
        }

    roots = _collect_roots(by_name, imports, sources)

    # BFS over the name-resolved call graph
    reachable: dict[tuple[str, str], _FuncRef] = {}
    frontier = [
        (_module_name(r.src.path), r.node.name, r) for r in roots
    ]
    while frontier:
        mod, name, ref = frontier.pop()
        key = (mod, name)
        if key in reachable:
            continue
        reachable[key] = ref
        imps = imports[mod]
        defs = {n: r.node for n, r in by_name.get(mod, {}).items()}
        for cmod, cname in _call_edges(ref.node, imps, defs):
            owner = cmod or mod
            cref = by_name.get(owner, {}).get(cname)
            if cref is not None and (owner, cname) not in reachable:
                frontier.append((owner, cname, cref))

    findings: list[Finding] = []
    mutated_cache: dict[str, set[str]] = {}
    mutables_cache: dict[str, dict[str, int]] = {}

    for (mod, name), ref in sorted(reachable.items()):
        src, fn, imps = ref.src, ref.node, imports[mod]
        _check_host_effects(findings, src, fn, imps, name)
        if ref.entry:
            _check_numpy_on_traced(findings, src, fn, imps, name, ref.static)
        if mod not in mutated_cache:
            mutated_cache[mod] = _module_mutated_names(src)
            mutables_cache[mod] = _module_mutable_globals(src)
        _check_mutable_capture(
            findings, src, fn, imps, name,
            mutables_cache[mod], mutated_cache[mod],
        )
    return findings


def _check_host_effects(
    out: list[Finding], src: ModuleSource, fn: ast.FunctionDef,
    imps: _Imports, fname: str,
) -> None:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                src.emit(out, "GL101", node,
                         "`print` inside jit-reachable function",
                         fname, fn.lineno)
            elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                owner = imps.module_of(f.value.id)
                if owner == "time" and f.attr in _TIME_FNS:
                    src.emit(out, "GL101", node,
                             f"`time.{f.attr}` inside jit-reachable function",
                             fname, fn.lineno)
                elif owner == "os" and f.attr == "getenv":
                    src.emit(out, "GL101", node,
                             "`os.getenv` inside jit-reachable function",
                             fname, fn.lineno)
                elif owner and owner.startswith(_EFFECT_MODULES):
                    src.emit(
                        out, "GL101", node,
                        f"observability call `{f.value.id}.{f.attr}` inside "
                        "jit-reachable function",
                        fname, fn.lineno,
                    )
            if isinstance(f, ast.Name) and f.id in imps.from_names:
                owner, _orig = imps.from_names[f.id]
                if owner.startswith(_EFFECT_MODULES):
                    src.emit(
                        out, "GL101", node,
                        f"observability call `{f.id}` inside jit-reachable "
                        "function",
                        fname, fn.lineno,
                    )
        elif isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            owner = imps.module_of(node.value.id)
            if owner == "os" and node.attr == "environ":
                src.emit(out, "GL101", node,
                         "`os.environ` inside jit-reachable function",
                         fname, fn.lineno)
            elif (
                node.value.id == "pathway_config"
                and imps.from_names.get("pathway_config", ("", ""))[0]
                == "pathway_tpu.internals.config"
            ):
                src.emit(
                    out, "GL101", node,
                    f"config read `pathway_config.{node.attr}` inside "
                    "jit-reachable function (frozen at trace time)",
                    fname, fn.lineno,
                )


def _check_numpy_on_traced(
    out: list[Finding], src: ModuleSource, fn: ast.FunctionDef,
    imps: _Imports, fname: str, static: set[str],
) -> None:
    a = fn.args
    params = {arg.arg for arg in list(a.posonlyargs) + list(a.args)
              + list(a.kwonlyargs)}
    traced = params - static
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
            continue
        if imps.module_of(f.value.id) not in _NUMPY_MODULES:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in traced:
                src.emit(
                    out, "GL102", node,
                    f"`{f.value.id}.{f.attr}({arg.id})` on traced parameter "
                    f"`{arg.id}` of jitted `{fname}`",
                    fname, fn.lineno,
                )
                break


def _check_mutable_capture(
    out: list[Finding], src: ModuleSource, fn: ast.FunctionDef,
    imps: _Imports, fname: str,
    mutables: dict[str, int], mutated: set[str],
) -> None:
    if not mutables:
        return
    bound = _local_names(fn)
    seen: set[str] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
            continue
        nm = node.id
        if nm in bound or nm in seen or nm not in mutables:
            continue
        if nm not in mutated:
            continue  # never mutated -> effectively constant, fine
        seen.add(nm)
        src.emit(
            out, "GL103", node,
            f"jit-reachable `{fname}` captures module-level mutable `{nm}` "
            f"(mutated elsewhere in {src.path}) — value is frozen at trace "
            "time",
            fname, fn.lineno,
        )
