"""GL3xx — kill-switch coverage pass.

PRs 2–7 established the discipline: every perf feature ships with an env
kill switch, and a test pins the killed path byte-identical to the
feature path. Until now that was remembered, not enforced. The registry
now carries the contract explicitly — ``Flag.kill_switch=True`` plus
``Flag.pinned_by="tests/test_x.py"`` — and **GL301** verifies it stays
live: the named test file must exist and must actually reference the
env var (a renamed or deleted pinning test un-pins the switch and fails
the analyzer, not a human's memory).

Registry-wide by nature: runs only on full-package scans (needs
``internals/config.py`` in the scanned set). Unit tests drive
:func:`check_kill_switches` directly with synthetic registries and a
tmp_path tests tree.
"""

from __future__ import annotations

import ast
import os

from pathway_tpu.analysis.core import Finding, PackageCtx
from pathway_tpu.analysis.flag_hygiene import CONFIG_PATH, _registry_line


def check_kill_switches(flags, repo_root: str) -> list[tuple[str, str]]:
    """``[(env, problem), ...]`` for every ``kill_switch=True`` flag whose
    pinning contract is broken."""
    problems: list[tuple[str, str]] = []
    for flag in flags:
        if not getattr(flag, "kill_switch", False):
            continue
        pinned_by = getattr(flag, "pinned_by", None)
        if not pinned_by:
            problems.append(
                (flag.env, "kill_switch=True but no `pinned_by=` test file")
            )
            continue
        full = os.path.join(repo_root, pinned_by)
        if not os.path.exists(full):
            problems.append(
                (flag.env, f"pinned_by `{pinned_by}` does not exist")
            )
            continue
        with open(full, encoding="utf-8") as f:
            if flag.env not in f.read():
                problems.append(
                    (flag.env,
                     f"pinned_by `{pinned_by}` never references `{flag.env}` "
                     "— the pinning test is gone or renamed")
                )
    return problems


def run(ctx: PackageCtx) -> list[Finding]:
    config = ctx.module(CONFIG_PATH)
    if config is None or not ctx.registry_checks:
        return []
    from pathway_tpu.internals.config import FLAG_REGISTRY

    findings: list[Finding] = []
    for env, problem in check_kill_switches(FLAG_REGISTRY, ctx.repo_root):
        line = _registry_line(config, env)
        node = ast.Constant(value=env)
        node.lineno = line
        config.emit(findings, "GL301", node,
                    f"`{env}`: {problem}", env)
    return findings
