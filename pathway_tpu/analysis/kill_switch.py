"""GL3xx — kill-switch coverage pass.

PRs 2–7 established the discipline: every perf feature ships with an env
kill switch, and a test pins the killed path byte-identical to the
feature path. Until now that was remembered, not enforced. The registry
now carries the contract explicitly — ``Flag.kill_switch=True`` plus
``Flag.pinned_by="tests/test_x.py"`` — and **GL301** verifies it stays
live: the named test file must exist and must actually reference the
env var (a renamed or deleted pinning test un-pins the switch and fails
the analyzer, not a human's memory).

**GL302** tightens the reference requirement: the env var must appear in
the pinning test's *code* — a string literal outside docstrings (a
``monkeypatch.setenv`` arg, a parametrize id, an env dict key). A
mention that lives only in a docstring or comment satisfies GL301's
substring scan while pinning nothing; GL302 catches exactly that
drift.

Registry-wide by nature: runs only on full-package scans (needs
``internals/config.py`` in the scanned set). Unit tests drive
:func:`check_kill_switches` / :func:`check_pinning_refs` directly with
synthetic registries and a tmp_path tests tree.
"""

from __future__ import annotations

import ast
import os

from pathway_tpu.analysis.core import Finding, PackageCtx
from pathway_tpu.analysis.flag_hygiene import CONFIG_PATH, _registry_line


def check_kill_switches(flags, repo_root: str) -> list[tuple[str, str]]:
    """``[(env, problem), ...]`` for every ``kill_switch=True`` flag whose
    pinning contract is broken."""
    problems: list[tuple[str, str]] = []
    for flag in flags:
        if not getattr(flag, "kill_switch", False):
            continue
        pinned_by = getattr(flag, "pinned_by", None)
        if not pinned_by:
            problems.append(
                (flag.env, "kill_switch=True but no `pinned_by=` test file")
            )
            continue
        full = os.path.join(repo_root, pinned_by)
        if not os.path.exists(full):
            problems.append(
                (flag.env, f"pinned_by `{pinned_by}` does not exist")
            )
            continue
        with open(full, encoding="utf-8") as f:
            if flag.env not in f.read():
                problems.append(
                    (flag.env,
                     f"pinned_by `{pinned_by}` never references `{flag.env}` "
                     "— the pinning test is gone or renamed")
                )
    return problems


def _code_strings(source: str) -> list[str]:
    """Every string literal in ``source`` that is NOT a docstring.

    Comments never reach the AST and module/class/function docstrings are
    the leading ``Expr``-statement constants of their bodies — everything
    left is a literal the code actually uses (a ``setenv`` argument, a
    parametrize list entry, an env dict key, ...).
    """
    tree = ast.parse(source)
    doc_nodes: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                doc_nodes.add(id(body[0].value))
    out: list[str] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in doc_nodes
        ):
            out.append(node.value)
    return out


def check_pinning_refs(flags, repo_root: str) -> list[tuple[str, str]]:
    """``[(env, problem), ...]`` for every kill switch whose pinning test
    mentions the env var ONLY in prose (docstrings/comments) — a
    reference GL301's substring scan accepts but which pins nothing."""
    problems: list[tuple[str, str]] = []
    for flag in flags:
        if not getattr(flag, "kill_switch", False):
            continue
        pinned_by = getattr(flag, "pinned_by", None)
        if not pinned_by:
            continue  # GL301's finding; nothing further to refine
        full = os.path.join(repo_root, pinned_by)
        if not os.path.exists(full):
            continue  # GL301's finding
        with open(full, encoding="utf-8") as f:
            source = f.read()
        if flag.env not in source:
            continue  # GL301's finding
        try:
            strings = _code_strings(source)
        except SyntaxError:
            continue  # unparseable test file fails loudly elsewhere
        if not any(flag.env in s for s in strings):
            problems.append(
                (flag.env,
                 f"pinned_by `{pinned_by}` mentions `{flag.env}` only in "
                 "docstrings/comments — the test must use the env var in "
                 "code (setenv / parametrize / env dict)")
            )
    return problems


def run(ctx: PackageCtx) -> list[Finding]:
    config = ctx.module(CONFIG_PATH)
    if config is None or not ctx.registry_checks:
        return []
    from pathway_tpu.internals.config import FLAG_REGISTRY

    findings: list[Finding] = []
    for rule, checker in (
        ("GL301", check_kill_switches),
        ("GL302", check_pinning_refs),
    ):
        for env, problem in checker(FLAG_REGISTRY, ctx.repo_root):
            line = _registry_line(config, env)
            node = ast.Constant(value=env)
            node.lineno = line
            config.emit(findings, rule, node, f"`{env}`: {problem}", env)
    return findings
