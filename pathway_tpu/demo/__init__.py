"""``pw.demo`` — synthetic stream generators (reference ``python/pathway/demo/``).

``range_stream``, ``noisy_linear``, ``generate_custom_stream``, ``replay_csv``
(+ ``replay_csv_with_time``) — streaming inputs for examples and tests.
"""

from __future__ import annotations

import csv
import time as time_mod
from typing import Any, Callable, Mapping

from pathway_tpu.engine.operators.core import InputNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.io._streams import BaseConnector, next_commit_time


class _GeneratorConnector(BaseConnector):
    def __init__(self, node, gen_rows: Callable, input_rate: float, autocommit_ms: int | None):
        super().__init__(node)
        self.gen_rows = gen_rows
        self.input_rate = input_rate

    def run(self):
        for key, row in self.gen_rows():
            if self.should_stop():
                return
            t = next_commit_time()
            self.emit(t, [(key, row, 1)])
            self.advance(t + 1)
            if self.input_rate > 0:
                time_mod.sleep(1.0 / self.input_rate)


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema,
    nb_rows: int | None = None,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    persistent_id: str | None = None,
    name: str | None = None,
) -> Table:
    cols = list(schema.column_names())
    node = InputNode(G.engine_graph, cols, name="DemoStream")

    def gen_rows():
        i = 0
        while nb_rows is None or i < nb_rows:
            values = {c: value_generators[c](i) for c in cols}
            pk = schema.primary_key_columns()
            key = (
                hash_values(*[values[c] for c in pk]) if pk else hash_values(i)
            )
            yield key, tuple(values[c] for c in cols)
            i += 1

    conn = _GeneratorConnector(node, gen_rows, input_rate, autocommit_duration_ms)
    G.register_connector(conn)
    return Table(node, schema, Universe())


def range_stream(
    nb_rows: int = 30,
    offset: int = 0,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 1000,
    **kwargs,
) -> Table:
    schema = schema_mod.schema_from_types(value=int)
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def noisy_linear_stream(
    nb_rows: int = 10, input_rate: float = 1.0, **kwargs
) -> Table:
    import random

    schema = schema_mod.schema_from_types(x=float, y=float)
    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + random.uniform(-1, 1),
        },
        schema=schema,
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def replay_csv(
    path: str,
    *,
    schema,
    input_rate: float = 1.0,
) -> Table:
    cols = list(schema.column_names())
    dtypes = {n: c.dtype for n, c in schema.__columns__.items()}
    node = InputNode(G.engine_graph, cols, name="ReplayCsv")

    def gen_rows():
        with open(path, newline="") as f:
            for i, record in enumerate(csv.DictReader(f)):
                values = {}
                for c in cols:
                    v = record[c]
                    d = dtypes[c]
                    if d is dt.INT:
                        v = int(v)
                    elif d is dt.FLOAT:
                        v = float(v)
                    elif d is dt.BOOL:
                        v = v.lower() in ("1", "true", "yes")
                    values[c] = v
                pk = schema.primary_key_columns()
                key = hash_values(*[values[c] for c in pk]) if pk else hash_values(i)
                yield key, tuple(values[c] for c in cols)

    conn = _GeneratorConnector(node, gen_rows, input_rate, None)
    G.register_connector(conn)
    return Table(node, schema, Universe())


def replay_csv_with_time(
    path: str,
    *,
    schema,
    time_column: str,
    unit: str = "s",
    autocommit_ms: int = 100,
    speedup: float = 1,
) -> Table:
    return replay_csv(path, schema=schema, input_rate=0)
