"""pathway_tpu.stdlib.viz — notebook display & live plotting.

Importing this module attaches ``show``/``plot``/``_repr_mimebundle_`` to
``Table`` (the reference wires these the same way so `t.show()` / `t.plot()`
work without an explicit viz import, stdlib/viz/table_viz.py:20).
"""

from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.viz.plotting import plot
from pathway_tpu.stdlib.viz.table_viz import show, _repr_mimebundle_

Table.show = show
Table.plot = plot
Table._repr_mimebundle_ = _repr_mimebundle_

__all__ = ["plot", "show"]
