"""Table display: live widget when panel/bokeh are installed, static HTML
snapshot otherwise.

Mirrors the reference's jupyter integration (`stdlib/viz/table_viz.py:26`
``show`` + ``_repr_mimebundle_``) with an explicit no-dependency fallback:
this framework targets headless TPU hosts where panel is usually absent, so
``show`` must degrade to something useful instead of ImportError-ing the
whole notebook cell.
"""

from __future__ import annotations

from typing import Any


def _dtype_label(dtype: Any) -> str:
    s = str(dtype)
    return s.removeprefix("<class '").removesuffix("'>")


def _snapshot_dataframe(table):
    from pathway_tpu.debug import table_to_pandas

    return table_to_pandas(table)


def _frame_for_display(df, include_id: bool, short_pointers: bool):
    if not include_id:
        return df.reset_index(drop=True)
    if short_pointers:
        df = df.copy()
        df.index = [str(i)[:12] for i in df.index]
    return df


def show(table, *, include_id: bool = True, short_pointers: bool = True):
    """Display a table. With panel installed, returns a live-updating panel
    widget fed by ``io.subscribe``; without it, computes the current static
    snapshot and returns an HTML object (works in plain Jupyter).

    Reference parity: ``pw.Table.show`` / cell-magic display
    (stdlib/viz/table_viz.py:26-140).
    """
    try:
        import panel as pn
    except ImportError:
        pn = None

    if pn is None:
        df = _frame_for_display(
            _snapshot_dataframe(table), include_id, short_pointers
        )
        html = df.to_html(max_rows=100)
        try:  # inside IPython, return a rich display object
            from IPython.display import HTML

            return HTML(html)
        except ImportError:
            return html

    import pandas as pd

    import pathway_tpu as pw

    column_names = table.schema.column_names()
    rows: dict[Any, dict] = {}
    widget = pn.widgets.Tabulator(
        pd.DataFrame(columns=column_names), disabled=True
    )

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    def on_time_end(time):
        widget.value = _frame_for_display(
            pd.DataFrame.from_dict(rows, orient="index"),
            include_id, short_pointers,
        )

    pw.io.subscribe(table, on_change=on_change, on_time_end=on_time_end)
    return pn.Column(widget)


def _repr_mimebundle_(self, include=None, exclude=None):
    """Rich notebook repr: schema summary without forcing a compute."""
    cols = {
        name: _dtype_label(cdef.dtype)
        for name, cdef in self.schema.columns().items()
    }
    head = "".join(
        f"<tr><td>{n}</td><td><tt>{t}</tt></td></tr>" for n, t in cols.items()
    )
    html = (
        "<table><thead><tr><th>column</th><th>dtype</th></tr></thead>"
        f"<tbody>{head}</tbody></table>"
    )
    return {"text/html": html, "text/plain": repr(self)}
