"""Table display: live widget when panel/bokeh are installed, static HTML
snapshot otherwise.

Mirrors the reference's jupyter integration (`stdlib/viz/table_viz.py:26`
``show`` + ``_repr_mimebundle_``) with an explicit no-dependency fallback:
this framework targets headless TPU hosts where panel is usually absent, so
``show`` must degrade to something useful instead of ImportError-ing the
whole notebook cell.
"""

from __future__ import annotations

from typing import Any


def _dtype_label(dtype: Any) -> str:
    s = str(dtype)
    return s.removeprefix("<class '").removesuffix("'>")


def _snapshot_dataframe(table):
    from pathway_tpu.debug import table_to_pandas

    return table_to_pandas(table)


def _frame_for_display(df, include_id: bool, short_pointers: bool):
    if not include_id:
        return df.reset_index(drop=True)
    if short_pointers:
        df = df.copy()
        df.index = [str(i)[:12] for i in df.index]
    return df


def _format_value(x, short_pointers: bool = True):
    """Type-aware cell formatting (reference ``table_viz.py:60-70``
    ``_format_types``): Pointers shorten, long Json truncates, the rest
    passes through."""
    from pathway_tpu.engine.value import Pointer
    from pathway_tpu.internals.json import Json

    if isinstance(x, Pointer):
        s = str(x)
        if len(s) > 8 and short_pointers:
            s = s[:8] + "..."
        return s
    if isinstance(x, Json):
        s = str(x)
        if len(s) > 64:
            s = s[:64] + " ..."
        return s
    return x


def show(
    table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    snapshot: bool = True,
):
    """Display a table. With panel installed, returns a live-updating panel
    widget fed by ``io.subscribe``; without it, computes the current static
    snapshot and returns an HTML object (works in plain Jupyter).

    ``snapshot=False`` shows the CHANGELOG instead of the current state:
    every update row with its engine ``time`` and ``diff``, newest first,
    retractions styled red / additions green — the reference's streaming
    table view (stdlib/viz/table_viz.py:55-100).
    """
    try:
        import panel as pn
    except ImportError:
        pn = None

    if pn is None:
        df = _frame_for_display(
            _snapshot_dataframe(table), include_id, short_pointers
        )
        df = df.map(lambda x: _format_value(x, short_pointers))
        html = df.to_html(max_rows=100)
        try:  # inside IPython, return a rich display object
            from IPython.display import HTML

            return HTML(html)
        except ImportError:
            return html

    import pandas as pd

    import pathway_tpu as pw

    column_names = list(table.schema.column_names())
    frame_cols = column_names + (["time", "diff"] if not snapshot else [])
    widget = pn.widgets.Tabulator(
        pd.DataFrame(columns=frame_cols), disabled=True
    )
    if not snapshot:
        # changelog view: color retractions red, additions green
        def _diff_colors(row):
            color = "red" if row["diff"] < 0 else "green"
            return [f"color: {color}" for _ in row]

        style = getattr(widget, "style", None)
        if style is not None:
            style.apply(_diff_colors, axis=1)

    rows: dict[Any, dict] = {}
    changelog: list[dict] = []

    def on_change(key, row, time, is_addition):
        if snapshot:
            if is_addition:
                rows[key] = row
            else:
                rows.pop(key, None)
        else:
            changelog.append(
                {**row, "time": time, "diff": 1 if is_addition else -1}
            )

    def on_time_end(time):
        if snapshot:
            df = _frame_for_display(
                pd.DataFrame.from_dict(rows, orient="index"),
                include_id, short_pointers,
            )
        else:
            df = pd.DataFrame(
                list(reversed(changelog)), columns=frame_cols
            )
        widget.value = df.map(lambda x: _format_value(x, short_pointers))

    pw.io.subscribe(table, on_change=on_change, on_time_end=on_time_end)
    return pn.Column(widget)


def _repr_mimebundle_(self, include=None, exclude=None):
    """Rich notebook repr: schema summary without forcing a compute."""
    cols = {
        name: _dtype_label(cdef.dtype)
        for name, cdef in self.schema.columns().items()
    }
    head = "".join(
        f"<tr><td>{n}</td><td><tt>{t}</tt></td></tr>" for n, t in cols.items()
    )
    html = (
        "<table><thead><tr><th>column</th><th>dtype</th></tr></thead>"
        f"<tbody>{head}</tbody></table>"
    )
    return {"text/html": html, "text/plain": repr(self)}
