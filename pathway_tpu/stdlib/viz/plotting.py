"""Live Bokeh plots over streaming tables.

Reference parity: ``stdlib/viz/plotting.py:35`` ``plot(table,
plotting_function, sorting_col)`` — a user function receives a Bokeh
``ColumnDataSource`` and returns a figure. Like the reference:

* a table with only BOUNDED inputs renders immediately ("Static preview"
  banner) — the subgraph is computed on the spot and the source filled;
* a table with streaming inputs renders a "Streaming mode" banner and the
  source auto-updates from the change stream after ``pw.run()`` starts,
  via incremental ``source.stream(..., rollover=n)`` pushes (not full
  re-assignment — bokeh diffs streamed patches efficiently).

Bokeh/panel are optional: on headless TPU hosts ``plot`` raises a clear
ImportError naming the extras instead of failing at some deeper import.
"""

from __future__ import annotations

from typing import Any, Callable


def _has_streaming_input(table) -> bool:
    """True when any live connector feeds the table's subgraph (the
    reference asks its GraphRunner ``has_bounded_input``; here sources are
    explicit on the parse graph: connectors stream, static sources don't).
    """
    from pathway_tpu.internals.parse_graph import G

    seen: set[int] = set()
    stack = [table._node]
    connector_nodes = {c.node.id for c in G.connectors}
    while stack:
        node = stack.pop()
        if node.id in seen:
            continue
        seen.add(node.id)
        if node.id in connector_nodes:
            return True
        stack.extend(node.inputs)
    return False


def _ordered_rows(rows: dict, column_names, sorting_col):
    ordered = list(rows.values())
    if sorting_col is not None:
        name = getattr(sorting_col, "name", sorting_col)
        ordered.sort(key=lambda r: r[name])
    return {c: [r.get(c) for r in ordered] for c in column_names}


def plot(table, plotting_function: Callable, sorting_col=None):
    """Build a live plot of the table.

    ``plotting_function(source) -> bokeh.models.Plot`` receives a
    ``ColumnDataSource`` whose columns follow the table's columns; the
    returned figure re-renders on every engine time advancement (or at
    once for bounded inputs).
    """
    try:
        import panel as pn
        from bokeh.models import ColumnDataSource
    except ImportError as e:
        raise ImportError(
            "pw.Table.plot needs the optional viz dependencies; "
            "install bokeh and panel"
        ) from e

    import pathway_tpu as pw

    column_names = table.schema.column_names()
    source = ColumnDataSource(data={c: [] for c in column_names})
    fig = plotting_function(source)
    streaming = _has_streaming_input(table)
    banner = "Streaming mode" if streaming else "Static preview"
    viz = pn.Column(pn.Row(banner), fig)

    if not streaming:
        # bounded inputs: compute the snapshot right away, like the
        # reference's immediate preview for bounded data sources
        from pathway_tpu.internals.run import capture_table

        cap = capture_table(table)
        rows = {
            k: dict(zip(cap.column_names, row))
            for k, row in dict(cap.state.rows).items()
        }
        data = _ordered_rows(rows, column_names, sorting_col)
        n = len(next(iter(data.values()), []))
        source.stream(data, rollover=n or None)
        return viz

    rows: dict[Any, dict] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    def on_time_end(time):
        data = _ordered_rows(rows, column_names, sorting_col)
        if not rows:
            # an all-rows retraction must CLEAR the figure; stream() with
            # empty columns would leave the stale points rendered
            source.data = data
            return
        # stream+rollover replaces the window in one patch; bokeh ships
        # the patch to the browser instead of re-serializing the figure
        source.stream(data, rollover=len(rows))

    pw.io.subscribe(table, on_change=on_change, on_time_end=on_time_end)
    return viz
