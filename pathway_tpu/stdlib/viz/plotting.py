"""Live Bokeh plots over streaming tables.

Reference parity: `stdlib/viz/plotting.py:35` ``plot(table,
plotting_function, sorting_col)`` — a user function receives a Bokeh
``ColumnDataSource`` and returns a figure; the source is updated from the
table's change stream so the figure animates as the computation progresses.

Bokeh/panel are optional: on headless TPU hosts ``plot`` raises a clear
ImportError naming the extras instead of failing at some deeper import.
"""

from __future__ import annotations

from typing import Any, Callable


def plot(table, plotting_function: Callable, sorting_col=None):
    """Build a live plot of the table.

    ``plotting_function(source) -> bokeh.models.Plot`` receives a
    ``ColumnDataSource`` whose columns follow the table's columns; the
    returned figure re-renders on every engine time advancement.
    """
    try:
        import panel as pn
        from bokeh.models import ColumnDataSource
    except ImportError as e:
        raise ImportError(
            "pw.Table.plot needs the optional viz dependencies; "
            "install bokeh and panel"
        ) from e

    import pathway_tpu as pw

    column_names = table.schema.column_names()
    source = ColumnDataSource(data={c: [] for c in column_names})
    fig = plotting_function(source)
    rows: dict[Any, dict] = {}

    def on_change(key, row, time, is_addition):
        if is_addition:
            rows[key] = row
        else:
            rows.pop(key, None)

    def on_time_end(time):
        ordered = list(rows.values())
        if sorting_col is not None:
            name = getattr(sorting_col, "name", sorting_col)
            ordered.sort(key=lambda r: r[name])
        source.data = {
            c: [r.get(c) for r in ordered] for c in column_names
        }

    pw.io.subscribe(table, on_change=on_change, on_time_end=on_time_end)
    return pn.Column(pn.pane.Bokeh(fig))
