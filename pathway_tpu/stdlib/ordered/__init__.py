"""Ordered-table operations (reference ``stdlib/ordered/diff.py``)."""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod


def diff(table, timestamp, *values, instance=None):
    """Per-row difference with the previous row ordered by ``timestamp``:
    ``diff_<name>`` columns (reference ``Table.diff``)."""
    sorted_ptrs = table.sort(timestamp, instance=instance)
    prev_vals = {}
    for v in values:
        name = v.name if isinstance(v, expr_mod.ColumnReference) else str(v)
        prev = table.ix(sorted_ptrs.prev, optional=True)[name]
        prev_vals[f"diff_{name}"] = expr_mod.apply_with_type(
            lambda cur, pv: None if pv is None else cur - pv,
            None,
            table[name],
            prev,
        )
    return table.with_columns(**prev_vals)
