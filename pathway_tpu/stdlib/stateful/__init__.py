"""Stateful operations (reference ``stdlib/stateful/deduplicate.py``)."""

from __future__ import annotations

from typing import Any, Callable


def deduplicate(
    table,
    *,
    value,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
):
    """Keep the previously accepted value per instance unless ``acceptor(new,
    old)`` approves a change."""
    return table.deduplicate(value=value, instance=instance, acceptor=acceptor)
