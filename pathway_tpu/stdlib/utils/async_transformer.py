"""AsyncTransformer — fully-async row→row transformation with its own output
universe (reference ``stdlib/utils/async_transformer.py:282``): invoke() runs
per row; failed rows are filtered out; ``.successful`` / ``.failed`` /
``.finished`` views.
"""

from __future__ import annotations

import asyncio
from abc import ABC, abstractmethod
from typing import Any, ClassVar

from pathway_tpu.engine.value import ERROR
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table


import enum


class ResultType(enum.Enum):
    """Row outcome of an async transformer invocation (reference
    ``async_transformer.py:ResultType``)."""

    SUCCESS = "success"
    FAILURE = "failure"


class AsyncTransformer(ABC):
    output_schema: ClassVar[Any]

    def __init_subclass__(cls, /, output_schema=None, **kwargs):
        # reference API: class X(pw.AsyncTransformer, output_schema=Schema)
        super().__init_subclass__(**kwargs)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(self, input_table: Table, instance=None, **kwargs):
        self._input_table = input_table
        self._instance = instance

    @abstractmethod
    async def invoke(self, *args, **kwargs) -> dict: ...

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def successful(self) -> Table:
        return self.result

    @property
    def failed(self) -> Table:
        result = self._full_result()
        # rows whose outputs errored: the apply propagates ERROR, fill_error
        # turns it into True; clean rows evaluate to False and are dropped
        cond = expr_mod.fill_error(
            expr_mod.apply_with_type(
                lambda *vals: False, bool, *[result[n] for n in result.column_names()]
            ),
            True,
        )
        failed = result.filter(cond)
        # the error outputs themselves are unusable values — surface them
        # as None so the failed table can flow into sinks/joins (matching
        # the reference's consumable failure diagnostics)
        return failed.select(
            **{
                n: expr_mod.fill_error(failed[n], None)
                for n in result.column_names()
            }
        )

    @property
    def finished(self) -> Table:
        return self._full_result()

    _cached: Table | None = None

    def _full_result(self) -> Table:
        if self._cached is not None:
            return self._cached
        self.open()
        schema = self.output_schema
        cols = list(self._input_table.column_names())
        out_cols = list(schema.column_names())
        transformer = self

        async def call(*vals):
            kwargs = dict(zip(cols, vals))
            result = await transformer.invoke(**kwargs)
            return tuple(result.get(c) for c in out_cols)

        tuple_expr = expr_mod.AsyncApplyExpression(
            call,
            dt.ANY_TUPLE,
            args=tuple(self._input_table[c] for c in cols),
        )
        packed = self._input_table.select(__packed=tuple_expr)
        exprs = {
            name: expr_mod.GetExpression(
                packed["__packed"], i, check_if_exists=False
            )
            for i, name in enumerate(out_cols)
        }
        result = packed.select(**exprs)
        result = Table(
            result._node,
            schema_mod.schema_builder_from_definitions(
                {
                    n: schema_mod.ColumnDefinition(
                        dtype=schema.__columns__[n].dtype, name=n
                    )
                    for n in out_cols
                }
            ),
            result._universe,
        )
        self._cached = result
        return result

    @property
    def result(self) -> Table:
        result = self._full_result()
        cond = expr_mod.fill_error(
            expr_mod.apply_with_type(
                lambda *vals: True, bool, *[result[n] for n in result.column_names()]
            ),
            False,
        )
        return result.filter(cond)

    def with_options(self, **kwargs) -> "AsyncTransformer":
        return self
