"""``@pw.pandas_transformer`` (reference ``stdlib/utils/pandas_transformer.py``):
run a pandas DataFrame function over full tables, re-entering the dataflow.
Executes per epoch end via capture + static rebuild (batch semantics)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import schema as schema_mod


def pandas_transformer(output_schema: Any, output_universe: Any | None = None):
    def decorator(fun: Callable):
        def wrapper(*tables):
            from pathway_tpu.debug import table_from_pandas, table_to_pandas

            dfs = [table_to_pandas(t, include_id=False) for t in tables]
            out = fun(*dfs)
            out.columns = list(output_schema.column_names())
            return table_from_pandas(out, schema=output_schema)

        return wrapper

    return decorator
