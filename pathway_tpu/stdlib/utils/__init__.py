"""``pw.utils`` helpers (reference ``python/pathway/stdlib/utils/``)."""

from pathway_tpu.stdlib.utils import bucketing, col, filtering
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer

__all__ = ["AsyncTransformer", "pandas_transformer", "bucketing", "col", "filtering"]
