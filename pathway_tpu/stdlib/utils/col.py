"""Column manipulation helpers (reference ``stdlib/utils/col.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference


def unpack_col(column, *unpacked_columns, schema=None):
    """Expand a tuple column into separate columns."""
    table = column.table
    if schema is not None:
        names = list(schema.column_names())
    else:
        names = [
            c.name if isinstance(c, ColumnReference) else c
            for c in unpacked_columns
        ]
    from pathway_tpu.internals import expression as expr_mod

    exprs = {
        name: expr_mod.GetExpression(column, i, check_if_exists=False)
        for i, name in enumerate(names)
    }
    return table.select(**exprs)


def flatten_column(column, origin_id="origin_id"):
    """Deprecated alias for ``Table.flatten`` (reference ``col.py:16``)."""
    import warnings

    warnings.warn(
        "utils.col.flatten_column() is deprecated, use Table.flatten() instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return column.table.flatten(column, origin_id=origin_id)


def unpack_col_dict(column, schema):
    """Unpack a Json-object column into typed columns given by ``schema``
    (reference ``col.py:143``).  Missing keys become None; non-optional
    columns are unwrapped."""
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals import expression as expr_mod

    table = column.table
    typehints = schema._dtypes()

    def convert(name):
        target = typehints[name]
        is_optional = target.is_optional()
        inner = target.strip_optional()
        col = expr_mod.GetExpression(column, name, default=None, check_if_exists=True)
        # Json payloads in this engine hold plain Python scalars, so no
        # as_int/as_float coercion chain is needed; float columns may still
        # arrive as Json ints.
        if inner == dt.FLOAT:
            col = expr_mod.apply_with_type(
                lambda v: None if v is None else float(v), target, col
            )
        if not is_optional:
            col = expr_mod.unwrap(col)
        return col

    result = table.select(**{n: convert(n) for n in schema.column_names()})
    return result.update_types(**{n: typehints[n] for n in schema.column_names()})


def multiapply_all_rows(*cols, fun, result_col_names):
    """Apply ``fun`` to entire columns at once (all rows gathered into one
    state), returning several result columns re-keyed to the original rows.
    Reference ``col.py:multiapply_all_rows``; meant for small tables."""
    from pathway_tpu.internals import expression as expr_mod
    from pathway_tpu.internals import reducers

    assert len(cols) > 0
    table = cols[0].table
    n_cols = len(cols)
    names = [
        c.name if isinstance(c, ColumnReference) else c for c in result_col_names
    ]

    packed = table.select(
        packed=expr_mod.apply(lambda *a: tuple(a), table.id, *cols)
    )
    reduced = packed.reduce(rows=reducers.sorted_tuple(packed.packed))

    def fun_wrapped(rows):
        ids = [r[0] for r in rows]
        col_lists = [[r[i + 1] for r in rows] for i in range(n_cols)]
        results = fun(*col_lists)
        return [
            (ids[j], *[results[m][j] for m in range(len(names))])
            for j in range(len(ids))
        ]

    out = reduced.select(out=expr_mod.apply(fun_wrapped, reduced.rows))
    flat = out.flatten(out.out)
    keyed = flat.select(
        _pw_key=expr_mod.GetExpression(flat.out, 0, check_if_exists=False),
        **{
            name: expr_mod.GetExpression(flat.out, i + 1, check_if_exists=False)
            for i, name in enumerate(names)
        },
    )
    return keyed.with_id(keyed["_pw_key"]).without("_pw_key")


def apply_all_rows(*cols, fun, result_col_name):
    """Single-output-column variant of ``multiapply_all_rows``: ``fun``
    returns ONE list of per-row results (reference ``col.py:apply_all_rows``)."""
    return multiapply_all_rows(
        *cols, fun=lambda *col_lists: (fun(*col_lists),),
        result_col_names=[result_col_name]
    )


def groupby_reduce_majority(column, votes_column):
    """Per-group majority vote: groups rows by ``column`` and reduces
    ``votes_column`` to its most frequent value in column ``majority``
    (reference ``col.py:groupby_reduce_majority``)."""
    from collections import Counter

    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals import expression as expr_mod
    from pathway_tpu.internals import reducers

    table = column.table
    return table.groupby(column).reduce(
        column,
        majority=expr_mod.apply_with_type(
            lambda vs: Counter(vs).most_common(1)[0][0],
            dt.ANY,
            reducers.tuple(votes_column),
        ),
    )
