"""Column manipulation helpers (reference ``stdlib/utils/col.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.expression import ColumnReference


def unpack_col(column, *unpacked_columns, schema=None):
    """Expand a tuple column into separate columns."""
    table = column.table
    if schema is not None:
        names = list(schema.column_names())
    else:
        names = [
            c.name if isinstance(c, ColumnReference) else c
            for c in unpacked_columns
        ]
    from pathway_tpu.internals import expression as expr_mod

    exprs = {
        name: expr_mod.GetExpression(column, i, check_if_exists=False)
        for i, name in enumerate(names)
    }
    return table.select(**exprs)


def multiapply_all_rows(*cols, fun, result_col_names):
    raise NotImplementedError("multiapply_all_rows arrives with row transformers")


def apply_all_rows(*cols, fun, result_col_name):
    raise NotImplementedError("apply_all_rows arrives with row transformers")


def groupby_reduce_majority(column, votes_column):
    table = column.table
    grouped = table.groupby(column, votes_column).reduce(
        column, votes_column, _pw_count=_count_reducer()
    )
    return grouped


def _count_reducer():
    from pathway_tpu.internals import reducers

    return reducers.count()
