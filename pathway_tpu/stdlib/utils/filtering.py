"""Argmin/argmax row filtering helpers (reference ``stdlib/utils/filtering.py``)."""

from __future__ import annotations

from pathway_tpu.internals import reducers


def argmin_rows(table, *on, what):
    ids = table.groupby(*on).reduce(argmin_id=reducers.argmin(what))
    return _pick(table, ids)


def argmax_rows(table, *on, what):
    ids = table.groupby(*on).reduce(argmin_id=reducers.argmax(what))
    return _pick(table, ids)


def _pick(table, ids):
    reindexed = ids.with_id(ids.argmin_id)
    return table.restrict(reindexed)
