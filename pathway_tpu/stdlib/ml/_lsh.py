"""LSH bucketers (reference ``stdlib/ml/classifiers/_lsh.py``).

``generate_euclidean_lsh_bucketer`` / ``generate_cosine_lsh_bucketer`` build
callables mapping a vector to ``L`` integer bucket ids (one per OR-band, each
the AND of ``M`` hashes).  ``lsh`` applies a bucketer to a vector column and
flattens the table to one row per (origin row, band).

The projections are a single ``(d, M*L)`` matmul per vector; when applied to a
whole column the engine batches rows, so the matmul is a batched ``(B, d) @
(d, M*L)`` — small enough that host numpy beats a TPU round-trip, which is why
this stays off-device (the TPU KNN path lives in ``ops/knn.py``).
"""

from __future__ import annotations

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.fingerprints import fingerprint
from pathway_tpu.stdlib.utils.col import unpack_col


def generate_euclidean_lsh_bucketer(d: int, M: int, L: int, A: float = 1.0, seed=0):
    """LSH for Euclidean distance: project on ``M*L`` random unit lines,
    quantize into buckets of width ``A``, fingerprint each band of ``M``."""
    gen = np.random.default_rng(seed=seed)
    lines = gen.standard_normal((d, M * L))
    lines = lines / np.linalg.norm(lines, axis=0)
    shift = gen.random(size=M * L) * A

    def bucketify(x: np.ndarray) -> np.ndarray:
        quantized = np.floor_divide(np.asarray(x) @ lines + shift, A).astype(int)
        bands = np.split(quantized, L)
        return np.array([fingerprint(band.tobytes(), format="i32") for band in bands])

    return bucketify


def generate_cosine_lsh_bucketer(d: int, M: int, L: int, seed=0):
    """LSH for cosine similarity: sign patterns against ``M*L`` random
    hyperplanes, each band of ``M`` signs packed into one integer."""
    gen = np.random.default_rng(seed=seed)
    planes = gen.standard_normal((d, M * L))
    powers = 2 ** np.arange(M)

    def bucketify(x: np.ndarray) -> np.ndarray:
        signs = (np.asarray(x) @ planes >= 0).astype(int)
        bands = np.split(signs, L)
        return np.array([int(band @ powers) for band in bands])

    return bucketify


def lsh(data, bucketer, origin_id: str = "origin_id", include_data: bool = True):
    """Apply ``bucketer`` to ``data.data`` and flatten: one output row per
    (input row, band) with columns ``bucketing`` (band index), ``band``
    (bucket id) and, when ``include_data``, the original vector."""
    flat = data.select(
        buckets=expr_mod.apply(
            lambda x: [(i, int(b)) for i, b in enumerate(bucketer(x))], data.data
        )
    )
    flat = flat.flatten(flat.buckets, origin_id=origin_id)
    result = flat.select(flat[origin_id]) + unpack_col(
        flat.buckets, "bucketing", "band"
    )
    if include_data:
        result += result.select(data.ix(result[origin_id]).data)
    return result
