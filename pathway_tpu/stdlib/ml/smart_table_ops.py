"""Fuzzy join (reference ``stdlib/ml/smart_table_ops/_fuzzy_join.py``):
match rows of two tables by feature overlap."""

from __future__ import annotations

import enum

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals import thisclass


class FuzzyJoinFeatureGeneration(enum.Enum):
    AUTO = 0
    TOKENIZE = 1


class FuzzyJoinNormalization(enum.Enum):
    WEIGHT = 0
    LOG_WEIGHT = 1


def smart_fuzzy_join(
    left,
    right,
    left_column=None,
    right_column=None,
    **kwargs,
):
    """Match rows by shared lowercase tokens, scoring by inverse token
    frequency; returns (left_id, right_id, weight)."""
    import re

    def tokens(s):
        return tuple(t.lower() for t in re.findall(r"[A-Za-z0-9]+", s or ""))

    lcol = left_column if left_column is not None else left[left.column_names()[0]]
    rcol = right_column if right_column is not None else right[right.column_names()[0]]

    ltok = left.select(
        lid=left.id, token=expr_mod.apply_with_type(tokens, dt.ANY_TUPLE, lcol)
    ).flatten(thisclass.this.token)
    rtok = right.select(
        rid=right.id, token=expr_mod.apply_with_type(tokens, dt.ANY_TUPLE, rcol)
    ).flatten(thisclass.this.token)
    pairs = ltok.join(rtok, ltok.token == rtok.token).select(
        lid=thisclass.left.lid, rid=thisclass.right.rid
    )
    scored = pairs.groupby(pairs.lid, pairs.rid).reduce(
        pairs.lid, pairs.rid, weight=reducers.count()
    )
    best = scored.groupby(thisclass.this.lid).reduce(
        left_id=thisclass.this.lid,
        best_match=reducers.argmax(thisclass.this.weight),
        weight=reducers.max(thisclass.this.weight),
    )
    return best


fuzzy_match_tables = smart_fuzzy_join
