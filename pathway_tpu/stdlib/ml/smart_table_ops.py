"""Fuzzy joins (reference ``stdlib/ml/smart_table_ops/_fuzzy_join.py``):
match rows of two tables by weighted feature overlap, normalized by
feature frequency, resolved to a near-1-1 matching by per-side argmax.

Pipeline: column(s) → feature bags (tokens/letters) → (node, feature,
weight) edge tables → frequency-normalized pair scores → mutual-argmax
matching.  Rare features pair directly through a feature equi-join; heavy
features (≥ HEAVY_LIGHT_THRESHOLD occurrences) only re-score pairs the
light features already produced, avoiding the quadratic blowup.
"""

from __future__ import annotations

import math
from enum import IntEnum, auto
from typing import Any, Callable

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.api import Pointer
from pathway_tpu.internals.schema import Schema


class Node(Schema):
    pass


class Feature(Schema):
    weight: float
    normalization_type: int


class Edge(Schema):
    node: Pointer
    feature: Pointer
    weight: float


class JoinResult(Schema):
    left: Pointer
    right: Pointer
    weight: float


def _tokenize(obj: Any) -> Any:
    return str(obj).split()


def _letters(obj: Any) -> Any:
    return [c.lower() for c in str(obj) if c.isalnum()]


class FuzzyJoinFeatureGeneration(IntEnum):
    AUTO = auto()
    TOKENIZE = auto()
    LETTERS = auto()

    @property
    def generate(self) -> Callable[[Any], Any]:
        if self == FuzzyJoinFeatureGeneration.LETTERS:
            return _letters
        return _tokenize


def _discrete_weight(cnt: float) -> float:
    return 0.0 if cnt == 0 else 1 / (2 ** math.ceil(math.log2(cnt)))


def _discrete_logweight(cnt: float) -> float:
    return 0.0 if cnt == 0 else 1 / math.ceil(math.log2(cnt + 1))


class FuzzyJoinNormalization(IntEnum):
    WEIGHT = auto()
    LOGWEIGHT = auto()
    NONE = auto()

    @property
    def normalize(self) -> Callable[[float], float]:
        if self == FuzzyJoinNormalization.WEIGHT:
            return _discrete_weight
        if self == FuzzyJoinNormalization.LOGWEIGHT:
            return _discrete_logweight
        return lambda cnt: cnt


def _concatenate_columns(table):
    return table.select(
        desc=expr_mod.apply(
            lambda *args: " ".join(str(a) for a in args),
            *[table[name] for name in table.column_names()],
        )
    )


def _edges_and_features(tab, col, feature_generation, normalization):
    """Build the (node, feature, weight) edge table and the feature table
    for one side."""
    bags = tab.select(
        feature=expr_mod.apply(feature_generation.generate, col)
    )
    bags = bags.flatten(bags.feature, origin_id="origin_id")
    features = bags.groupby(bags.feature).reduce(
        normalization_type=int(normalization),
        weight=1.0,
    )
    edges = bags.select(
        node=bags.origin_id,
        feature=features.pointer_from(bags.feature),
        weight=1.0,
    )
    return edges, features


def smart_fuzzy_match(
    left_col,
    right_col,
    *,
    by_hand_match=None,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
    HEAVY_LIGHT_THRESHOLD: int = 100,
):
    """Fuzzy-match two string columns; returns a JoinResult table
    (reference ``_fuzzy_join.py:199``)."""
    left, right = left_col.table, right_col.table
    self_match = left is right and left_col.name == right_col.name

    edges_left, features_left = _edges_and_features(
        left, left_col, feature_generation, normalization
    )
    if self_match:
        return fuzzy_self_match(
            edges_left, features_left, by_hand_match, HEAVY_LIGHT_THRESHOLD
        )
    edges_right, features_right = _edges_and_features(
        right, right_col, feature_generation, normalization
    )
    features = features_left.update_rows(features_right)
    return fuzzy_match(
        edges_left, edges_right, features, by_hand_match, HEAVY_LIGHT_THRESHOLD
    )


def fuzzy_self_match(
    edges, features, by_hand_match=None, HEAVY_LIGHT_THRESHOLD: int = 100
):
    """Match a table against itself (reference ``_fuzzy_join.py:249``)."""
    return _fuzzy_match(
        edges,
        edges,
        features,
        symmetric=True,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        by_hand_match=by_hand_match,
    )


def fuzzy_match(
    edges_left, edges_right, features, by_hand_match=None,
    HEAVY_LIGHT_THRESHOLD: int = 100,
):
    """Match two edge tables over shared features (reference
    ``_fuzzy_join.py:265``)."""
    return _fuzzy_match(
        edges_left,
        edges_right,
        features,
        symmetric=False,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        by_hand_match=by_hand_match,
    )


def fuzzy_match_with_hint(
    edges_left, edges_right, features, by_hand_match,
    HEAVY_LIGHT_THRESHOLD: int = 100,
):
    """Like ``fuzzy_match`` but with hand-matched pairs pinned
    (reference ``_fuzzy_join.py:282``)."""
    return _fuzzy_match(
        edges_left,
        edges_right,
        features,
        symmetric=False,
        HEAVY_LIGHT_THRESHOLD=HEAVY_LIGHT_THRESHOLD,
        by_hand_match=by_hand_match,
    )


def fuzzy_match_tables(
    left_table,
    right_table,
    *,
    by_hand_match=None,
    normalization=FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation=FuzzyJoinFeatureGeneration.AUTO,
    left_projection: dict | None = None,
    right_projection: dict | None = None,
):
    """Fuzzy-match whole tables; columns optionally projected into named
    buckets matched bucket-against-bucket (reference ``_fuzzy_join.py:106``)."""
    left_projection = left_projection or {}
    right_projection = right_projection or {}
    if not left_projection or not right_projection:
        left = _concatenate_columns(left_table)
        right = _concatenate_columns(right_table)
        return smart_fuzzy_match(
            left.desc,
            right.desc,
            by_hand_match=by_hand_match,
            normalization=normalization,
            feature_generation=feature_generation,
        )

    buckets_left: dict[str, list] = {}
    buckets_right: dict[str, list] = {}
    order: list[str] = []
    for col, b in left_projection.items():
        if b not in order:
            order.append(b)
        buckets_left.setdefault(b, []).append(col)
    for col, b in right_projection.items():
        if b not in order:
            order.append(b)
        buckets_right.setdefault(b, []).append(col)

    partial = []
    for b in order:
        lt = left_table.select(**{c: left_table[c] for c in buckets_left.get(b, [])})
        rt = right_table.select(
            **{c: right_table[c] for c in buckets_right.get(b, [])}
        )
        partial.append(
            fuzzy_match_tables(
                lt,
                rt,
                by_hand_match=by_hand_match,
                normalization=normalization,
                feature_generation=feature_generation,
            )
        )
    matchings = partial[0].concat_reindex(*partial[1:])
    merged = matchings.groupby(matchings.left, matchings.right).reduce(
        matchings.left,
        matchings.right,
        weight=reducers.sum(matchings.weight),
    )
    if by_hand_match is not None:
        # every bucket appended the hand pairs, so the sum above multiplied
        # their weight by the bucket count; pin the original weights back
        merged = merged.with_id_from(merged.left, merged.right).update_rows(
            by_hand_match.with_id_from(by_hand_match.left, by_hand_match.right)
        )
    return merged


def _filter_out_matched_by_hand(edges_left, edges_right, symmetric, by_hand_match):
    matched_left = by_hand_match.select(node=by_hand_match.left)
    matched_right = by_hand_match.select(node=by_hand_match.right)
    if symmetric:
        matched_left = matched_left.concat_reindex(matched_right)
        matched_right = matched_left
    taken_l = matched_left.groupby(matched_left.node).reduce(matched_left.node)
    taken_r = matched_right.groupby(matched_right.node).reduce(matched_right.node)

    def keep(edges, taken):
        j = edges.join_left(taken, edges.node == taken.node, id=edges.id).select(
            hit=taken.node
        )
        return edges.filter(
            expr_mod.apply_with_type(lambda h: h is None, bool, j.restrict(edges).hit)
        )

    out_l = keep(edges_left, taken_l)
    out_r = out_l if symmetric else keep(edges_right, taken_r)
    return out_l, out_r


def _fuzzy_match(
    edges_left,
    edges_right,
    features,
    *,
    symmetric: bool,
    HEAVY_LIGHT_THRESHOLD: int,
    by_hand_match=None,
):
    if by_hand_match is not None:
        edges_left, edges_right = _filter_out_matched_by_hand(
            edges_left, edges_right, symmetric, by_hand_match
        )

    if symmetric:
        all_edges = edges_left
    else:
        all_edges = edges_left.concat_reindex(edges_right)
    features_cnt = features.select(cnt=0).update_rows(
        all_edges.groupby(id=all_edges.feature).reduce(cnt=reducers.count())
    )

    def split(edges):
        heavy = edges.filter(
            features_cnt.ix(edges.feature).cnt >= HEAVY_LIGHT_THRESHOLD
        )
        light = edges.filter(
            features_cnt.ix(edges.feature).cnt < HEAVY_LIGHT_THRESHOLD
        )
        return heavy, light

    left_heavy, left_light = split(edges_left)
    if symmetric:
        right_heavy, right_light = left_heavy, left_light
    else:
        right_heavy, right_light = split(edges_right)

    features_normalized = features.select(
        weight=features.weight
        * expr_mod.apply_with_type(
            lambda cnt, ntype: FuzzyJoinNormalization(ntype).normalize(cnt),
            float,
            features_cnt.restrict(features).cnt,
            features.normalization_type,
        )
    )

    # rare features generate candidate pairs directly; side markers
    # (thisclass.left/right) keep the sides distinct in the symmetric
    # self-join case where both operands are the same table object
    from pathway_tpu.internals import thisclass

    light_pairs = left_light.join(
        right_light,
        thisclass.left.feature == thisclass.right.feature,
    ).select(
        left=thisclass.left.node,
        right=thisclass.right.node,
        weight=thisclass.left.weight
        * thisclass.right.weight
        * features_normalized.ix(thisclass.left.feature).weight,
    )
    if symmetric:
        light_pairs = light_pairs.filter(light_pairs.left != light_pairs.right)
    light_pairs = light_pairs.groupby(light_pairs.left, light_pairs.right).reduce(
        light_pairs.left,
        light_pairs.right,
        weight=reducers.sum(light_pairs.weight),
    )

    # heavy features only add weight to pairs the light ones already found
    lh = light_pairs.join(left_heavy, light_pairs.left == left_heavy.node).select(
        left=light_pairs.left,
        right=light_pairs.right,
        feature=left_heavy.feature,
        lw=left_heavy.weight,
    )
    heavy_pairs = lh.join(
        right_heavy,
        lh.right == right_heavy.node,
        lh.feature == right_heavy.feature,
    ).select(
        left=lh.left,
        right=lh.right,
        weight=lh.lw
        * right_heavy.weight
        * features_normalized.ix(lh.feature).weight,
    )

    node_node = light_pairs.concat_reindex(heavy_pairs)
    node_node = node_node.groupby(node_node.left, node_node.right).reduce(
        node_node.left,
        node_node.right,
        weight=reducers.sum(node_node.weight),
    )
    # pseudo-weight makes (w, a, b) and (w, b, a) compare identically, so the
    # two argmax passes agree on symmetric inputs
    node_node = node_node.with_columns(
        weight=expr_mod.if_else(
            node_node.left < node_node.right,
            expr_mod.make_tuple(node_node.weight, node_node.left, node_node.right),
            expr_mod.make_tuple(node_node.weight, node_node.right, node_node.left),
        )
    )

    by_left = node_node.groupby(node_node.left).reduce(
        node_node.left,
        ptr=reducers.argmax(node_node.weight),
        weight=reducers.max(node_node.weight),
    )
    by_left = by_left.select(
        by_left.left, by_left.weight, right=node_node.ix(by_left.ptr).right
    )
    by_right = by_left.groupby(by_left.right).reduce(
        by_left.right,
        ptr=reducers.argmax(by_left.weight),
        weight=reducers.max(by_left.weight),
    )
    matched = by_right.select(
        by_right.right,
        by_right.weight,
        left=by_left.ix(by_right.ptr).left,
    )

    if symmetric:
        matched = matched.filter(matched.left < matched.right)

    result = matched.select(
        matched.left,
        matched.right,
        weight=expr_mod.GetExpression(matched.weight, 0, check_if_exists=False),
    )
    if by_hand_match is not None:
        result = result.concat_reindex(by_hand_match)
    return result


def smart_fuzzy_join(left, right, left_column=None, right_column=None, **kwargs):
    """Back-compat convenience wrapper: fuzzy-match a column of each table
    (defaults to the first column); returns (left, right, weight) rows."""
    lcol = left_column if left_column is not None else left[left.column_names()[0]]
    rcol = right_column if right_column is not None else right[right.column_names()[0]]
    return smart_fuzzy_match(lcol, rcol, **kwargs)
