"""Hidden Markov Model decoding as a stateful reducer.

Reference surface: ``stdlib/ml/hmm.py`` ``create_hmm_reducer(graph,
beam_size, num_results_kept)`` — a reducer that Viterbi-decodes the most
likely hidden-state sequence from a stream of observations grouped per key.

The HMM is described as a ``networkx.DiGraph``: each node carries a
``calc_emission_log_ppb(observation) -> float`` attribute and each edge a
``log_transition_ppb`` weight. The engine's stateful reducer replays the
group's *multiset* of rows on every consolidation (retraction-safe but
order-free: duplicates are netted to counts), so for a meaningful sequence
pass an explicit ordering column as the second reducer argument —
``reducer(this.observation, this.t)`` — and the decode sorts by it. With a
single argument the replay order groups equal observations together.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import reducers as reducers_mod


def create_hmm_reducer(graph, beam_size: int | None = None,
                       num_results_kept: int | None = None):
    """Build a reducer decoding the HMM state sequence from observations.

    Args:
        graph: ``networkx.DiGraph`` whose nodes have a
            ``calc_emission_log_ppb`` callable attribute and whose edges have
            ``log_transition_ppb`` weights.
        beam_size: keep only the best ``beam_size`` states per step
            (beam search); None = exact Viterbi over all states.
        num_results_kept: truncate the decoded sequence to its most recent
            ``num_results_kept`` states; None = keep all.

    Returns a reducer usable in ``groupby(...).reduce(
    decoded=reducer(this.observation, this.t))``; the value is a tuple of
    decoded states, most recent last. The second (ordering) argument is
    optional but required for correct sequencing when the same observation
    value can recur non-consecutively.
    """
    states = list(graph.nodes)
    emission = {
        s: graph.nodes[s]["calc_emission_log_ppb"] for s in states
    }
    # incoming transitions per target state
    incoming: dict[Any, list[tuple[Any, float]]] = {s: [] for s in states}
    for u, v, data in graph.edges(data=True):
        incoming[v].append((u, float(data["log_transition_ppb"])))

    def decode(_state, rows):
        entries: list[tuple] = []
        for args, count in rows:
            for _ in range(count):
                entries.append(args)
        if not entries:
            return ()
        if entries and len(entries[0]) > 1:  # (observation, order_key)
            entries.sort(key=lambda a: a[1])
        observations = [a[0] for a in entries]

        # Viterbi with optional beam pruning; log-probs, paths per state
        logp: dict[Any, float] = {}
        path: dict[Any, tuple] = {}
        first = observations[0]
        for s in states:
            logp[s] = float(emission[s](first))
            path[s] = (s,)
        for obs in observations[1:]:
            new_logp: dict[Any, float] = {}
            new_path: dict[Any, tuple] = {}
            for v in states:
                best = None
                best_u = None
                for u, w in incoming[v]:
                    lp = logp.get(u)
                    if lp is None:
                        continue
                    cand = lp + w
                    if best is None or cand > best:
                        best, best_u = cand, u
                if best is None:
                    continue
                new_logp[v] = best + float(emission[v](obs))
                new_path[v] = path[best_u] + (v,)
            if not new_logp:  # no reachable state: restart from this obs
                for s in states:
                    new_logp[s] = float(emission[s](obs))
                    new_path[s] = (s,)
            if beam_size is not None and len(new_logp) > beam_size:
                kept = sorted(new_logp, key=new_logp.get, reverse=True)
                kept = kept[:beam_size]
                new_logp = {s: new_logp[s] for s in kept}
                new_path = {s: new_path[s] for s in kept}
            logp, path = new_logp, new_path

        best_state = max(logp, key=logp.get)
        decoded = path[best_state]
        if num_results_kept is not None:
            decoded = decoded[-num_results_kept:]
        return decoded

    return reducers_mod.stateful_many(decode)
