"""Dataset helpers (reference ``stdlib/ml/datasets/classification``:
``load_mnist_sample``/``load_mnist_stream``).

The reference fetches MNIST from OpenML.  In air-gapped environments this
module falls back to a deterministic synthetic stand-in with the same shape
contract (784-dim float vectors in [0, 1], string digit labels, 6:1
train/test split) so pipelines and tests remain runnable offline.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from pathway_tpu.debug import table_from_pandas


def _synthetic_mnist(n: int, seed: int = 0):
    """Ten well-separated Gaussian blobs in 784-d, mimicking MNIST's shape."""
    gen = np.random.default_rng(seed)
    centers = gen.random((10, 784))
    labels = gen.integers(0, 10, size=n)
    X = np.clip(centers[labels] + gen.normal(0, 0.08, size=(n, 784)), 0.0, 1.0)
    y = labels.astype(str)
    return X, y


def _fetch_mnist(sample_size: int):
    try:
        from sklearn.datasets import fetch_openml

        X, y = fetch_openml("mnist_784", version=1, return_X_y=True, as_frame=False)
        return X / 255.0, y
    except Exception:
        import warnings

        warnings.warn(
            "MNIST download unavailable (no network); using a deterministic "
            "synthetic stand-in with the same shape contract.",
            stacklevel=3,
        )
        return _synthetic_mnist(max(sample_size, 7000))


def load_mnist_sample(sample_size: int = 70000):
    """Return (X_train, y_train, X_test, y_test) tables with columns
    ``data`` (784-dim vector) / ``label`` (str), split 6:1."""
    X, y = _fetch_mnist(sample_size)
    n = min(sample_size, len(X))
    train_size = int(n * 6 / 7)
    test_size = n - train_size
    X_train, y_train = X[:train_size], y[:train_size]
    X_test, y_test = X[train_size:train_size + test_size], y[train_size:train_size + test_size]

    def vec_table(mat):
        # list(mat) yields row views without boxing every float
        return table_from_pandas(pd.DataFrame({"data": list(mat)}))

    def label_table(labels):
        return table_from_pandas(pd.DataFrame({"label": labels.tolist()}))

    return (
        vec_table(X_train),
        label_table(y_train),
        vec_table(X_test),
        label_table(y_test),
    )


load_mnist_stream = load_mnist_sample


def load_mnist(*args, **kwargs):
    return load_mnist_sample(*args, **kwargs)
