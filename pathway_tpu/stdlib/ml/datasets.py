"""Dataset helpers (reference ``stdlib/ml/datasets``) — loaders for local
files; remote fetching requires network access and raises."""

from __future__ import annotations


def load_mnist(*args, **kwargs):
    raise NotImplementedError("dataset download requires network access")
