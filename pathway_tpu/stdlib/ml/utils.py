"""ML helper utilities (reference ``stdlib/ml/utils.py``)."""

from __future__ import annotations

from pathway_tpu.internals import reducers


def classifier_accuracy(predicted_labels, exact_labels):
    """Tally predicted-vs-exact label matches: returns a table grouped by
    the boolean ``match`` with counts (reference ``ml/utils.py:13``)."""
    predicted_labels.promise_universe_is_subset_of(exact_labels)
    comparative = predicted_labels.select(
        predicted_label=predicted_labels.predicted_label,
        label=exact_labels.restrict(predicted_labels).label,
    )
    comparative = comparative + comparative.select(
        match=comparative.label == comparative.predicted_label
    )
    accuracy = comparative.groupby(comparative.match).reduce(
        cnt=reducers.count(),
        value=comparative.match,
    )
    return accuracy
