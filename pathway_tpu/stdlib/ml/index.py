"""Legacy ``KNNIndex`` API (reference ``stdlib/ml/index.py``: KNNIndex:9,
get_nearest_items:54, get_nearest_items_asof_now:194).

The reference implements this with LSH bucketing in pure dataflow; here it
delegates to the TPU brute-force index (exact, faster on this hardware) while
keeping the public API: queries/data as vector columns, results collapsed.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import BruteForceKnn


class KNNIndex:
    def __init__(
        self,
        data_embedding: expr_mod.ColumnReference,
        data: Any,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: expr_mod.ColumnReference | None = None,
    ):
        metric = "l2sq" if distance_type == "euclidean" else "cos"
        self._inner = BruteForceKnn(
            data_embedding,
            metadata,
            dimensions=n_dimensions,
            metric=metric,
        )
        self._index = DataIndex(data, self._inner)

    def get_nearest_items(
        self,
        query_embedding: expr_mod.ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: expr_mod.ColumnExpression | None = None,
    ):
        return self._index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            with_distances=with_distances,
            metadata_filter=metadata_filter,
        )

    def get_nearest_items_asof_now(
        self,
        query_embedding: expr_mod.ColumnReference,
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: expr_mod.ColumnExpression | None = None,
    ):
        return self._index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            with_distances=with_distances,
            metadata_filter=metadata_filter,
        )
