"""LSH-based kNN classifiers (reference ``stdlib/ml/classifiers/``:
``_lsh.py``, ``_knn_lsh.py:63-325``, ``_clustering_via_lsh.py:31``).

The classifier keeps the reference's public API — ``knn_lsh_classifier_train``
returns a query callable ``(queries, k, with_distances) -> Table`` — but the
dataflow shape is our own: instead of materialising ``L`` per-band candidate
columns and merging them with ``update_rows``, both sides flatten their bucket
vectors to ``(band, bucket)`` rows and meet in a single join, with candidate
sets collected by one groupby.  Distances for the (small) candidate sets are
computed host-side per query; the exact TPU path is ``stdlib/ml/index.KNNIndex``.
"""

from __future__ import annotations

import builtins
from collections import Counter
from typing import Literal

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.schema import Schema
from pathway_tpu.stdlib.ml._lsh import (
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
    lsh,
)
from pathway_tpu.stdlib.ml.index import KNNIndex
from pathway_tpu.stdlib.utils.col import groupby_reduce_majority

DistanceTypes = Literal["euclidean", "cosine"]


class DataPoint(Schema):
    data: np.ndarray


class MetaDataSchema(Schema):
    metadata: dict


def _euclidean_distance(data_table: np.ndarray, query_point: np.ndarray) -> np.ndarray:
    return np.sum((data_table - query_point) ** 2, axis=1).astype(float)


def compute_cosine_dist(data_table: np.ndarray, query_point: np.ndarray) -> np.ndarray:
    return 1 - np.dot(data_table, query_point) / (
        np.linalg.norm(data_table, axis=1) * np.linalg.norm(query_point)
    )


def _metadata_matches(flt, metadata) -> bool:
    if flt is None:
        return True
    from pathway_tpu.engine.operators.external_index import _eval_jmespath_subset

    try:
        doc = metadata.value if hasattr(metadata, "value") else metadata
        return bool(_eval_jmespath_subset(flt, doc))
    except Exception:
        return False


def knn_lsh_generic_classifier_train(data, lsh_projection, distance_function, L: int):
    """Index ``data.data`` under ``lsh_projection``; return a query callable.

    ``L`` is accepted for reference-API parity only: the bucketer already
    encodes its band count in the vectors it emits.

    Both data and queries flatten their ``L``-band bucket vectors into
    ``(band_index, bucket_id)`` rows; a single equi-join pairs queries with
    data rows sharing any band bucket, and a groupby per query collects the
    candidate set for the host-side distance + top-k step.
    """
    has_metadata = "metadata" in data.column_names()

    def bucket_rows(table):
        tagged = table.select(
            buckets=expr_mod.apply(
                lambda x: [(i, int(b)) for i, b in enumerate(lsh_projection(x))],
                table.data,
            )
        )
        flat = tagged.flatten(tagged.buckets, origin_id="origin_id")
        return flat.select(
            flat.origin_id,
            band=expr_mod.GetExpression(flat.buckets, 0, check_if_exists=False),
            bucket=expr_mod.GetExpression(flat.buckets, 1, check_if_exists=False),
        )

    data_buckets = bucket_rows(data)

    def lsh_perform_query(queries, k=None, with_distances: bool = False):
        if k is not None:
            queries += queries.select(k=k)
        has_filter = "metadata_filter" in queries.column_names()

        query_buckets = bucket_rows(queries)
        matched = query_buckets.join(
            data_buckets,
            query_buckets.band == data_buckets.band,
            query_buckets.bucket == data_buckets.bucket,
        ).select(
            query_id=query_buckets.origin_id,
            data_id=data_buckets.origin_id,
        )
        grouped = matched.groupby(matched.query_id).reduce(
            matched.query_id,
            ids=reducers.sorted_tuple(matched.data_id),
        )
        candidates = grouped.select(
            grouped.query_id,
            ids=expr_mod.apply_with_type(
                lambda t: builtins.tuple(dict.fromkeys(t)), dt.ANY, grouped.ids
            ),
        )

        def knns(querypoint, ids_tuple, k, metadata_filter, vectors, metadatas):
            # ids are already deduplicated upstream (dict.fromkeys per query)
            cand_ids, cand_vecs = [], []
            for cid, vec, md in zip(ids_tuple, vectors, metadatas):
                if _metadata_matches(metadata_filter, md):
                    cand_ids.append(cid)
                    cand_vecs.append(vec)
            if not cand_ids:
                return []
            dists = distance_function(np.array(cand_vecs), np.asarray(querypoint))
            neighs = min(int(k), len(cand_ids))
            order = np.argsort(dists, kind="stable")[:neighs]
            return [(cand_ids[i], float(dists[i])) for i in order]

        flat_cand = candidates.flatten(candidates.ids)
        flat_cand += flat_cand.select(
            vec=data.ix(flat_cand.ids).data,
            md=(data.ix(flat_cand.ids).metadata if has_metadata else None),
        )
        gathered = flat_cand.groupby(flat_cand.query_id).reduce(
            flat_cand.query_id,
            ids=reducers.tuple(flat_cand.ids),
            vectors=reducers.tuple(flat_cand.vec),
            metadatas=reducers.tuple(flat_cand.md),
        )

        joined = queries.join_left(gathered, queries.id == gathered.query_id).select(
            query_id=queries.id,
            data=queries.data,
            k=queries.k,
            metadata_filter=(queries.metadata_filter if has_filter else None),
            ids=expr_mod.coalesce(gathered.ids, ()),
            vectors=expr_mod.coalesce(gathered.vectors, ()),
            metadatas=expr_mod.coalesce(gathered.metadatas, ()),
        )
        knn_result = joined.select(
            joined.query_id,
            knns_ids_with_dists=expr_mod.apply_with_type(
                lambda qp, ids_t, kk, mf, vecs, mds: (
                    knns(qp, ids_t, kk, mf, vecs, mds) if ids_t else []
                ),
                dt.ANY,
                joined.data,
                joined.ids,
                joined.k,
                joined.metadata_filter,
                joined.vectors,
                joined.metadatas,
            ),
        )
        if not with_distances:
            knn_result = knn_result.select(
                knn_result.query_id,
                knns_ids=expr_mod.apply_with_type(
                    lambda pairs: tuple(p[0] for p in pairs),
                    dt.ANY,
                    knn_result.knns_ids_with_dists,
                ),
            )
        return knn_result

    return lsh_perform_query


def knn_lsh_classifier_train(
    data, L: int, type: DistanceTypes = "euclidean", **kwargs  # noqa: A002
):
    """Build an LSH index over ``data``; dispatches on distance type.
    Reference ``_knn_lsh.py:63``."""
    if type == "euclidean":
        projection = generate_euclidean_lsh_bucketer(
            kwargs["d"], kwargs["M"], L, kwargs["A"]
        )
        return knn_lsh_generic_classifier_train(
            data, projection, _euclidean_distance, L
        )
    elif type == "cosine":
        projection = generate_cosine_lsh_bucketer(kwargs["d"], kwargs["M"], L)
        return knn_lsh_generic_classifier_train(data, projection, compute_cosine_dist, L)
    raise ValueError(
        f"Not supported `type` {type} in knn_lsh_classifier_train. "
        "The allowed values are 'euclidean' and 'cosine'."
    )


def knn_lsh_euclidean_classifier_train(data, d, M, L, A):
    """Euclidean-distance LSH index (reference ``_knn_lsh.py:293``)."""
    projection = generate_euclidean_lsh_bucketer(d, M, L, A)
    return knn_lsh_generic_classifier_train(data, projection, _euclidean_distance, L)


def knn_lsh_classify(knn_model, data_labels, queries, k):
    """Label queries by majority vote over the ``k`` nearest data points
    (reference ``_knn_lsh.py:306``)."""
    knns = knn_model(queries, k)
    votes = knns.flatten(knns.knns_ids)
    votes += votes.select(label=data_labels.ix(votes.knns_ids).label)
    nonempty = votes.groupby(votes.query_id).reduce(
        votes.query_id,
        predicted_label=expr_mod.apply_with_type(
            lambda ls: Counter(ls).most_common(1)[0][0],
            dt.ANY,
            reducers.tuple(votes.label),
        ),
    )
    rekeyed = nonempty.with_id(nonempty.query_id)
    nonempty = rekeyed.select(rekeyed.predicted_label)
    empty = queries.select(predicted_label=None)
    return empty.update_cells(nonempty.promise_universe_is_subset_of(empty))


# Back-compat aliases kept from the first cut of this module.
knn_lsh_train = knn_lsh_classifier_train


class Label:
    """API-parity marker (reference ``_clustering_via_lsh.py:Label``) — the
    label column contract of ``clustering_via_lsh`` output; not a Schema."""

    label: int


def np_divide(data: np.ndarray, other: float) -> np.ndarray:
    return data / other


def clustering_via_lsh(data, bucketer, k: int):
    """(Pre)clustering via LSH (reference ``_clustering_via_lsh.py:31``):
    bucket representatives (weighted means) are k-means-clustered on the TPU
    (``ops/ivf.kmeans_fit``), then every row takes the majority label over
    the buckets it fell into."""
    import jax.numpy as jnp

    from pathway_tpu.ops.ivf import kmeans_fit
    from pathway_tpu.stdlib.utils.col import apply_all_rows

    flat_data = lsh(data, bucketer, origin_id="data_id", include_data=True)

    reduced = flat_data.groupby(flat_data.bucketing, flat_data.band).reduce(
        flat_data.bucketing,
        flat_data.band,
        sum=reducers.npsum(flat_data.data),
        count=reducers.count(),
    )
    representatives = reduced.select(
        reduced.bucketing,
        reduced.band,
        data=expr_mod.apply(np_divide, reduced.sum, reduced.count),
        weight=reduced.count,
    )

    def clustering(vectors, weights):
        arr = jnp.asarray(np.array(vectors, dtype=np.float32))
        w = np.asarray(weights, dtype=np.float32)
        # initialise centroids at the k heaviest representatives
        init = arr[np.argsort(-w)[:k]]
        if init.shape[0] < k:
            reps = -(-k // max(init.shape[0], 1))
            init = jnp.tile(init, (reps, 1))[:k]
        centroids = kmeans_fit(arr, init)
        d2 = (
            jnp.sum(arr * arr, axis=1, keepdims=True)
            + jnp.sum(centroids * centroids, axis=1)[None, :]
            - 2.0 * arr @ centroids.T
        )
        return [int(x) for x in np.asarray(jnp.argmin(d2, axis=1))]

    labels = apply_all_rows(
        representatives.data,
        representatives.weight,
        fun=clustering,
        result_col_name="label",
    )
    representatives += labels
    votes = flat_data.join(
        representatives,
        flat_data.bucketing == representatives.bucketing,
        flat_data.band == representatives.band,
    ).select(
        flat_data.data_id,
        representatives.label,
    )

    result = groupby_reduce_majority(votes.data_id, votes.label)
    return result.select(label=result.majority).with_id(result.data_id)


def knn_classifier(data, labels, queries, k: int = 3, *, n_dimensions: int = 0,
                   distance_type: str = "euclidean"):
    """Exact TPU-backed classification: brute-force KNN on device + majority
    vote (the fast path this framework prefers over LSH approximation).
    ``labels`` must share ``data``'s universe; its label column is joined
    onto the index rows so each neighborhood carries its labels."""
    label_name = (
        labels.column_names()[0] if hasattr(labels, "column_names") else "label"
    )
    combined = data + labels.select(**{label_name: labels[label_name]})
    index = KNNIndex(combined.data, combined, n_dimensions=n_dimensions,
                     distance_type=distance_type)
    neighbors = index.get_nearest_items(queries.data, k=k)

    def majority(ls):
        if not ls:
            return None
        return Counter(ls).most_common(1)[0][0]

    return neighbors.select(
        predicted_label=expr_mod.apply_with_type(majority, dt.ANY, neighbors[label_name])
    )
