"""KNN classifiers (reference ``stdlib/ml/classifiers.py`` — LSH-based
kNN voting). Voting over the TPU KNN index results."""

from __future__ import annotations

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.stdlib.ml.index import KNNIndex


def knn_lsh_classifier_train(data, L: int = 20, type: str = "euclidean", **kwargs):  # noqa: A002
    """Returns a classify(queries, k, labels) callable (API parity)."""
    n_dim = kwargs.get("d", kwargs.get("n_dimensions"))

    def classify(queries_embedding, labels_column, k: int = 3):
        index = KNNIndex(
            kwargs["data_embedding"] if "data_embedding" in kwargs else data.data,
            data,
            n_dimensions=n_dim or 0,
            distance_type="euclidean" if type == "euclidean" else "cosine",
        )
        neighbors = index.get_nearest_items(queries_embedding, k=k)
        label_name = labels_column.name

        def majority(labels):
            from collections import Counter

            if not labels:
                return None
            return Counter(labels).most_common(1)[0][0]

        return neighbors.select(
            predicted_label=expr_mod.apply_with_type(
                majority, dt.ANY, neighbors[label_name]
            )
        )

    return classify


knn_lsh_train = knn_lsh_classifier_train


def knn_lsh_classify(classifier, *args, **kwargs):
    return classifier(*args, **kwargs)
