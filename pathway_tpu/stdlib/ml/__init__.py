"""``pw.ml`` — classic ML utilities (reference ``stdlib/ml/``): the legacy
``KNNIndex`` API (``ml/index.py``), classifiers, smart-table fuzzy join."""

from pathway_tpu.stdlib.ml import (
    classifiers,
    datasets,
    hmm,
    index,
    smart_table_ops,
    utils,
)
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = [
    "KNNIndex",
    "classifiers",
    "datasets",
    "hmm",
    "index",
    "smart_table_ops",
    "utils",
]
