"""``pw.temporal`` — event-time windows, temporal joins, behaviors.

Parity with reference ``python/pathway/stdlib/temporal/``:
windows (``tumbling``, ``sliding``, ``session``, ``intervals_over``) +
``windowby``; ``interval_join`` / ``asof_join`` / ``asof_now_join`` /
``window_join``; behaviors (``common_behavior``, ``exactly_once_behavior``)
lowered to the engine's buffer/forget/freeze operators.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.engine.operators import core as core_ops
from pathway_tpu.engine.operators.instance_recompute import InstanceRecomputeNode
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import substitute
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.universe import Universe

__all__ = [
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "windowby",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "asof_now_join",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
    "asof_now_join_inner",
    "asof_now_join_left",
    "common_behavior",
    "exactly_once_behavior",
    "apply_temporal_behavior",
    "Behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "Direction",
    "utils",
    "time_utils",
    "inactivity_detection",
    "utc_now",
]

from pathway_tpu.stdlib.temporal import time_utils, utils  # noqa: E402  (cycle-safe tail imports)
from pathway_tpu.stdlib.temporal.time_utils import (  # noqa: E402
    inactivity_detection,
    utc_now,
)


# ---------------------------------------------------------------------------
# behaviors


class Behavior:
    """Superclass of temporal behaviors (reference
    ``temporal_behavior.py:Behavior``)."""


@dataclass
class CommonBehavior(Behavior):
    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(delay=None, cutoff=None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any = None


def exactly_once_behavior(shift=None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)


# ---------------------------------------------------------------------------
# window definitions


class Window:
    pass


@dataclass
class TumblingWindow(Window):
    duration: Any
    origin: Any = None

    def assign(self, t):
        origin = self.origin if self.origin is not None else _zero_like(t)
        idx = _floor_div(t - origin, self.duration)
        start = origin + idx * self.duration
        return [(start, start + self.duration)]


@dataclass
class SlidingWindow(Window):
    hop: Any
    duration: Any
    origin: Any = None

    def assign(self, t):
        origin = self.origin if self.origin is not None else _zero_like(t)
        out = []
        # windows [s, s+duration) with s = origin + k*hop containing t
        k_max = _floor_div(t - origin, self.hop)
        k = k_max
        while True:
            start = origin + k * self.hop
            if start + self.duration <= t:
                break
            out.append((start, start + self.duration))
            k -= 1
        return list(reversed(out))


@dataclass
class SessionWindow(Window):
    predicate: Callable | None = None
    max_gap: Any = None


def tumbling(duration=None, origin=None, **kwargs) -> TumblingWindow:
    return TumblingWindow(duration, origin)


def sliding(hop=None, duration=None, origin=None, ratio=None, **kwargs) -> SlidingWindow:
    # validate eagerly: a hopless/durationless window would otherwise fail
    # with an opaque TypeError deep inside window assignment (or silently
    # assign zero windows)
    if hop is None:
        raise ValueError("sliding() requires hop (optionally with ratio)")
    if duration is None and ratio is not None:
        duration = hop * ratio
    if duration is None:
        raise ValueError("sliding() requires duration or ratio")
    return SlidingWindow(hop, duration, origin)


def session(predicate=None, max_gap=None) -> SessionWindow:
    return SessionWindow(predicate, max_gap)


@dataclass
class IntervalsOverWindow(Window):
    at: Any
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def intervals_over(*, at, lower_bound, upper_bound, is_outer: bool = True):
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


def _zero_like(t):
    import pandas as pd

    if isinstance(t, pd.Timestamp):
        ts = pd.Timestamp(0)
        return ts.tz_localize("UTC") if t.tzinfo is not None else ts
    if isinstance(t, float):
        return 0.0
    return 0


def _floor_div(delta, step) -> int:
    import pandas as pd

    if isinstance(delta, pd.Timedelta):
        return int(delta.value // pd.Timedelta(step).value)
    return math.floor(delta / step)


# ---------------------------------------------------------------------------
# windowby


class WindowGroupedTable:
    """Result of windowby: reduce() aggregates per (instance, window)."""

    def __init__(self, table, time_expr, window: Window, behavior, instance):
        self._table = table
        self._time_expr = time_expr
        self._window = window
        self._behavior = behavior
        self._instance = instance

    def reduce(self, *args, **kwargs):
        from pathway_tpu.internals.table import Table

        table = self._table
        window = self._window
        if isinstance(window, SessionWindow):
            tagged = _session_tag_table(
                table, self._time_expr, window, self._instance
            )
        else:
            win = window

            def windows_of(t):
                if t is None:
                    return ()
                return tuple(win.assign(t))

            with_windows = table.with_columns(
                __windows=expr_mod.apply_with_type(
                    windows_of, dt.ANY_TUPLE, self._time_expr
                ),
                __winst=(
                    self._instance
                    if self._instance is not None
                    else expr_mod.ColumnConstExpression(None)
                ),
            )
            flat = with_windows.flatten(with_windows["__windows"])
            tagged = flat.with_columns(
                _pw_window_start=flat["__windows"].get(0),
                _pw_window_end=flat["__windows"].get(1),
                _pw_window=expr_mod.make_tuple(
                    flat["__winst"],
                    flat["__windows"].get(0),
                    flat["__windows"].get(1),
                ),
            )
        # reference parity: the grouped view exposes the colocation key as
        # ``_pw_instance`` alongside the window columns
        inst_src = "__inst" if isinstance(window, SessionWindow) else "__winst"
        tagged = tagged.with_columns(_pw_instance=tagged[inst_src])
        # apply behavior: delay/cutoff on window end vs time column.
        # Lateness operators (freeze/forget) must see the RAW stream: their
        # watermark is derived from observed rows, and a buffer placed before
        # them would lag it — late rows released together with the buffered
        # batch would sneak past the cutoff (the reference's time_column
        # operators share the timely frontier, so order doesn't matter there).
        if self._behavior is not None and isinstance(self._behavior, CommonBehavior):
            b = self._behavior
            tagged = _ensure_time_col(tagged, self._time_expr)
            if b.cutoff is not None:
                if b.keep_results:
                    tagged = tagged._freeze(
                        tagged._pw_window_end + b.cutoff, tagged["__time_value"]
                    )
                else:
                    tagged = tagged._forget(
                        tagged._pw_window_end + b.cutoff, tagged["__time_value"]
                    )
            if b.delay is not None:
                tagged = tagged._buffer(
                    tagged._pw_window_start + b.delay, tagged["__time_value"]
                )
        elif self._behavior is not None and isinstance(self._behavior, ExactlyOnceBehavior):
            shift = self._behavior.shift
            tagged = _ensure_time_col(tagged, self._time_expr)
            thr = (
                tagged._pw_window_end + shift
                if shift is not None
                else tagged._pw_window_end
            )
            tagged = tagged._freeze(thr, tagged["__time_value"])
            tagged = tagged._buffer(thr, tagged["__time_value"])

        grouped = tagged.groupby(
            tagged._pw_window,
            sort_by=None,
        )
        # substitute special refs in reduce args (_window_meta_rewrite maps
        # the _pw_* meta columns to any(...) reducers)
        new_kwargs = {}
        from pathway_tpu.internals import reducers as red_mod

        instance_name = (
            self._instance.name
            if isinstance(self._instance, ColumnReference)
            else None
        )
        for name, e in _named_reduce_args(args, kwargs).items():
            e = expr_mod.smart_coerce(e)
            e = substitute(e, {thisclass.this: tagged})
            new_kwargs[name] = _window_meta_rewrite(e, tagged, instance_name)
        result = grouped.reduce(**new_kwargs)
        return result


def _named_reduce_args(args, kwargs) -> dict:
    """Positional reduce args (column references, e.g. the window-key
    columns) project under their own names, like ``Table.reduce``."""
    named = {}
    for a in args:
        if not isinstance(a, ColumnReference):
            raise ValueError(
                "positional windowby(...).reduce arguments must be column "
                "references; use keyword arguments for computed values"
            )
        if a.name in named or a.name in kwargs:
            raise ValueError(f"duplicate reduce column {a.name!r}")
        named[a.name] = a
    named.update(kwargs)
    return named


def _window_meta_rewrite(e, tagged, instance_name=None):
    """Map refs that are constant within a window group — the _pw_window*
    meta columns and the instance column — to `any(...)` reducers."""
    from pathway_tpu.internals import reducers as red_mod

    if isinstance(e, ColumnReference):
        constant_cols = (
            "_pw_window_start", "_pw_window_end", "_pw_window", "_pw_instance",
        )
        if e.name in constant_cols or (
            instance_name is not None and e.name == instance_name
        ):
            return red_mod.any(tagged[e.name])
        return e
    import copy

    e = copy.copy(e)
    for attr in ("_left", "_right", "_expr", "_if", "_then", "_else"):
        if hasattr(e, attr):
            v = getattr(e, attr)
            if isinstance(v, ColumnExpression):
                setattr(e, attr, _window_meta_rewrite(v, tagged))
    if hasattr(e, "_args") and not isinstance(e, expr_mod.ReducerExpression):
        e._args = tuple(
            _window_meta_rewrite(a, tagged) if isinstance(a, ColumnExpression) else a
            for a in e._args
        )
    return e


def _ensure_time_col(tagged, time_expr):
    if "__time_value" in tagged.column_names():
        return tagged
    if isinstance(time_expr, ColumnReference) and time_expr.name in tagged.column_names():
        return tagged.with_columns(__time_value=tagged[time_expr.name])
    return tagged.with_columns(__time_value=tagged._pw_window_end)


def _merge_sessions(entries, time_of, predicate, max_gap):
    """THE session-merge rule (shared by windowby sessions and session
    window joins so the two can never drift): entries are time-sorted;
    adjacent entries merge when ``predicate(prev_t, next_t)`` (or
    ``next_t - prev_t <= max_gap``)."""
    sessions: list[list] = [[entries[0]]]
    for prev, nxt in zip(entries, entries[1:]):
        pt, nt = time_of(prev), time_of(nxt)
        if predicate is not None:
            merge = predicate(pt, nt)
        else:
            merge = (nt - pt) <= max_gap
        if merge:
            sessions[-1].append(nxt)
        else:
            sessions.append([nxt])
    return sessions


def _session_tag_table(table, time_expr, window: SessionWindow, instance):
    """Tag rows with merged session windows per instance."""
    from pathway_tpu.internals.table import Table, _prepare_env

    exprs = {
        "__t": time_expr,
        "__inst": (
            instance if instance is not None else expr_mod.ColumnConstExpression(None)
        ),
        **{n: ColumnReference(table, n) for n in table.column_names()},
    }
    env, rw = _prepare_env(table, exprs)
    prep = core_ops.RowwiseNode(G.engine_graph, env, rw)
    in_cols = prep.column_names
    ti = in_cols.index("__t")
    max_gap = window.max_gap
    predicate = window.predicate
    out_cols = list(in_cols) + ["_pw_window_start", "_pw_window_end", "_pw_window"]

    def compute(inst, rows):
        entries = sorted(rows.items(), key=lambda kv: (kv[1][ti], kv[0]))
        out: dict[int, tuple] = {}
        if not entries:
            return out
        sessions = _merge_sessions(
            entries, lambda e: e[1][ti], predicate, max_gap
        )
        for sess in sessions:
            start = sess[0][1][ti]
            end = sess[-1][1][ti]
            wid = (inst, start, end)
            for key, row in sess:
                out[key] = tuple(row) + (start, end, wid)
        return out

    node = InstanceRecomputeNode(
        G.engine_graph,
        [prep],
        ["__inst"],
        out_cols,
        lambda inst, rows: compute(inst, rows),
        name="SessionWindows",
    )
    defs = dict(table._schema.__columns__)
    schema = schema_mod.schema_builder_from_definitions(
        {
            **{
                n: schema_mod.ColumnDefinition(
                    dtype=(
                        defs[n].dtype if n in defs else dt.ANY
                    ),
                    name=n,
                )
                for n in in_cols
            },
            "_pw_window_start": schema_mod.ColumnDefinition(dtype=dt.ANY),
            "_pw_window_end": schema_mod.ColumnDefinition(dtype=dt.ANY),
            "_pw_window": schema_mod.ColumnDefinition(dtype=dt.ANY),
        }
    )
    return Table(node, schema, Universe())


def windowby(table, time_expr, *, window: Window, behavior=None, instance=None, **kwargs):
    time_expr = substitute(time_expr, {thisclass.this: table})
    if instance is not None:
        instance = substitute(
            expr_mod.smart_coerce(instance), {thisclass.this: table}
        )
    if isinstance(window, IntervalsOverWindow):
        return _intervals_over_grouped(table, time_expr, window, instance)
    return WindowGroupedTable(table, time_expr, window, behavior, instance)


def _intervals_over_grouped(table, time_expr, window: IntervalsOverWindow, instance):
    """intervals_over: for each value in `at`, aggregate rows with time in
    [at+lower, at+upper]."""

    class _IntervalsGrouped:
        def reduce(self_inner, *args, **kwargs):
            from pathway_tpu.internals.table import Table, _prepare_env

            at_col = window.at
            at_table = at_col.table if isinstance(at_col, ColumnReference) else table
            # left: data rows; right: at-points; both keyed by shared instance
            exprs = {
                "__t": time_expr,
                "__inst": (
                    instance
                    if instance is not None
                    else expr_mod.ColumnConstExpression(None)
                ),
                **{n: ColumnReference(table, n) for n in table.column_names()},
            }
            env, rw = _prepare_env(table, exprs)
            data_prep = core_ops.RowwiseNode(G.engine_graph, env, rw)
            at_exprs = {
                "__at": at_col,
                "__inst": expr_mod.ColumnConstExpression(None),
            }
            env2, rw2 = _prepare_env(at_table, at_exprs)
            at_prep = core_ops.RowwiseNode(G.engine_graph, env2, rw2)
            in_cols = data_prep.column_names
            ti = in_cols.index("__t")
            lower, upper = window.lower_bound, window.upper_bound
            out_cols = list(in_cols) + ["_pw_window", "_pw_window_location"]

            def compute(inst, data_rows, at_rows):
                out: dict[int, tuple] = {}
                ats = {row[0] for row in at_rows.values()}
                for at in ats:
                    lo, hi = at + lower, at + upper
                    wid = (inst, at)
                    members = [
                        (k, row)
                        for k, row in data_rows.items()
                        if lo <= row[ti] <= hi
                    ]
                    if not members and not window.is_outer:
                        continue
                    if not members:
                        k = hash_values(inst, at, "empty")
                        out[k] = tuple(
                            None for _ in in_cols
                        ) + (wid, at)
                        continue
                    for k, row in members:
                        out[hash_values(k, at)] = tuple(row) + (wid, at)
                return out

            node = InstanceRecomputeNode(
                G.engine_graph,
                [data_prep, at_prep],
                ["__inst", "__inst"],
                out_cols,
                compute,
                name="IntervalsOver",
            )
            defs = dict(table._schema.__columns__)
            schema = schema_mod.schema_builder_from_definitions(
                {
                    **{
                        n: schema_mod.ColumnDefinition(
                            dtype=(defs[n].dtype if n in defs else dt.ANY), name=n
                        )
                        for n in in_cols
                    },
                    "_pw_window": schema_mod.ColumnDefinition(dtype=dt.ANY),
                    "_pw_window_location": schema_mod.ColumnDefinition(dtype=dt.ANY),
                }
            )
            tagged = Table(node, schema, Universe())
            grouped = tagged.groupby(tagged._pw_window)
            new_kwargs = {}
            for name, e in _named_reduce_args(args, kwargs).items():
                e = expr_mod.smart_coerce(e)
                e = substitute(e, {thisclass.this: tagged})
                new_kwargs[name] = _window_meta_rewrite_io(e, tagged)
            return grouped.reduce(**new_kwargs)

    return _IntervalsGrouped()


def _window_meta_rewrite_io(e, tagged):
    from pathway_tpu.internals import reducers as red_mod

    if isinstance(e, ColumnReference):
        if e.name in ("_pw_window_location", "_pw_window"):
            return red_mod.any(tagged[e.name])
        return e
    import copy

    e = copy.copy(e)
    if hasattr(e, "_args") and not isinstance(e, expr_mod.ReducerExpression):
        e._args = tuple(
            _window_meta_rewrite_io(a, tagged)
            if isinstance(a, ColumnExpression)
            else a
            for a in e._args
        )
    return e


# ---------------------------------------------------------------------------
# temporal joins


@dataclass
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    return Interval(lower_bound, upper_bound)


class _Direction:
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


def _binary_temporal(
    left_table,
    right_table,
    t_left,
    t_right,
    on,
    how: str,
    compute_factory,
    extra_out_cols: list[str],
    name: str,
):
    """Shared plumbing: prep both sides with (__t, __inst, columns), run an
    InstanceRecomputeNode, expose a JoinResult-like select surface."""
    from pathway_tpu.internals.table import Table, _prepare_env

    t_left = substitute(t_left, {thisclass.this: left_table, thisclass.left: left_table})
    t_right = substitute(t_right, {thisclass.this: right_table, thisclass.right: right_table})
    l_on_exprs = []
    r_on_exprs = []
    for cond in on:
        if not isinstance(cond, expr_mod.ColumnBinaryOpExpression) or cond._operator != "==":
            raise ValueError("temporal join conditions must be equality")
        l_on_exprs.append(
            substitute(cond._left, {thisclass.left: left_table, thisclass.this: left_table})
        )
        r_on_exprs.append(
            substitute(cond._right, {thisclass.right: right_table, thisclass.this: right_table})
        )

    def make_inst(exprs):
        if not exprs:
            return expr_mod.ColumnConstExpression(None)
        if len(exprs) == 1:
            return exprs[0]
        return expr_mod.make_tuple(*exprs)

    lexprs = {
        "__t": t_left,
        "__inst": make_inst(l_on_exprs),
        "__id": ColumnReference(left_table, "id"),
        **{f"__l_{n}": ColumnReference(left_table, n) for n in left_table.column_names()},
    }
    env, rw = _prepare_env(left_table, lexprs)
    lprep = core_ops.RowwiseNode(G.engine_graph, env, rw)
    rexprs = {
        "__t": t_right,
        "__inst": make_inst(r_on_exprs),
        "__id": ColumnReference(right_table, "id"),
        **{f"__r_{n}": ColumnReference(right_table, n) for n in right_table.column_names()},
    }
    env, rw = _prepare_env(right_table, rexprs)
    rprep = core_ops.RowwiseNode(G.engine_graph, env, rw)

    l_cols = lprep.column_names
    r_cols = rprep.column_names
    out_cols = (
        [c for c in l_cols if c.startswith("__l_")]
        + ["__l_id", "__l_t"]
        + [c for c in r_cols if c.startswith("__r_")]
        + ["__r_id", "__r_t"]
        + extra_out_cols
    )
    compute = compute_factory(l_cols, r_cols, out_cols)
    node = InstanceRecomputeNode(
        G.engine_graph, [lprep, rprep], ["__inst", "__inst"], out_cols, compute, name=name
    )
    return _TemporalJoinResult(node, left_table, right_table, how)


class _TemporalJoinResult:
    def __init__(self, node, left_table, right_table, how):
        self._node = node
        self._left = left_table
        self._right = right_table
        self._how = how

    def select(self, *args, **kwargs):
        from pathway_tpu.internals.table import Table

        exprs: dict[str, ColumnExpression] = {}
        for a in args:
            if isinstance(a, thisclass._StarMarker):
                src = a.placeholder
                if src is thisclass.left:
                    for n in self._left.column_names():
                        exprs[n] = ColumnReference(thisclass.left, n)
                elif src is thisclass.right:
                    for n in self._right.column_names():
                        exprs[n] = ColumnReference(thisclass.right, n)
                else:
                    for n in self._left.column_names():
                        exprs[n] = ColumnReference(thisclass.left, n)
                    for n in self._right.column_names():
                        if n not in exprs:
                            exprs[n] = ColumnReference(thisclass.right, n)
            elif isinstance(a, ColumnReference):
                exprs[a.name] = a
            else:
                raise ValueError(f"bad select argument {a!r}")
        for name, e in kwargs.items():
            exprs[name] = expr_mod.smart_coerce(e)

        def rw(e):
            import copy

            if isinstance(e, ColumnReference):
                t = e._table
                if t is thisclass.left or t is self._left:
                    return ColumnReference(
                        None, "__l_id" if e._name == "id" else f"__l_{e._name}"
                    )
                if t is thisclass.right or t is self._right:
                    return ColumnReference(
                        None, "__r_id" if e._name == "id" else f"__r_{e._name}"
                    )
                if t is thisclass.this:
                    if e._name in self._left.column_names():
                        return ColumnReference(None, f"__l_{e._name}")
                    return ColumnReference(None, f"__r_{e._name}")
                return e
            e = copy.copy(e)
            for attr in ("_left", "_right", "_expr", "_if", "_then", "_else",
                         "_val", "_obj", "_index", "_default", "_replacement"):
                if hasattr(e, attr):
                    v = getattr(e, attr)
                    if isinstance(v, ColumnExpression):
                        setattr(e, attr, rw(v))
            if hasattr(e, "_args"):
                e._args = tuple(
                    rw(a) if isinstance(a, ColumnExpression) else a for a in e._args
                )
            return e

        rewritten = {n: rw(e) for n, e in exprs.items()}
        out = core_ops.RowwiseNode(G.engine_graph, self._node, rewritten)
        defs = {}
        for name, orig in exprs.items():
            dtype = dt.ANY
            if isinstance(orig, ColumnReference):
                t = orig._table
                src = None
                if t is thisclass.left or t is self._left:
                    src = self._left
                elif t is thisclass.right or t is self._right:
                    src = self._right
                elif t is thisclass.this:
                    src = (
                        self._left
                        if orig._name in self._left.column_names()
                        else self._right
                    )
                if src is not None and orig._name in src._schema.__columns__:
                    dtype = src._schema.__columns__[orig._name].dtype
                    if self._how != "inner":
                        dtype = dt.Optional(dtype)
            defs[name] = schema_mod.ColumnDefinition(dtype=dtype, name=name)
        schema = schema_mod.schema_builder_from_definitions(defs)
        return Table(out, schema, Universe())


def _null_row(cols, prefix):
    return tuple(None for c in cols if c.startswith(prefix))


def asof_join(
    left_table,
    right_table,
    t_left,
    t_right,
    *on,
    how="inner",
    defaults=None,
    direction="backward",
):
    """For each left row, match the right row closest in time (per direction).

    Reference: ``stdlib/temporal/_asof_join.py:479``.
    """
    if hasattr(how, "value"):
        how = how.value

    def factory(l_cols, r_cols, out_cols):
        lti = l_cols.index("__t")
        lid = l_cols.index("__id")
        rti = r_cols.index("__t")
        rid = r_cols.index("__id")
        l_data = [i for i, c in enumerate(l_cols) if c.startswith("__l_")]
        r_data = [i for i, c in enumerate(r_cols) if c.startswith("__r_")]

        def compute(inst, lrows, rrows):
            out: dict[int, tuple] = {}
            rsorted = sorted(rrows.values(), key=lambda r: (r[rti], r[rid]))
            import bisect

            rtimes = [r[rti] for r in rsorted]
            matched_right = set()
            for lk, lrow in lrows.items():
                t = lrow[lti]
                match = None
                if direction == "backward":
                    i = bisect.bisect_right(rtimes, t) - 1
                    if i >= 0:
                        match = rsorted[i]
                elif direction == "forward":
                    i = bisect.bisect_left(rtimes, t)
                    if i < len(rsorted):
                        match = rsorted[i]
                else:  # nearest
                    i = bisect.bisect_right(rtimes, t) - 1
                    cand = []
                    if i >= 0:
                        cand.append(rsorted[i])
                    if i + 1 < len(rsorted):
                        cand.append(rsorted[i + 1])
                    if cand:
                        match = min(cand, key=lambda r: abs(r[rti] - t))
                if match is None and how == "inner":
                    continue
                lpart = tuple(lrow[i] for i in l_data) + (lrow[lid], lrow[lti])
                if match is not None:
                    matched_right.add(match[rid].value if hasattr(match[rid], "value") else match[rid])
                    rpart = tuple(match[i] for i in r_data) + (
                        match[rid],
                        match[rti],
                    )
                else:
                    rpart = tuple(None for _ in r_data) + (None, None)
                key = lrow[lid].value if hasattr(lrow[lid], "value") else lk
                out[key] = lpart + rpart
            if how in ("right", "outer"):
                for rk, rrow in rrows.items():
                    rid_v = rrow[rid].value if hasattr(rrow[rid], "value") else rk
                    if rid_v in matched_right:
                        continue
                    lpart = tuple(None for _ in l_data) + (None, None)
                    rpart = tuple(rrow[i] for i in r_data) + (rrow[rid], rrow[rti])
                    out[rid_v] = lpart + rpart
            return out

        return compute

    return _binary_temporal(
        left_table, right_table, t_left, t_right, on, how, factory, [], "AsofJoin"
    )


def asof_join_left(l, r, tl, tr, *on, **kw):
    return asof_join(l, r, tl, tr, *on, how="left", **kw)


def asof_join_right(l, r, tl, tr, *on, **kw):
    return asof_join(l, r, tl, tr, *on, how="right", **kw)


def asof_join_outer(l, r, tl, tr, *on, **kw):
    return asof_join(l, r, tl, tr, *on, how="outer", **kw)


def interval_join(
    left_table, right_table, t_left, t_right, interval_: Interval, *on, how="inner"
):
    """Pairs (l, r) with t_right - t_left in [lower, upper] (reference
    ``_interval_join.py:577``)."""
    if hasattr(how, "value"):
        how = how.value
    lower, upper = interval_.lower_bound, interval_.upper_bound

    def factory(l_cols, r_cols, out_cols):
        lti = l_cols.index("__t")
        lid = l_cols.index("__id")
        rti = r_cols.index("__t")
        rid = r_cols.index("__id")
        l_data = [i for i, c in enumerate(l_cols) if c.startswith("__l_")]
        r_data = [i for i, c in enumerate(r_cols) if c.startswith("__r_")]

        def compute(inst, lrows, rrows):
            out: dict[int, tuple] = {}
            matched_l = set()
            matched_r = set()
            for lk, lrow in lrows.items():
                for rk, rrow in rrows.items():
                    delta = rrow[rti] - lrow[lti]
                    if lower <= delta <= upper:
                        matched_l.add(lk)
                        matched_r.add(rk)
                        key = hash_values(lk, rk)
                        out[key] = (
                            tuple(lrow[i] for i in l_data)
                            + (lrow[lid], lrow[lti])
                            + tuple(rrow[i] for i in r_data)
                            + (rrow[rid], rrow[rti])
                        )
            if how in ("left", "outer"):
                for lk, lrow in lrows.items():
                    if lk not in matched_l:
                        out[hash_values(lk, 0)] = (
                            tuple(lrow[i] for i in l_data)
                            + (lrow[lid], lrow[lti])
                            + tuple(None for _ in r_data)
                            + (None, None)
                        )
            if how in ("right", "outer"):
                for rk, rrow in rrows.items():
                    if rk not in matched_r:
                        out[hash_values(0, rk)] = (
                            tuple(None for _ in l_data)
                            + (None, None)
                            + tuple(rrow[i] for i in r_data)
                            + (rrow[rid], rrow[rti])
                        )
            return out

        return compute

    return _binary_temporal(
        left_table, right_table, t_left, t_right, on, how, factory, [], "IntervalJoin"
    )


def interval_join_inner(l, r, tl, tr, i, *on, **kw):
    return interval_join(l, r, tl, tr, i, *on, how="inner", **kw)


def interval_join_left(l, r, tl, tr, i, *on, **kw):
    return interval_join(l, r, tl, tr, i, *on, how="left", **kw)


def interval_join_right(l, r, tl, tr, i, *on, **kw):
    return interval_join(l, r, tl, tr, i, *on, how="right", **kw)


def interval_join_outer(l, r, tl, tr, i, *on, **kw):
    return interval_join(l, r, tl, tr, i, *on, how="outer", **kw)


def window_join(left_table, right_table, t_left, t_right, window: Window, *on, how="inner"):
    """Pairs of rows falling into the same window (reference
    ``_window_join.py``)."""
    if hasattr(how, "value"):
        how = how.value

    def factory(l_cols, r_cols, out_cols):
        lti = l_cols.index("__t")
        lid = l_cols.index("__id")
        rti = r_cols.index("__t")
        rid = r_cols.index("__id")
        l_data = [i for i, c in enumerate(l_cols) if c.startswith("__l_")]
        r_data = [i for i, c in enumerate(r_cols) if c.startswith("__r_")]

        def emit_pairs(out, ls, rs, w):
            """Shared pairing per window id ``w`` with outer padding."""
            if ls and rs:
                for lk, lrow in ls:
                    for rk, rrow in rs:
                        out[hash_values(lk, rk, w)] = (
                            tuple(lrow[i] for i in l_data)
                            + (lrow[lid], lrow[lti])
                            + tuple(rrow[i] for i in r_data)
                            + (rrow[rid], rrow[rti])
                        )
            elif ls and how in ("left", "outer"):
                for lk, lrow in ls:
                    out[hash_values(lk, 0, w)] = (
                        tuple(lrow[i] for i in l_data)
                        + (lrow[lid], lrow[lti])
                        + tuple(None for _ in r_data)
                        + (None, None)
                    )
            elif rs and how in ("right", "outer"):
                for rk, rrow in rs:
                    out[hash_values(0, rk, w)] = (
                        tuple(None for _ in l_data)
                        + (None, None)
                        + tuple(rrow[i] for i in r_data)
                        + (rrow[rid], rrow[rti])
                    )

        def compute_session(inst, lrows, rrows):
            # sessions merge over the UNION of both sides' times (reference
            # ``_window_join.py`` session mode): a session window id cannot
            # be assigned per row, so merge here and pair within sessions
            entries = sorted(
                [("l", k, row, row[lti]) for k, row in lrows.items()]
                + [("r", k, row, row[rti]) for k, row in rrows.items()],
                key=lambda e: (e[3], e[0], e[1]),
            )
            out: dict[int, tuple] = {}
            if not entries:
                return out
            sessions = _merge_sessions(
                entries, lambda e: e[3], window.predicate, window.max_gap
            )
            for sess in sessions:
                w = (sess[0][3], sess[-1][3])
                ls = [(k, row) for side, k, row, _t in sess if side == "l"]
                rs = [(k, row) for side, k, row, _t in sess if side == "r"]
                emit_pairs(out, ls, rs, w)
            return out

        def compute(inst, lrows, rrows):
            from collections import defaultdict as dd

            out: dict[int, tuple] = {}
            l_by_win = dd(list)
            r_by_win = dd(list)
            for lk, lrow in lrows.items():
                for w in window.assign(lrow[lti]):
                    l_by_win[w].append((lk, lrow))
            for rk, rrow in rrows.items():
                for w in window.assign(rrow[rti]):
                    r_by_win[w].append((rk, rrow))
            for w in set(l_by_win) | set(r_by_win):
                emit_pairs(out, l_by_win.get(w, []), r_by_win.get(w, []), w)
            return out

        return compute_session if isinstance(window, SessionWindow) else compute

    return _binary_temporal(
        left_table, right_table, t_left, t_right, on, how, factory, [], "WindowJoin"
    )


def asof_now_join(left_table, right_table, *on, id=None, how="inner"):
    """Join where left rows are matched against the right table *as of their
    arrival* — left updates don't retrigger (reference ``_asof_now_join.py``).

    Engine note: with the epoch model, new left rows see the right state at
    their epoch; subsequent right updates do not update old results.
    """
    from pathway_tpu.engine.operators.asof_now import AsofNowJoinNode
    from pathway_tpu.internals.table import _prepare_env
    from pathway_tpu.internals.table import Table

    l_on, r_on = [], []
    for cond in on:
        if not isinstance(cond, expr_mod.ColumnBinaryOpExpression) or cond._operator != "==":
            raise ValueError("join conditions must be equality")
        l_on.append(
            substitute(cond._left, {thisclass.left: left_table, thisclass.this: left_table})
        )
        r_on.append(
            substitute(cond._right, {thisclass.right: right_table, thisclass.this: right_table})
        )
    lexprs = {f"__c_{n}": ColumnReference(left_table, n) for n in left_table.column_names()}
    lexprs["__id"] = ColumnReference(left_table, "id")
    for i, e in enumerate(l_on):
        lexprs[f"__jk{i}"] = e
    env, rw = _prepare_env(left_table, lexprs)
    lprep = core_ops.RowwiseNode(G.engine_graph, env, rw)
    rexprs = {f"__c_{n}": ColumnReference(right_table, n) for n in right_table.column_names()}
    rexprs["__id"] = ColumnReference(right_table, "id")
    for i, e in enumerate(r_on):
        rexprs[f"__jk{i}"] = e
    env, rw = _prepare_env(right_table, rexprs)
    rprep = core_ops.RowwiseNode(G.engine_graph, env, rw)
    from pathway_tpu.internals.joins import JoinResult

    jr = JoinResult.__new__(JoinResult)
    jr._left = left_table
    jr._right = right_table
    jr._how = how
    jr._id = id

    jk_cols = [f"__jk{i}" for i in range(len(l_on))]
    output_spec = (
        [(f"__l_{n}", "left", f"__c_{n}") for n in left_table.column_names()]
        + [("__l_id", "left", "__id")]
        + [(f"__r_{n}", "right", f"__c_{n}") for n in right_table.column_names()]
        + [("__r_id", "right", "__id")]
    )
    node = AsofNowJoinNode(
        G.engine_graph,
        lprep,
        rprep,
        jk_cols,
        jk_cols,
        how,
        output_spec,
        key_mode="left",
    )
    jr._build = lambda: node  # reuse JoinResult.select over this node
    return jr


Direction = _Direction


def apply_temporal_behavior(table, behavior):
    """Lower a ``CommonBehavior`` onto a table carrying a ``_pw_time``
    column: delay buffers, cutoff freezes+forgets (reference
    ``temporal_behavior.py:101``)."""
    if behavior is not None:
        if not isinstance(behavior, CommonBehavior):
            raise TypeError(
                "apply_temporal_behavior expects a CommonBehavior (use "
                "common_behavior(...)); exactly_once_behavior applies only "
                "inside windowby"
            )
        time_col = table["_pw_time"]
        if behavior.delay is not None:
            table = table._buffer(time_col + behavior.delay, time_col)
        if behavior.cutoff is not None:
            # same lowering as windowby: freeze drops late arrivals; results
            # are retracted past the cutoff only when keep_results is False
            threshold = table["_pw_time"] + behavior.cutoff
            table = table._freeze(threshold, table["_pw_time"])
            if not behavior.keep_results:
                table = table._forget(threshold, table["_pw_time"])
    return table


def window_join_inner(left_table, right_table, t_left, t_right, window, *on):
    return window_join(left_table, right_table, t_left, t_right, window, *on, how="inner")


def window_join_left(left_table, right_table, t_left, t_right, window, *on):
    return window_join(left_table, right_table, t_left, t_right, window, *on, how="left")


def window_join_right(left_table, right_table, t_left, t_right, window, *on):
    return window_join(left_table, right_table, t_left, t_right, window, *on, how="right")


def window_join_outer(left_table, right_table, t_left, t_right, window, *on):
    return window_join(left_table, right_table, t_left, t_right, window, *on, how="outer")


def asof_now_join_inner(left_table, right_table, *on, id=None):  # noqa: A002
    return asof_now_join(left_table, right_table, *on, id=id, how="inner")


def asof_now_join_left(left_table, right_table, *on, id=None):  # noqa: A002
    return asof_now_join(left_table, right_table, *on, id=id, how="left")


# reference result-class names (our temporal joins expose the same select
# surface through _binary_temporal's JoinResult-like object)
class AsofJoinResult:  # noqa: D401 — name parity marker
    """Alias target for reference ``_asof_join.py:AsofJoinResult``."""


class AsofNowJoinResult:
    """Alias target for reference ``_asof_now_join.py:AsofNowJoinResult``."""


class IntervalJoinResult:
    """Alias target for reference ``_interval_join.py:IntervalJoinResult``."""


class WindowJoinResult:
    """Alias target for reference ``_window_join.py:WindowJoinResult``."""
