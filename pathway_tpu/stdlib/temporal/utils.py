"""Temporal type plumbing (reference ``stdlib/temporal/utils.py``)."""

from __future__ import annotations

import datetime
from typing import Any, Union

import pandas as pd

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.type_interpreter import infer_dtype

TimeEventType = Union[int, float, datetime.datetime]
IntervalType = Union[int, float, datetime.timedelta]


def get_default_origin(time_event_type: dt.DType) -> TimeEventType:
    """Default window origin per time dtype; 1973 starts on a Monday so
    week-wide windows align to Mondays (reference ``utils.py:16``)."""
    mapping: dict[Any, TimeEventType] = {
        dt.INT: 0,
        dt.FLOAT: 0.0,
        dt.DATE_TIME_NAIVE: pd.Timestamp(year=1973, month=1, day=1, tz=None),
        dt.DATE_TIME_UTC: pd.Timestamp(year=1973, month=1, day=1, tz="UTC"),
    }
    return mapping[time_event_type]


def zero_length_interval(interval_type: type[IntervalType]) -> IntervalType:
    if issubclass(interval_type, datetime.timedelta):
        return datetime.timedelta(0)
    if issubclass(interval_type, bool):
        raise TypeError("unsupported interval type")
    if issubclass(interval_type, int):
        return 0
    if issubclass(interval_type, float):
        return 0.0
    raise TypeError("unsupported interval type")


_TIME_EVENT_DTYPES = (dt.INT, dt.FLOAT, dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC)
_INTERVAL_DTYPES = (dt.INT, dt.FLOAT, dt.DURATION, dt.DURATION)


def check_joint_types(parameters: dict[str, tuple[Any, Any]]) -> None:
    """Verify that time/interval arguments use a consistent family:
    (int, int), (float, float) or (datetime, timedelta)
    (reference ``utils.py:46``)."""
    parameters = {
        name: (variable, expected)
        for name, (variable, expected) in parameters.items()
        if variable is not None
    }
    if not parameters:
        return

    def possible(expected) -> tuple[dt.DType, ...]:
        if expected is TimeEventType:
            return _TIME_EVENT_DTYPES
        if expected is IntervalType:
            return _INTERVAL_DTYPES
        raise ValueError("Type has to be either TimeEventType or IntervalType.")

    def dtype_of(variable) -> dt.DType:
        from pathway_tpu.internals.expression import ColumnExpression

        if isinstance(variable, ColumnExpression):
            table = None
            tables = variable._tables()
            if tables:
                table = tables[0]
            try:
                return infer_dtype(variable, table)
            except Exception:
                return dt.ANY
        return dt.wrap(type(variable))

    types = {name: dtype_of(v) for name, (v, _e) in parameters.items()}
    for i in range(len(_TIME_EVENT_DTYPES)):
        candidate = {
            name: possible(expected)[i]
            for name, (_v, expected) in parameters.items()
        }
        if all(
            types[name] == candidate[name] or types[name] == dt.ANY
            for name in parameters
        ):
            return
    expected_str = " or ".join(
        repr(
            tuple(
                possible(expected)[i] for _n, (_v, expected) in parameters.items()
            )
        )
        for i in range(len(_TIME_EVENT_DTYPES))
    )
    raise TypeError(
        f"Arguments ({', '.join(parameters)}) have to be of types "
        f"{expected_str} but are of types "
        f"{tuple(types[n] for n in parameters)!r}."
    )
