"""Clock streams and inactivity detection (reference
``stdlib/temporal/time_utils.py``)."""

from __future__ import annotations

import datetime
import time

from pathway_tpu.internals import reducers
from pathway_tpu.internals.datetime_types import DateTimeUtc
from pathway_tpu.internals.schema import Schema
from pathway_tpu.io.python import ConnectorSubject


class TimestampSchema(Schema):
    timestamp_utc: DateTimeUtc


class TimestampSubject(ConnectorSubject):
    """Emits the current UTC time every ``refresh_rate``; exits promptly
    when the connector is stopped."""

    def __init__(self, refresh_rate: datetime.timedelta) -> None:
        super().__init__()
        self._refresh_rate = refresh_rate
        self._stopped = False

    def run(self) -> None:
        while not self._stopped and not self._connector_stopping():
            now_utc = datetime.datetime.now(datetime.timezone.utc)
            self.next(timestamp_utc=now_utc)
            self.commit()
            deadline = time.monotonic() + self._refresh_rate.total_seconds()
            while time.monotonic() < deadline:
                if self._stopped or self._connector_stopping():
                    return
                time.sleep(min(0.1, self._refresh_rate.total_seconds()))

    def _connector_stopping(self) -> bool:
        c = self._connector
        return c is not None and c.should_stop()

    def on_stop(self) -> None:
        self._stopped = True


# memoized per (refresh_rate, engine graph): a cleared graph must get a
# fresh stream, not a Table bound to dead nodes
_utc_now_memo: dict = {}


def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)):
    """A continuously updating stream of the current UTC time
    (reference ``time_utils.py:utc_now``); one stream per refresh rate per
    engine graph."""
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.io import python as io_python

    key = (refresh_rate, id(G.engine_graph))
    if key not in _utc_now_memo:
        _utc_now_memo[key] = io_python.read(
            TimestampSubject(refresh_rate=refresh_rate),
            schema=TimestampSchema,
        )
    return _utc_now_memo[key]


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance=None,
):
    """Flag inactivity gaps longer than ``allowed_inactivity_period`` and the
    events that resume activity (reference ``time_utils.py:52``).  Returns
    (inactivities, resumed_activities) with columns ``inactive_t`` /
    ``resumed_t`` (+ ``instance`` when given).  Assumes event times track
    current UTC."""
    events_t = event_time_column.table.select(t=event_time_column, instance=instance)

    now_t = utc_now(refresh_rate=refresh_rate)
    # build-time cutoff avoids alerting while backfilling historical events
    started_at = datetime.datetime.now(datetime.timezone.utc)
    grouped = events_t.groupby(events_t.instance).reduce(
        events_t.instance, latest_t=reducers.max(events_t.t)
    )
    latest_t = grouped.filter(grouped.latest_t > started_at)
    joined = now_t.asof_now_join(latest_t).select(
        timestamp_utc=now_t.timestamp_utc,
        instance=latest_t.instance,
        latest_t=latest_t.latest_t,
    )
    stale = joined.filter(
        joined.latest_t + allowed_inactivity_period < joined.timestamp_utc
    )
    inactivities = (
        stale.groupby(stale.latest_t, stale.instance)
        .reduce(stale.latest_t, stale.instance)
    )
    inactivities = inactivities.select(
        instance=inactivities.instance, inactive_t=inactivities.latest_t
    )

    latest_inactivity = inactivities.groupby(inactivities.instance).reduce(
        inactivities.instance,
        inactive_t=reducers.latest(inactivities.inactive_t),
    )
    resumed_joined = events_t.asof_now_join(
        latest_inactivity, events_t.instance == latest_inactivity.instance
    ).select(
        t=events_t.t,
        instance=events_t.instance,
        inactive_t=latest_inactivity.inactive_t,
    )
    after = resumed_joined.filter(resumed_joined.t > resumed_joined.inactive_t)
    resumed_activities = after.groupby(after.inactive_t, after.instance).reduce(
        after.instance, resumed_t=reducers.min(after.t)
    )
    if instance is None:
        inactivities = inactivities.without("instance")
        resumed_activities = resumed_activities.without("instance")
    return inactivities, resumed_activities
