"""Graph / WeightedGraph containers with cluster contraction
(reference ``stdlib/graphs/graph.py``)."""

from __future__ import annotations

from dataclasses import dataclass

from pathway_tpu.internals import reducers
from pathway_tpu.stdlib.graphs.common import Clustering, Edge, Vertex, Weight


def _extended_to_full_clustering(vertices, clustering):
    """Extend a partial clustering so unassigned vertices form singleton
    clusters keyed by their own id."""
    return vertices.select(c=vertices.id).update_rows(clustering)


def _contract(edges, clustering):
    """Contract clusters: one vertex per cluster; edges re-pointed to the
    clusters containing their endpoints."""
    grouped = clustering.groupby(clustering.c).reduce(v=clustering.c)
    new_vertices = grouped.with_id(grouped.v).select()
    new_edges = edges.select(u=clustering.ix(edges.u).c, v=clustering.ix(edges.v).c)
    return Graph(new_vertices, new_edges)


def _contract_weighted(edges, clustering):
    g = _contract(edges, clustering)
    new_edges = edges.select(
        u=clustering.ix(edges.u).c,
        v=clustering.ix(edges.v).c,
        weight=edges.weight,
    )
    return WeightedGraph.from_vertices_and_weighted_edges(g.V, new_edges)


@dataclass
class Graph:
    """Undirected, unweighted (multi)graph."""

    V: object
    E: object

    def contracted_to_multi_graph(self, clustering):
        full = _extended_to_full_clustering(self.V, clustering)
        return _contract(self.E, full)

    def contracted_to_unweighted_simple_graph(self, clustering, **reducer_expressions):
        contracted = self.contracted_to_multi_graph(clustering)
        contracted.E = contracted.E.groupby(contracted.E.u, contracted.E.v).reduce(
            contracted.E.u, contracted.E.v
        )
        return contracted

    def contracted_to_weighted_simple_graph(self, clustering, **reducer_expressions):
        contracted = self.contracted_to_multi_graph(clustering)
        WE = contracted.E.groupby(contracted.E.u, contracted.E.v).reduce(
            contracted.E.u, contracted.E.v, **reducer_expressions
        )
        return WeightedGraph.from_vertices_and_weighted_edges(contracted.V, WE)

    def without_self_loops(self):
        return Graph(self.V, self.E.filter(self.E.u != self.E.v))


@dataclass
class WeightedGraph(Graph):
    """Undirected weighted (multi)graph; ``WE`` carries u, v, weight."""

    WE: object = None

    @staticmethod
    def from_vertices_and_weighted_edges(V, WE):
        return WeightedGraph(V, WE, WE)

    def contracted_to_multi_graph(self, clustering):
        full = _extended_to_full_clustering(self.V, clustering)
        return _contract_weighted(self.WE, full)

    def contracted_to_weighted_simple_graph(self, clustering, **reducer_expressions):
        contracted = self.contracted_to_multi_graph(clustering)
        contracted.WE = contracted.WE.groupby(
            contracted.WE.u, contracted.WE.v
        ).reduce(contracted.WE.u, contracted.WE.v, **reducer_expressions)
        return contracted

    def without_self_loops(self):
        return WeightedGraph.from_vertices_and_weighted_edges(
            self.V, self.WE.filter(self.WE.u != self.WE.v)
        )


def exact_modularity(G: WeightedGraph, C, round_digits: int = 16):
    """Modularity of clustering ``C`` on weighted graph ``G``:
    Q = Σ_c (internal_c·m − degree_c²) / m², rounded to ``round_digits``
    (reference ``louvain_communities/impl.py:340``).  ``G.WE`` is taken as a
    directed edge list; for an undirected graph list each edge once per
    direction (or accept the reference's same halving convention)."""
    clusters = C.groupby(id=C.c).reduce()

    by_u = G.WE.with_columns(c=C.ix(G.WE.u).c)
    cluster_degrees = clusters.with_columns(degree=0.0).update_rows(
        by_u.groupby(id=by_u.c).reduce(degree=reducers.sum(by_u.weight))
    )

    tagged = G.WE.with_columns(cu=C.ix(G.WE.u).c, cv=C.ix(G.WE.v).c)
    internal_edges = tagged.filter(tagged.cu == tagged.cv)
    cluster_internal = clusters.with_columns(internal=0.0).update_rows(
        internal_edges.groupby(id=internal_edges.cu).reduce(
            internal=reducers.sum(internal_edges.weight)
        )
    )

    total_weight = G.WE.reduce(m=reducers.sum(G.WE.weight))

    from pathway_tpu.internals import expression as expr_mod

    score = clusters.select(
        modularity=expr_mod.apply_with_type(
            lambda internal, degree, total: (internal * total - degree * degree)
            / (total * total),
            float,
            cluster_internal.restrict(clusters).internal,
            cluster_degrees.restrict(clusters).degree,
            total_weight.ix_ref().m,
        )
    )
    return score.reduce(
        modularity=expr_mod.apply_with_type(
            lambda s: round(s, round_digits), float, reducers.sum(score.modularity)
        )
    )
