"""Louvain community detection as incremental dataflow
(reference ``stdlib/graphs/louvain_communities/impl.py``).

The reference runs per-iteration parallel move proposals with an
independent-set filter.  This implementation uses synchronous parallel
moves (every vertex adopts its best neighboring cluster each iteration):
simpler, fully incremental, and bounded by the fixed iteration count — on
oscillation-free graphs both converge to the same clustering.  The exact
hierarchical driver contracts between levels via ``WeightedGraph``.

The host-side batch variant (faster for static graphs) remains
``stdlib.graphs.louvain_communities``.
"""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.stdlib.graphs.graph import WeightedGraph


def _one_step(G: WeightedGraph, clustering):
    """One synchronous move round: each vertex joins the neighboring cluster
    maximizing the modularity gain  w(u→c) − deg(u)·deg(c)/(2m)."""
    WE = G.WE
    tagged = WE.select(
        u=WE.u,
        weight=WE.weight,
        cu=clustering.ix(WE.u).c,
        cv=clustering.ix(WE.v).c,
    )

    # weight from vertex u to each adjacent cluster c
    to_cluster = tagged.groupby(tagged.u, tagged.cv).reduce(
        tagged.u,
        c=tagged.cv,
        w=reducers.sum(tagged.weight),
    )

    deg_u = tagged.groupby(tagged.u).reduce(tagged.u, deg=reducers.sum(tagged.weight))
    deg_c = tagged.groupby(tagged.cv).reduce(
        c=tagged.cv, deg=reducers.sum(tagged.weight)
    )
    total = WE.reduce(m=reducers.sum(WE.weight))

    cand = to_cluster.select(
        to_cluster.u,
        to_cluster.c,
        gain=to_cluster.w
        - deg_u.ix_ref(to_cluster.u).deg
        * deg_c.ix_ref(to_cluster.c).deg
        / total.ix_ref().m,
    )
    best = cand.groupby(cand.u).reduce(
        cand.u,
        ptr=reducers.argmax(cand.gain),
        gain=reducers.max(cand.gain),
    )
    best = best.select(
        best.u,
        best.gain,
        c=cand.ix(best.ptr).c,
        cur=clustering.ix(best.u).c,
    )
    # symmetry-break synchronous moves: labels flow monotonically toward
    # smaller cluster ids, which kills the label-rotation cycles a fully
    # parallel update would produce (cf. min-label propagation)
    moves_tbl = best.filter(
        (best.gain > 0.0)
        & expr_mod.apply_with_type(
            lambda new, cur: new is not None
            and cur is not None
            and new.value < cur.value,
            bool,
            best.c,
            best.cur,
        )
    )
    rekeyed = moves_tbl.with_id(moves_tbl.u)
    moves = rekeyed.select(c=rekeyed.c)
    return clustering.update_rows(moves)


def louvain_level_fixed_iterations(G: WeightedGraph, number_of_iterations: int):
    """Run ``number_of_iterations`` synchronous move rounds from singleton
    clusters; returns a Clustering table (vertex id → cluster pointer ``c``).
    Reference ``impl.py:252`` (``_louvain_level_fixed_iterations``)."""
    clustering = G.V.select(c=G.V.id)
    for _ in range(number_of_iterations):
        clustering = _one_step(G, clustering)
    return clustering


class louvain_communities_fixed_iterations:
    """Hierarchical Louvain with a fixed iteration budget per level
    (reference ``impl.py:282``).  After construction:

    - ``clustering_levels`` — list of per-level Clustering tables (finest
      first, each mapping that level's vertices to the next level's),
    - ``hierarchical_clustering`` — composed mapping from original vertices
      to top-level clusters,
    - ``G`` — the original graph; ``levels`` — the level count.
    """

    def __init__(self, G: WeightedGraph, iterations: int = 10, levels: int = 1):
        self.G = G
        self.levels = levels
        self.clustering_levels = []
        current = G
        composed = None
        for _ in range(levels):
            clustering = louvain_level_fixed_iterations(current, iterations)
            self.clustering_levels.append(clustering)
            if composed is None:
                composed = clustering
            else:
                composed = composed.select(c=clustering.ix(composed.c).c)
            current = current.contracted_to_weighted_simple_graph(
                clustering, weight=reducers.sum(current.WE.weight)
            )
        self.hierarchical_clustering = composed
