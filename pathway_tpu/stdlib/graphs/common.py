"""Graph schema vocabulary (reference ``stdlib/graphs/common.py``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.api import Pointer
from pathway_tpu.internals.schema import Schema


class Vertex(Schema):
    pass


class Edge(Schema):
    """An edge holds pointers to its endpoint vertices."""

    u: Pointer[Any]
    v: Pointer[Any]


class Weight(Schema):
    """Weight column mixin for Vertex / Edge tables."""

    weight: float


class Cluster(Vertex, Schema):
    pass


class Clustering(Schema):
    """Cluster membership: vertex (row id) belongs to cluster ``c``."""

    c: Pointer[Any]


class Dist(Schema):
    """Edge length for shortest paths (reference ``bellman_ford/impl.py``)."""

    dist: float


class DistFromSource(Schema):
    dist_from_source: float


class PageRankResult(Schema):
    """Reference ``pagerank/impl.py:Result`` (rank is a damped probability
    mass, a float)."""

    rank: float
