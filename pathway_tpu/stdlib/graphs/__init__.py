"""Graph algorithms over ``pw.iterate`` (reference ``stdlib/graphs/``):
bellman_ford, pagerank, louvain communities; Graph/WeightedGraph
containers with cluster contraction and exact modularity."""

from __future__ import annotations

import math

import pathway_tpu.internals.iterate as iterate_mod
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.stdlib.graphs.common import (
    Cluster,
    Clustering,
    Edge,
    Vertex,
    Weight,
)
from pathway_tpu.stdlib.graphs.graph import Graph, WeightedGraph, exact_modularity
from pathway_tpu.stdlib.graphs.louvain import (
    louvain_communities_fixed_iterations,
    louvain_level_fixed_iterations,
)


def bellman_ford(vertices, edges):
    """Single-source shortest paths (reference
    ``stdlib/graphs/bellman_ford/impl.py:42``).  ``vertices`` carries either
    ``is_source`` (bool, reference API) or a prebuilt ``dist_from_source`` /
    ``dist_from_start`` float column; ``edges`` has u, v, dist columns.
    Returns a table with ``dist_from_source`` on the vertex universe."""
    names = vertices.column_names()
    if "is_source" in names:
        vertices = vertices.select(
            dist_from_source=expr_mod.if_else(vertices.is_source, 0.0, math.inf)
        )
    elif "dist_from_start" in names:
        vertices = vertices.select(dist_from_source=vertices.dist_from_start)
    else:
        vertices = vertices.select(dist_from_source=vertices.dist_from_source)

    def step(vertices, edges):
        # min candidate distance per target vertex
        j = edges.join(vertices, edges.u == vertices.id).select(
            target=edges.v, cand=vertices.dist_from_source + edges.dist
        )
        best = j.groupby(j.target).reduce(
            j.target, best=reducers.min(j.cand)
        )
        joined = vertices.join_left(best, vertices.id == best.target, id=vertices.id).select(
            old=vertices.dist_from_source,
            cand=best.best,
        )
        new_vertices = joined.select(
            dist_from_source=expr_mod.if_else(
                expr_mod.coalesce(joined.cand, math.inf) < joined.old,
                expr_mod.coalesce(joined.cand, math.inf),
                joined.old,
            )
        )
        return dict(vertices=new_vertices, edges=edges)

    return iterate_mod.iterate(
        lambda vertices, edges: step(vertices, edges),
        vertices=vertices,
        edges=edges,
    ).vertices


def pagerank(edges, steps: int = 50, damping: float = 0.85):
    """PageRank over an edge table with ``u``/``v`` endpoint columns —
    iterative power method. Returns a table keyed by vertex (id =
    ``pointer_from(v)``) with columns ``v`` (the vertex value) and ``rank``.
    """
    from pathway_tpu.internals import thisclass

    vertices = (
        edges.select(v=edges.u)
        .concat_reindex(edges.select(v=edges.v))
        .groupby(thisclass.this.v)
        .reduce(thisclass.this.v)
        .with_id_from(thisclass.this.v)
    )
    degrees = edges.groupby(edges.u).reduce(
        edges.u, degree=reducers.count()
    )
    ranks = vertices.select(vertices.v, rank=1.0)

    for _ in range(steps):
        with_rank = edges.join(ranks, edges.u == ranks.v).select(
            u=edges.u, target=edges.v, rank=ranks.rank
        )
        contribs = with_rank.join(degrees, with_rank.u == degrees.u).select(
            target=with_rank.target,
            contrib=with_rank.rank / degrees.degree,
        )
        incoming = contribs.groupby(contribs.target).reduce(
            contribs.target, total=reducers.sum(contribs.contrib)
        )
        joined = ranks.join_left(
            incoming, ranks.v == incoming.target, id=ranks.id
        ).select(ranks.v, total=incoming.total)
        ranks = joined.select(
            joined.v,
            rank=(1 - damping) + damping * expr_mod.coalesce(joined.total, 0.0),
        )
    return ranks


def _louvain_partition(adj: dict, resolution: float, levels: int) -> dict:
    """Greedy-modularity Louvain on an undirected weighted adjacency map
    {node: {nbr: w}}. Deterministic (sorted node order). Returns
    {node: community_label}."""
    mapping = {n: n for n in adj}  # original node -> current supernode

    for _ in range(levels):
        nodes = sorted(adj, key=repr)
        m2 = sum(sum(nb.values()) for nb in adj.values())  # 2m (both dirs)
        if m2 == 0:
            break
        degree = {n: sum(adj[n].values()) for n in nodes}
        comm = {n: n for n in nodes}
        comm_degree = dict(degree)

        moved = True
        passes = 0
        while moved and passes < 10:
            moved = False
            passes += 1
            for n in nodes:
                cn = comm[n]
                comm_degree[cn] -= degree[n]
                # weight from n into each neighbouring community
                links: dict = {}
                for nbr, w in adj[n].items():
                    if nbr == n:
                        continue
                    links[comm[nbr]] = links.get(comm[nbr], 0.0) + w
                best_c, best_gain = cn, 0.0
                base = links.get(cn, 0.0) - resolution * comm_degree[cn] * degree[n] / m2
                for c, w_in in sorted(links.items(), key=lambda kv: repr(kv[0])):
                    gain = w_in - resolution * comm_degree[c] * degree[n] / m2
                    if gain > base and gain > best_gain:
                        best_gain, best_c = gain, c
                comm[n] = best_c
                comm_degree[best_c] += degree[n]
                if best_c != cn:
                    moved = True

        # relabel communities by their smallest member for determinism
        members: dict = {}
        for n, c in comm.items():
            members.setdefault(c, []).append(n)
        label = {c: min(ns, key=repr) for c, ns in members.items()}
        comm = {n: label[c] for n, c in comm.items()}
        mapping = {orig: comm[sup] for orig, sup in mapping.items()}
        if len(set(comm.values())) == len(adj):
            break  # no merge happened: converged

        # aggregate: communities become supernodes
        new_adj: dict = {}
        for n, nbrs in adj.items():
            cn = comm[n]
            row = new_adj.setdefault(cn, {})
            for nbr, w in nbrs.items():
                row[comm[nbr]] = row.get(comm[nbr], 0.0) + w
        adj = new_adj

    return mapping


def louvain_communities(edges, weight=None, resolution: float = 1.0,
                        levels: int = 3):
    """Community detection by greedy modularity (Louvain method) over an
    edge table with ``u``/``v`` columns and optional ``weight``.

    Reference capability: ``stdlib/graphs/louvain_communities`` (dataflow
    implementation over WeightedGraph). Here the whole graph is decoded by a
    stateful whole-table reducer on every consolidation — incremental in the
    replay sense (retractions re-cluster) — and flattened back into a table
    keyed by vertex with columns ``v`` (vertex) and ``community`` (the
    smallest member of the vertex's community, a deterministic label).
    """
    from pathway_tpu.internals import thisclass

    w_expr = weight if weight is not None else expr_mod.ColumnConstExpression(1.0)
    packed = edges.select(u=edges.u, v=edges.v, w=w_expr)

    def cluster(_state, rows):
        adj: dict = {}
        # the engine pre-filters to positive net counts (StatefulAcc.compute)
        for (u, v, w), count in rows:
            ww = float(w) * count
            adj.setdefault(u, {})[v] = adj.get(u, {}).get(v, 0.0) + ww
            adj.setdefault(v, {})[u] = adj.get(v, {}).get(u, 0.0) + ww
        if not adj:
            return ()
        mapping = _louvain_partition(adj, resolution, levels)
        return tuple(sorted(mapping.items(), key=lambda kv: repr(kv[0])))

    assign_reducer = reducers.stateful_many(cluster)
    assignments = packed.groupby().reduce(
        pairs=assign_reducer(packed.u, packed.v, packed.w)
    )
    flat = assignments.flatten(assignments.pairs)
    return flat.select(
        v=flat.pairs.get(0), community=flat.pairs.get(1)
    ).with_id_from(thisclass.this.v)
