"""Graph algorithms over ``pw.iterate`` (reference ``stdlib/graphs/``):
bellman_ford, pagerank, louvain communities (simplified)."""

from __future__ import annotations

import math

import pathway_tpu.internals.iterate as iterate_mod
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers


def bellman_ford(vertices, edges):
    """Single-source shortest paths; ``vertices`` has ``dist_from_start``
    (0 for source, inf otherwise), ``edges`` has u, v, dist columns."""

    def step(vertices, edges):
        # min candidate distance per target vertex
        j = edges.join(vertices, edges.u == vertices.id).select(
            target=edges.v, cand=vertices.dist_from_start + edges.dist
        )
        best = j.groupby(j.target).reduce(
            j.target, best=reducers.min(j.cand)
        )
        joined = vertices.join_left(best, vertices.id == best.target, id=vertices.id).select(
            old=vertices.dist_from_start,
            cand=best.best,
        )
        new_vertices = joined.select(
            dist_from_start=expr_mod.if_else(
                expr_mod.coalesce(joined.cand, math.inf) < joined.old,
                expr_mod.coalesce(joined.cand, math.inf),
                joined.old,
            )
        )
        return dict(vertices=new_vertices, edges=edges)

    return iterate_mod.iterate(
        lambda vertices, edges: step(vertices, edges),
        vertices=vertices,
        edges=edges,
    ).vertices


def pagerank(edges, steps: int = 50, damping: float = 0.85):
    """PageRank over an edge table with ``u``/``v`` endpoint columns —
    iterative power method. Returns a table keyed by vertex (id =
    ``pointer_from(v)``) with columns ``v`` (the vertex value) and ``rank``.
    """
    from pathway_tpu.internals import thisclass

    vertices = (
        edges.select(v=edges.u)
        .concat_reindex(edges.select(v=edges.v))
        .groupby(thisclass.this.v)
        .reduce(thisclass.this.v)
        .with_id_from(thisclass.this.v)
    )
    degrees = edges.groupby(edges.u).reduce(
        edges.u, degree=reducers.count()
    )
    ranks = vertices.select(vertices.v, rank=1.0)

    for _ in range(steps):
        with_rank = edges.join(ranks, edges.u == ranks.v).select(
            u=edges.u, target=edges.v, rank=ranks.rank
        )
        contribs = with_rank.join(degrees, with_rank.u == degrees.u).select(
            target=with_rank.target,
            contrib=with_rank.rank / degrees.degree,
        )
        incoming = contribs.groupby(contribs.target).reduce(
            contribs.target, total=reducers.sum(contribs.contrib)
        )
        joined = ranks.join_left(
            incoming, ranks.v == incoming.target, id=ranks.id
        ).select(ranks.v, total=incoming.total)
        ranks = joined.select(
            joined.v,
            rank=(1 - damping) + damping * expr_mod.coalesce(joined.total, 0.0),
        )
    return ranks


def louvain_communities(*args, **kwargs):
    raise NotImplementedError("louvain arrives with the graph-clustering pack")
