"""Statistical helpers (reference ``stdlib/statistical/_interpolate.py``)."""

from __future__ import annotations

import enum

from pathway_tpu.internals import expression as expr_mod


class InterpolateMode(enum.Enum):
    LINEAR = 0


def interpolate(table, timestamp, *values, mode: InterpolateMode | None = None):
    """Linear interpolation of None values between neighbors ordered by
    ``timestamp`` (reference ``Table.interpolate``). Current implementation
    fills from the previous non-None neighbor pair via prev/next pointers."""
    mode = mode or InterpolateMode.LINEAR
    sorted_ptrs = table.sort(timestamp)
    with_ptrs = table.with_columns(
        __prev=sorted_ptrs.prev, __next=sorted_ptrs.next
    )
    out = {}
    ts_name = timestamp.name

    for v in values:
        name = v.name if isinstance(v, expr_mod.ColumnReference) else str(v)

        prev_val = table.ix(with_ptrs["__prev"], optional=True)[name]
        next_val = table.ix(with_ptrs["__next"], optional=True)[name]
        prev_ts = table.ix(with_ptrs["__prev"], optional=True)[ts_name]
        next_ts = table.ix(with_ptrs["__next"], optional=True)[ts_name]

        def interp(cur, pv, nv, pt, nt, ct):
            if cur is not None:
                return float(cur)
            if pv is None and nv is None:
                return None
            if pv is None:
                return float(nv)
            if nv is None:
                return float(pv)
            if nt == pt:
                return float(pv)
            frac = (ct - pt) / (nt - pt)
            return float(pv) + (float(nv) - float(pv)) * frac

        out[name] = expr_mod.apply_with_type(
            interp,
            float | None,
            table[name],
            prev_val,
            next_val,
            prev_ts,
            next_ts,
            table[ts_name],
        )
    return table.with_columns(**out)
