"""DataIndex — the retrieval API of record (reference
``stdlib/indexing/data_index.py``: InnerIndex:206, DataIndex:278, query:349,
query_as_of_now:412).

A DataIndex wraps a data table + an inner index over one of its columns;
``query_as_of_now`` answers each query once against the live index and joins
back requested data columns (collapsed into rank-ordered tuples or flattened
one-row-per-match).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.desugaring import substitute
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference


class InnerIndex:
    """Base inner index: knows how to turn the indexed column (and queries)
    into index/query vectors and an engine index factory."""

    def __init__(self, data_column: ColumnReference, metadata_column=None):
        self.data_column = data_column
        self.metadata_column = metadata_column

    @property
    def data_table(self):
        return self.data_column.table

    def index_vector_expr(self) -> ColumnExpression:
        return self.data_column

    def query_vector_expr(self, query_column: ColumnExpression) -> ColumnExpression:
        return query_column

    def make_factory(self):
        raise NotImplementedError

    def score_to_dist(self, score_expr: ColumnExpression) -> ColumnExpression:
        return -score_expr


class DataIndex:
    def __init__(self, data_table, inner_index: InnerIndex):
        self.data_table = data_table
        self.inner_index = inner_index

    def query_as_of_now(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: int | ColumnExpression = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ):
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            with_distances=with_distances,
            metadata_filter=metadata_filter,
        )

    def query(
        self,
        query_column: ColumnExpression,
        *,
        number_of_matches: int | ColumnExpression = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnExpression | None = None,
    ):
        # full (non-as-of-now) mode would re-answer queries on index change;
        # the as-of-now engine path is used for both (documented divergence,
        # matching the dominant RAG usage).
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            with_distances=with_distances,
            metadata_filter=metadata_filter,
        )

    def _query(
        self,
        query_column,
        *,
        number_of_matches,
        collapse_rows,
        with_distances,
        metadata_filter,
    ):
        inner = self.inner_index
        query_table = (
            query_column.table
            if isinstance(query_column, ColumnReference)
            else query_column._tables()[0]
        )
        query_column = substitute(query_column, {thisclass.this: query_table})
        limit_expr = (
            number_of_matches
            if isinstance(number_of_matches, ColumnExpression)
            else expr_mod.ColumnConstExpression(int(number_of_matches))
        )
        reply = self.data_table._external_index_as_of_now(
            inner.make_factory(),
            query_table,
            index_column=inner.index_vector_expr(),
            query_column=inner.query_vector_expr(query_column),
            index_filter_data_column=inner.metadata_column,
            query_filter_column=metadata_filter,
            query_responses_limit_column=limit_expr,
        )
        # reply: keyed by query id, _pw_index_reply = ((ptr, score), ...)
        with_qid = reply.with_columns(
            __qid=expr_mod.ColumnReference(reply, "id")
        )
        flat = with_qid.flatten(with_qid._pw_index_reply)
        matched = flat.select(
            __qid=flat["__qid"],
            __ptr=flat._pw_index_reply.get(0),
            __score=flat._pw_index_reply.get(1),
        )
        data = self.data_table
        data_cols = [c for c in data.column_names()]
        # pointer GATHER, not a hash join: ``__ptr`` IS the data row key
        # (the index replies with row pointers), so IxNode looks replies
        # up against the data table's state directly — a hash join here
        # would re-shuffle the whole data table (with its vectors) into
        # join buckets just to serve key-equality lookups
        target = data.ix(matched["__ptr"])
        joined = matched.select(
            matched["__qid"],
            matched["__score"],
            **{c: target[c] for c in data_cols},
        )
        if collapse_rows:
            grouped = joined.groupby(joined["__qid"])
            agg = {
                c: reducers.tuple(
                    expr_mod.make_tuple(-joined["__score"], joined[c])
                )
                for c in data_cols
            }
            agg["_pw_index_reply_score"] = reducers.tuple(joined["__score"])
            red = grouped.reduce(__qid=joined["__qid"], **agg)

            def sort_tuples(pairs):
                pairs = sorted(pairs, key=lambda p: p[0])
                return tuple(p[1] for p in pairs)

            rekeyed = red.with_id(red["__qid"])
            out_exprs = {
                c: expr_mod.apply_with_type(
                    sort_tuples, dt.ANY_TUPLE, rekeyed[c]
                )
                for c in data_cols
            }
            if with_distances:
                out_exprs["_pw_dist"] = expr_mod.apply_with_type(
                    lambda scores: tuple(sorted((-s for s in scores))),
                    dt.ANY_TUPLE,
                    rekeyed["_pw_index_reply_score"],
                )
            collapsed = rekeyed.select(**out_exprs)
            # left-join onto the full query universe (queries with no match
            # get empty tuples)
            empty = query_table.select(
                **{c: expr_mod.ColumnConstExpression(()) for c in data_cols},
                **(
                    {"_pw_dist": expr_mod.ColumnConstExpression(())}
                    if with_distances
                    else {}
                ),
            )
            result = empty.update_rows(
                collapsed.promise_universe_is_subset_of(empty)
            )
            return result
        else:
            out = {c: joined[c] for c in data_cols}
            if with_distances:
                out["_pw_dist"] = -joined["__score"]
            out["_pw_query_id"] = joined["__qid"]
            return joined.select(**out)
