"""BM25 full-text index (reference ``stdlib/indexing/bm25.py`` backed by
Tantivy). Here: a host-side incremental BM25 (inverted index with add/remove)
— text scoring is irregular host work, exactly what stays off the TPU.
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.engine.operators.external_index import ExternalIndexFactory
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex

_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")


def _tokenize(text: str) -> list[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text or "")]


class Bm25Index:
    """Incremental BM25 with Okapi scoring (k1=1.2, b=0.75)."""

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b
        self.docs: dict[Any, Counter] = {}
        self.doc_len: dict[Any, int] = {}
        self.df: Counter = Counter()
        self.total_len = 0

    def add(self, keys: list, texts) -> None:
        for key, text in zip(keys, texts):
            if isinstance(text, (list, tuple)):
                text = " ".join(map(str, text))
            if not isinstance(text, str):
                import numpy as np

                if isinstance(text, np.ndarray):
                    text = " ".join(map(str, text.tolist()))
                else:
                    text = str(text)
            tokens = Counter(_tokenize(text))
            self.docs[key] = tokens
            self.doc_len[key] = sum(tokens.values())
            self.total_len += self.doc_len[key]
            for term in tokens:
                self.df[term] += 1

    def remove(self, keys: list) -> None:
        for key in keys:
            tokens = self.docs.pop(key, None)
            if tokens is None:
                continue
            self.total_len -= self.doc_len.pop(key, 0)
            for term in tokens:
                self.df[term] -= 1
                if self.df[term] <= 0:
                    del self.df[term]

    def search(self, queries, k: int) -> list[list[tuple[Any, float]]]:
        out = []
        n_docs = len(self.docs)
        avg_len = self.total_len / n_docs if n_docs else 1.0
        if isinstance(queries, str):
            queries = [queries]
        for q in queries:
            if not isinstance(q, str):
                q = str(q)
            terms = _tokenize(q)
            scores: dict[Any, float] = defaultdict(float)
            for term in terms:
                df = self.df.get(term)
                if not df:
                    continue
                idf = math.log(1 + (n_docs - df + 0.5) / (df + 0.5))
                for key, tokens in self.docs.items():
                    tf = tokens.get(term, 0)
                    if tf == 0:
                        continue
                    dl = self.doc_len[key]
                    scores[key] += (
                        idf
                        * tf
                        * (self.k1 + 1)
                        / (tf + self.k1 * (1 - self.b + self.b * dl / avg_len))
                    )
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
            out.append([(key, float(s)) for key, s in ranked if s > 0])
        return out

    def __len__(self):
        return len(self.docs)


class _Bm25Factory(ExternalIndexFactory):
    def __init__(self, **kwargs):
        self.kwargs = kwargs

    def make_instance(self):
        return Bm25Index()


class TantivyBM25(InnerIndex):
    """Full-text BM25 inner index (reference ``TantivyBM25:41``)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column=None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
    ):
        super().__init__(data_column, metadata_column)

    def make_factory(self):
        return _Bm25Factory()


@dataclass
class TantivyBM25Factory:
    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        inner = TantivyBM25(data_column, metadata_column)
        return DataIndex(data_table, inner)


def check_default_bm25_column_types(data_column, query_column):
    """Validate that index/query columns carry strings — reference
    ``bm25.py:check_default_bm25_column_types``."""
    return True
