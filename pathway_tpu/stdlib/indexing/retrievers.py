"""Retriever factory protocol (reference ``stdlib/indexing/retrievers.py``)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class AbstractRetrieverFactory(ABC):
    @abstractmethod
    def build_index(self, data_column, data_table, metadata_column=None): ...
