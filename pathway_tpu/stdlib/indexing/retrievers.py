"""Retriever factory protocol (reference ``stdlib/indexing/retrievers.py``)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class AbstractRetrieverFactory(ABC):
    @abstractmethod
    def build_index(self, data_column, data_table, metadata_column=None): ...


class InnerIndexFactory(AbstractRetrieverFactory):
    """Factory whose indices are ``InnerIndex`` instances wrapped into a
    ``DataIndex`` (reference ``retrievers.py:17``)."""

    def build_inner_index(self, data_column, metadata_column=None):
        raise NotImplementedError

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex

        inner = self.build_inner_index(data_column, metadata_column)
        return DataIndex(data_table, inner)
