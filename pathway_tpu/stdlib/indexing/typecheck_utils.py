"""Column type validation helpers (reference
``stdlib/indexing/typecheck_utils.py``)."""

from __future__ import annotations


def check_column_reference_type(column, expected, name: str = "column") -> None:
    """Validate a ColumnReference's dtype against ``expected`` (a DType or
    tuple of DTypes); ANY always passes."""
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.type_interpreter import infer_dtype

    try:
        actual = infer_dtype(column, getattr(column, "table", None))
    except Exception:  # noqa: BLE001
        return
    if actual == dt.ANY:
        return
    allowed = expected if isinstance(expected, tuple) else (expected,)
    if actual not in allowed and actual.strip_optional() not in allowed:
        raise TypeError(
            f"{name} has dtype {actual!r}; expected one of {allowed!r}"
        )
