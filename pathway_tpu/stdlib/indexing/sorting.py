"""Sorted-index stdlib — BST over keys, prev/next retrieval.

API parity with reference ``stdlib/indexing/sorting.py`` (``hash:14``,
``build_sorted_index:92``, ``sort_from_index:137``,
``retrieve_prev_next_values:195`` + the schema vocabulary). The reference
assembles a treap with ``pw.iterate`` over grouped argmin steps; here the
columnar engine backs the same contracts with stateful recompute-and-diff
operators (``engine/operators/sorted_index.py``) — same outputs (balanced
search tree with left/right/parent, per-instance root oracle, in-order
prev/next pointers, nearest non-None values), better per-epoch complexity.
"""

from __future__ import annotations

from typing import Any, Optional, TypedDict

import pathway_tpu.internals.dtype as dt
from pathway_tpu.engine.operators import sorted_index as engine_ops
from pathway_tpu.engine.value import hash_values
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.schema import Schema
from pathway_tpu.internals.table import Table, _prepare_env
from pathway_tpu.internals.universe import Universe


def hash(val) -> int:  # noqa: A001 — reference exports this name
    """Deterministic i64 fingerprint (reference sorting.py:14)."""
    return hash_values(int(val)) & 0x7FFFFFFFFFFFFFFF


class Hash(Schema):
    hash: int


class Node(Schema):
    pass


class Key(Schema):
    key: float


class LeftRight(Schema):
    left: Optional[Any]
    right: Optional[Any]


class Parent(Schema):
    parent: Optional[Any]


class Candidate(Schema):
    candidate: Any


class Instance(Schema):
    instance: Any


class PrevNext(Schema):
    prev: Optional[Any]
    next: Optional[Any]


class SortedIndex(TypedDict):
    index: Table
    oracle: Table


def _env_node(table: Table, exprs: dict):
    env_node, _rewritten = _prepare_env(table, exprs)
    return env_node


def build_sorted_index(nodes: Table, instance=None) -> SortedIndex:
    """Balanced BST (left/right/parent) over the ``key`` column, one tree per
    ``instance``; plus a per-instance root oracle (reference
    ``build_sorted_index`` sorting.py:92-135)."""
    key_expr = nodes.key
    if instance is None and "instance" in nodes._schema.column_names():
        instance = nodes.instance
    exprs = {"__key__": key_expr}
    inst_col = None
    if instance is not None:
        exprs["__instance__"] = instance
        inst_col = "__instance__"
    env_node, rewritten = _prepare_env(nodes, exprs)
    from pathway_tpu.engine.operators import core as core_ops

    combo = core_ops.RowwiseNode(G.engine_graph, env_node, rewritten)
    index_node = engine_ops.BuildSortedIndexNode(
        G.engine_graph, combo, "__key__", inst_col
    )
    ptr_t = dt.Optional(dt.Pointer(None))
    index_schema = schema_mod.schema_from_types(
        key=dt.ANY, left=ptr_t, right=ptr_t, parent=ptr_t, instance=dt.ANY
    )
    index = Table(index_node, index_schema, nodes._universe)
    root_node = engine_ops.SortedIndexRootNode(G.engine_graph, index_node)
    oracle_schema = schema_mod.schema_from_types(
        instance=dt.ANY, root=dt.Pointer(None)
    )
    oracle = Table(root_node, oracle_schema, Universe())
    return dict(index=index, oracle=oracle)


def sort_from_index(index: Table, oracle=None) -> Table:
    """Tree (left/right/parent) → in-order prev/next pointers (reference
    ``sort_from_index`` sorting.py:137-170)."""
    env_node, rewritten = _prepare_env(
        index,
        {"left": index.left, "right": index.right, "parent": index.parent},
    )
    from pathway_tpu.engine.operators import core as core_ops

    combo = core_ops.RowwiseNode(G.engine_graph, env_node, rewritten)
    node = engine_ops.SortFromIndexNode(G.engine_graph, combo)
    ptr_t = dt.Optional(dt.Pointer(None))
    schema = schema_mod.schema_from_types(prev=ptr_t, next=ptr_t)
    return Table(node, schema, index._universe)


def retrieve_prev_next_values(ordered_table: Table, value=None) -> Table:
    """For each row, nearest non-None ``value`` walking backward (prev_value)
    and forward (next_value) along prev/next chains; a row's own value counts
    first (reference ``retrieve_prev_next_values`` sorting.py:195-230)."""
    if value is None:
        value = ordered_table.value
    env_node, rewritten = _prepare_env(
        ordered_table,
        {
            "prev": ordered_table.prev,
            "next": ordered_table.next,
            "value": expr_mod.smart_coerce(value),
        },
    )
    from pathway_tpu.engine.operators import core as core_ops

    combo = core_ops.RowwiseNode(G.engine_graph, env_node, rewritten)
    node = engine_ops.RetrievePrevNextValuesNode(G.engine_graph, combo)
    schema = schema_mod.schema_from_types(prev_value=dt.ANY, next_value=dt.ANY)
    return Table(node, schema, ordered_table._universe)
