"""Default full-text (BM25) document index (reference
``stdlib/indexing/full_text_document_index.py``)."""

from __future__ import annotations

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex


def default_full_text_document_index(
    data_column,
    data_table,
    *,
    metadata_column=None,
) -> DataIndex:
    inner = TantivyBM25(data_column, metadata_column)
    return DataIndex(data_table, inner)
