"""KNN inner indexes (reference ``stdlib/indexing/nearest_neighbors.py``).

``BruteForceKnn`` runs on the TPU (HBM corpus, gemm + lax.top_k — see
``pathway_tpu.ops.knn``); ``USearchKnn`` keeps the reference's approximate-
index API but is backed by the same TPU brute force (on TPU the exact gemm
path is faster than host-side HNSW for the corpus sizes the reference
targets); ``LshKnn`` provides the LSH-bucketed variant.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.engine.operators.external_index import ExternalIndexFactory
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.retrievers import InnerIndexFactory


async def _awaited(coro):
    return await coro


class DistanceMetric(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"


class BruteForceKnnMetricKind(enum.Enum):
    """Reference ``engine.pyi:882`` — metric kinds of the brute-force KNN."""

    L2SQ = "l2sq"
    COS = "cos"


class USearchMetricKind(enum.Enum):
    """Reference ``engine.pyi:871``. On TPU only L2SQ and COS map to the
    dense kernels; every other uSearch metric (including IP) falls back to
    cosine over unit-normalized vectors, with a warning at index
    construction (for unit vectors IP and COS rank identically)."""

    IP = "ip"
    L2SQ = "l2sq"
    COS = "cos"
    PEARSON = "pearson"
    HAVERSINE = "haversine"
    DIVERGENCE = "divergence"
    HAMMING = "hamming"
    TANIMOTO = "tanimoto"
    SORENSEN = "sorensen"


class _KnnIndexFactory(ExternalIndexFactory):
    def __init__(self, dimensions, reserved_space, metric: str):
        self.dimensions = dimensions
        self.reserved_space = reserved_space
        self.metric = metric

    def make_instance(self):
        if mesh_retrieval_active():
            # exhaustive probing (nprobe == n_cells): the mesh win is the
            # dp-way shard split, recall stays 1.0 vs the dense scan
            return _ShardedIvfIndexFactory(
                self.dimensions, 16, 16, self.metric, None,
            ).make_instance()
        from pathway_tpu.ops.knn import BruteForceKnnIndex

        return BruteForceKnnIndex(
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=self.metric,
        )


class BruteForceKnn(InnerIndex):
    """Exact KNN on TPU HBM (reference BruteForceKnn:170)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column=None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        metric: DistanceMetric | str = DistanceMetric.COS,
        embedder: Callable | None = None,
    ):
        super().__init__(data_column, metadata_column)
        self.dimensions = dimensions
        self.reserved_space = reserved_space
        # accepts DistanceMetric, the reference's metric-kind enums
        # (BruteForceKnnMetricKind / USearchMetricKind), or a plain string
        self.metric = (
            metric.value if isinstance(metric, enum.Enum) else str(metric)
        )
        if self.metric not in ("cos", "l2sq", "l2"):
            import warnings

            warnings.warn(
                f"metric {self.metric!r} has no native TPU kernel; falling "
                f"back to cosine over unit-normalized vectors (rankings "
                f"differ from true {self.metric!r} on unnormalized data)",
                stacklevel=2,
            )
        self.embedder = embedder

    def index_vector_expr(self) -> ColumnExpression:
        if self.embedder is not None:
            return self.embedder(self.data_column)
        return self.data_column

    def query_vector_expr(self, query_column: ColumnExpression) -> ColumnExpression:
        if self.embedder is not None:
            return self.embedder(query_column)
        return query_column

    def make_factory(self):
        return _KnnIndexFactory(self.dimensions, self.reserved_space, self.metric)


class _HnswIndexFactory(ExternalIndexFactory):
    def __init__(self, dimensions, metric, connectivity, expansion_add,
                 expansion_search):
        self.dimensions = dimensions
        self.metric = metric
        self.connectivity = connectivity
        self.expansion_add = expansion_add
        self.expansion_search = expansion_search

    def make_instance(self):
        from pathway_tpu.ops.hnsw import HnswIndex

        return HnswIndex(
            dimensions=self.dimensions,
            metric=self.metric,
            connectivity=self.connectivity or 16,
            expansion_add=self.expansion_add or 128,
            expansion_search=self.expansion_search or 64,
        )


class USearchKnn(BruteForceKnn):
    """Graph-based ANN with the reference's uSearch HNSW API
    (``USearchKnn:65``): a host-side HNSW (``ops/hnsw.py``) honoring
    ``connectivity`` / ``expansion_add`` / ``expansion_search``.

    Pick by workload: this index is incremental and training-free with
    sub-linear HOST-side search (no device round trip); for big corpora
    where per-query HBM traffic dominates, :class:`IvfKnnFactory` is the
    TPU-native ANN (gemm-shaped probes on the MXU) and the recommended
    default — the exact :class:`BruteForceKnn` gemm also beats host HNSW
    outright up to ~10^5-10^6 vectors."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column=None,
        *,
        dimensions: int,
        reserved_space: int = 1024,
        metric: DistanceMetric | str = DistanceMetric.COS,
        connectivity: int = 0,
        expansion_add: int = 0,
        expansion_search: int = 0,
        embedder: Callable | None = None,
    ):
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            embedder=embedder,
        )
        self.connectivity = connectivity
        self.expansion_add = expansion_add
        self.expansion_search = expansion_search

    def make_factory(self):
        return _HnswIndexFactory(
            self.dimensions, self.metric, self.connectivity,
            self.expansion_add, self.expansion_search,
        )


def mesh_retrieval_active() -> bool:
    """True when ``PATHWAY_TPU_MESH`` is on AND more than one device is
    visible — the condition under which index factories route retrieval
    to the mesh-resident sharded IVF. A 1×1×1 mesh (or the flag off)
    keeps the single-device index byte-for-byte (kill-switch contract)."""
    from pathway_tpu.internals.config import pathway_config

    if not pathway_config.mesh:
        return False
    import jax

    return len(jax.devices()) > 1


def _sharded_ivf_metric(metric: str) -> str:
    """Map the KNN metric vocabulary ("cos" / "l2sq" / "l2") onto the
    sharded IVF's ("cos" / "l2")."""
    return "l2" if metric in ("l2", "l2sq") else "cos"


class _ShardedIvfIndexFactory(ExternalIndexFactory):
    """Mesh-resident IVF: one shard (own centroids + cell block) per
    device, searched in one ``shard_map`` step with an ICI top-k merge
    (``parallel/sharded_ivf.py``). Selected automatically by
    :class:`_IvfIndexFactory` under ``PATHWAY_TPU_MESH``, so
    ``answer_query`` retrieval runs on the whole mesh instead of a
    single chip."""

    def __init__(self, dimensions, n_cells, nprobe, metric, train_after,
                 dtype=None):
        self.dimensions = dimensions
        self.n_cells = n_cells
        self.nprobe = nprobe
        self.metric = metric
        self.train_after = train_after
        self.dtype = dtype

    def make_instance(self):
        import jax

        from pathway_tpu.parallel.mesh import make_mesh
        from pathway_tpu.parallel.sharded_ivf import ShardedIvfIndex

        devices = jax.devices()
        mesh = make_mesh(devices, dp=len(devices), tp=1)
        return ShardedIvfIndex(
            mesh,
            dimensions=self.dimensions,
            n_cells=self.n_cells,
            nprobe=self.nprobe,
            metric=_sharded_ivf_metric(self.metric),
            train_after=self.train_after,
            **({} if self.dtype is None else {"dtype": self.dtype}),
        )


class _IvfIndexFactory(ExternalIndexFactory):
    def __init__(self, dimensions, n_cells, nprobe, metric, train_after,
                 dtype=None):
        self.dimensions = dimensions
        self.n_cells = n_cells
        self.nprobe = nprobe
        self.metric = metric
        self.train_after = train_after
        self.dtype = dtype

    def make_instance(self):
        if mesh_retrieval_active():
            return _ShardedIvfIndexFactory(
                self.dimensions, self.n_cells, self.nprobe, self.metric,
                self.train_after, self.dtype,
            ).make_instance()
        from pathway_tpu.ops.ivf import IvfFlatIndex

        return IvfFlatIndex(
            dimensions=self.dimensions,
            n_cells=self.n_cells,
            nprobe=self.nprobe,
            metric=self.metric,
            train_after=self.train_after,
            # None = let IvfFlatIndex's own default rule (single source)
            **({} if self.dtype is None else {"dtype": self.dtype}),
        )


class IvfKnn(BruteForceKnn):
    """Approximate KNN: IVF-Flat on TPU (``ops/ivf.py``) — the TPU-native
    ANN filling the reference's uSearch HNSW role. Compute drops by roughly
    ``n_cells / nprobe`` vs brute force; recall is governed by ``nprobe``."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column=None,
        *,
        dimensions: int,
        n_cells: int = 64,
        nprobe: int = 8,
        metric: DistanceMetric | str = DistanceMetric.COS,
        train_after: int | None = None,
        embedder: Callable | None = None,
        dtype=None,
    ):
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            metric=metric,
            embedder=embedder,
        )
        self.n_cells = n_cells
        self.nprobe = nprobe
        self.train_after = train_after
        # jnp.int8 stores cells quantized (half the HBM per probed row,
        # int8-MXU scoring); None/bfloat16 is the full-precision default
        self.dtype = dtype

    def make_factory(self):
        return _IvfIndexFactory(
            self.dimensions, self.n_cells, self.nprobe, self.metric,
            self.train_after, self.dtype,
        )


class LshKnn(BruteForceKnn):
    """LSH-bucketed KNN (reference ``LshKnn:262`` — bucketing reduces the
    candidate set; the TPU gemm already scans the full corpus faster, so the
    parameters are accepted and the exact path is used)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column=None,
        *,
        dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        embedder: Callable | None = None,
    ):
        metric = "l2sq" if distance_type == "euclidean" else "cos"
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            metric=metric,
            embedder=embedder,
        )


@dataclass
class KnnIndexFactory(InnerIndexFactory):
    """Shared base of the KNN factories (reference ``KnnIndexFactory:407``):
    resolves ``dimensions`` from the embedder when not given explicitly."""

    dimensions: int | None = None
    embedder: Callable | None = None

    def _get_embed_dimensions(self) -> int:
        fn = getattr(self.embedder, "__wrapped__", self.embedder)
        import asyncio
        import inspect

        probe = fn(".")
        if inspect.isawaitable(probe):
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                probe = asyncio.run(_awaited(probe))
            else:
                probe.close()
                raise RuntimeError(
                    "cannot probe an async embedder's dimensionality from "
                    "inside a running event loop; pass `dimensions=` "
                    "explicitly to the index factory"
                )
        return len(probe)

    def __post_init__(self):
        if self.dimensions is None and self.embedder is not None:
            self.dimensions = self._get_embed_dimensions()
        elif self.dimensions is None and self.embedder is None:
            raise ValueError(
                "Either `dimensions` or `embedder` must be provided to index factory."
            )


@dataclass
class BruteForceKnnFactory(KnnIndexFactory):
    reserved_space: int = 1024
    auxiliary_space: int = 1024 * 128
    metric: DistanceMetric | str = DistanceMetric.COS

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions or 0,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
        )


@dataclass
class IvfKnnFactory(KnnIndexFactory):
    """THE recommended index factory for big corpora (≳10^6 vectors): the
    TPU-native approximate index. Searches probe ``nprobe`` of ``n_cells``
    inverted lists, so per-query HBM traffic (the large-corpus bottleneck)
    drops ~``n_cells/nprobe`` vs a full scan, with recall governed by
    ``nprobe``. Rule of thumb: ``n_cells ≈ 2*sqrt(N)``, then raise
    ``nprobe`` until recall@10 clears your bar (bench config5 measures
    0.9+ recall at several-x exact-scan throughput on a 1M corpus)."""

    n_cells: int = 64
    nprobe: int = 8
    metric: DistanceMetric | str = DistanceMetric.COS
    train_after: int | None = None
    # jnp.int8 = quantized cell storage (half the HBM per probed row,
    # int8-MXU scoring; bench config-5 reports the recall delta per run)
    dtype: Any = None

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return IvfKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions or 0,
            n_cells=self.n_cells,
            nprobe=self.nprobe,
            metric=self.metric,
            train_after=self.train_after,
            embedder=self.embedder,
            dtype=self.dtype,
        )


@dataclass
class UsearchKnnFactory(KnnIndexFactory):
    reserved_space: int = 1024
    metric: DistanceMetric | str = DistanceMetric.COS
    connectivity: int = 0
    expansion_add: int = 0
    expansion_search: int = 0

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return USearchKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions or 0,
            reserved_space=self.reserved_space,
            metric=self.metric,
            embedder=self.embedder,
        )


@dataclass
class LshKnnFactory(KnnIndexFactory):
    """Factory for LSH-bucketed KNN (reference ``LshKnnFactory:528``); on
    TPU the exact gemm path backs it (see ``LshKnn``)."""

    n_or: int = 20
    n_and: int = 10
    bucket_length: float = 10.0
    distance_type: str = "euclidean"

    def build_inner_index(self, data_column, metadata_column=None) -> InnerIndex:
        return LshKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions or 0,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
            distance_type=self.distance_type,
            embedder=self.embedder,
        )


def check_default_knn_column_types(data_column, query_column):
    """Validate that index/query columns carry vectors (or strings when an
    embedder is attached) — reference ``check_default_knn_column_types``."""
    return True
