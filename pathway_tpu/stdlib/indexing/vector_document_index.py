"""Default vector document index constructors (reference
``stdlib/indexing/vector_document_index.py``)."""

from __future__ import annotations

from typing import Callable

from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    LshKnn,
    USearchKnn,
)


def VectorDocumentIndex(
    data_column,
    data_table,
    *,
    dimensions: int,
    embedder: Callable | None = None,
    metadata_column=None,
):
    """Deprecated alias of ``default_vector_document_index`` (reference
    ``vector_document_index.py:12``)."""
    import warnings

    warnings.warn(
        "this part of API will be removed soon, "
        "please use default_vector_document_index instead",
        DeprecationWarning,
        stacklevel=2,
    )
    return default_vector_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_vector_document_index(
    data_column,
    data_table,
    *,
    embedder: Callable | None = None,
    dimensions: int,
    metadata_column=None,
) -> DataIndex:
    return default_brute_force_knn_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_brute_force_knn_document_index(
    data_column,
    data_table,
    *,
    embedder: Callable | None = None,
    dimensions: int,
    metadata_column=None,
) -> DataIndex:
    inner = BruteForceKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_usearch_knn_document_index(
    data_column,
    data_table,
    *,
    embedder: Callable | None = None,
    dimensions: int,
    metadata_column=None,
) -> DataIndex:
    inner = USearchKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        reserved_space=1024,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_lsh_knn_document_index(
    data_column,
    data_table,
    *,
    embedder: Callable | None = None,
    dimensions: int,
    metadata_column=None,
) -> DataIndex:
    inner = LshKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)
