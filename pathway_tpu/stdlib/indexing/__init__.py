"""``pw.indexing`` — vector/text indexes and the DataIndex retrieval API.

Parity with reference ``python/pathway/stdlib/indexing/``: ``DataIndex``
(``query`` / ``query_as_of_now``), inner indexes (``BruteForceKnn`` — TPU
HBM gemm+top-k, ``UsearchKnn`` — approximate (here: same TPU brute force, the
exact index dominates it on TPU), ``TantivyBM25`` — host-side BM25,
``LshKnn``), ``HybridIndex`` (RRF fusion), default factories.
"""

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.hybrid_index import (
    HybridIndex,
    HybridIndexDataIndex,
    HybridIndexFactory,
)
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    IvfKnn,
    IvfKnnFactory,
    KnnIndexFactory,
    LshKnnFactory,
    DistanceMetric,
    LshKnn,
    USearchKnn,
    USearchMetricKind,
    UsearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.retrievers import (
    AbstractRetrieverFactory,
    InnerIndexFactory,
)
from pathway_tpu.stdlib.indexing.vector_document_index import (
    VectorDocumentIndex,
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)
from pathway_tpu.stdlib.indexing.sorting import (
    SortedIndex,
    build_sorted_index,
    retrieve_prev_next_values,
    sort_from_index,
)

__all__ = [
    "BruteForceKnnMetricKind",
    "USearchMetricKind",
    "SortedIndex",
    "build_sorted_index",
    "retrieve_prev_next_values",
    "sort_from_index",
    "DataIndex",
    "InnerIndex",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "IvfKnn",
    "IvfKnnFactory",
    "USearchKnn",
    "UsearchKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "KnnIndexFactory",
    "InnerIndexFactory",
    "VectorDocumentIndex",
    "DistanceMetric",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexDataIndex",
    "HybridIndexFactory",
    "AbstractRetrieverFactory",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_usearch_knn_document_index",
    "default_lsh_knn_document_index",
    "default_full_text_document_index",
]
