"""Hybrid retrieval with Reciprocal Rank Fusion (reference
``stdlib/indexing/hybrid_index.py:14``): fuse rankings from several
DataIndexes (e.g. vector KNN + BM25)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod


class HybridIndex:
    def __init__(self, inner_indexes: list, k: float = 60.0):
        self.inner_indexes = inner_indexes
        self.k = k


class HybridIndexDataIndex:
    """DataIndex-like facade fusing results of several DataIndexes."""

    def __init__(self, indexes: list, k: float = 60.0):
        self.indexes = indexes
        self.k = k

    def query_as_of_now(
        self,
        query_column,
        *,
        number_of_matches: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter=None,
    ):
        if not collapse_rows:
            raise NotImplementedError("hybrid index returns collapsed rows")
        k_rrf = self.k
        sub_results = [
            idx.query_as_of_now(
                query_column,
                number_of_matches=number_of_matches * 2,
                collapse_rows=True,
                with_distances=False,
                metadata_filter=metadata_filter,
            )
            for idx in self.indexes
        ]
        data_cols = sub_results[0].column_names()
        base = sub_results[0]
        combined = base
        # zip sub-results per query key (same universe: the query table)
        packed_cols = {}
        for i, sub in enumerate(sub_results):
            for c in data_cols:
                packed_cols[f"__s{i}_{c}"] = sub[c]
        packed = base.select(**packed_cols)

        n_idx = len(sub_results)

        def fuse(*tuples_per_index):
            # tuples_per_index: for each sub-index, the per-column tuples in
            # rank order; fuse by RRF over the first column's identity
            scores: dict[Any, float] = {}
            rows: dict[Any, tuple] = {}
            per_index_cols = [
                tuples_per_index[i * len(data_cols) : (i + 1) * len(data_cols)]
                for i in range(n_idx)
            ]
            for cols in per_index_cols:
                first = cols[0]
                for rank, ident in enumerate(first):
                    row = tuple(col[rank] for col in cols)
                    key = repr(row)
                    scores[key] = scores.get(key, 0.0) + 1.0 / (k_rrf + rank + 1)
                    rows[key] = row
            ranked = sorted(scores.items(), key=lambda kv: -kv[1])[:number_of_matches]
            fused_cols = []
            for ci in range(len(data_cols)):
                fused_cols.append(tuple(rows[key][ci] for key, _s in ranked))
            return tuple(fused_cols)

        fused = packed.select(
            __fused=expr_mod.apply_with_type(
                fuse,
                dt.ANY_TUPLE,
                *[packed[f"__s{i}_{c}"] for i in range(n_idx) for c in data_cols],
            )
        )
        return fused.select(
            **{
                c: expr_mod.GetExpression(fused["__fused"], ci, check_if_exists=False)
                for ci, c in enumerate(data_cols)
            }
        )

    query = query_as_of_now


@dataclass
class HybridIndexFactory:
    retriever_factories: list = field(default_factory=list)
    k: float = 60.0

    def build_index(self, data_column, data_table, metadata_column=None):
        indexes = [
            f.build_index(data_column, data_table, metadata_column=metadata_column)
            for f in self.retriever_factories
        ]
        return HybridIndexDataIndex(indexes, self.k)
