"""Workload-driven autotuner for the flag surface (ROADMAP item 6).

``python -m pathway_tpu.cli tune <profile>`` searches the registry's
``Tunable`` flags for one :data:`~pathway_tpu.tuning.profiles.PROFILES`
entry, validates survivors under the SLO watchdog + a chaos drill, and
persists the winner as a tuned-config JSON that
``PATHWAY_TPU_TUNED_CONFIG=<path>`` loads at startup (explicit env vars
still win, flag-by-flag)."""

from pathway_tpu.tuning.profiles import (  # noqa: F401
    PROFILES,
    WorkloadProfile,
    decoder_resources,
    get_profile,
    run_trial,
)
from pathway_tpu.tuning.search import (  # noqa: F401
    Autotuner,
    TuneError,
    TuneResult,
    candidate_axes,
    save_artifact,
    to_artifact,
)
