"""Successive-halving search over the registry's tunable flag surface.

The search space is coordinate-wise: for each env name in the profile's
``tunables`` the flag's :class:`Tunable` spec yields a deterministic
candidate ladder, and each non-default rung becomes one single-flag
candidate. Successive halving evaluates the (seeded, shuffled) pool at
a small trace scale, keeps the better half, and re-runs survivors at
double scale until ≤ 2 remain; the per-coordinate winners are then
composed into one combined candidate. Obviously-bad trials early-abort
on a wall-clock budget derived from the best trial so far.

Surviving candidates are not trusted on speed alone: each is re-run
under the PR-9 SLO watchdog (profile objectives armed, zero alerts and
zero sheds required) and — when the profile has a serving fault surface
— under a ``PATHWAY_TPU_CHAOS`` drill (every request must still reach a
terminal state). A "faster" config that breaches p95 or shatters under
faults is rejected and the next-ranked candidate is tried. The winner
persists as a JSON tuned-config artifact that ``internals/config.py``
loads via ``PATHWAY_TPU_TUNED_CONFIG`` (explicit env vars still win).

The trial evaluator and the validator are injectable (``evaluate=`` /
``validate=``), so ``tests/test_autotune.py`` drives the whole search
against a synthetic cost model with no device work at all.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field

import numpy as np

from pathway_tpu.internals.config import (
    _REGISTRY_BY_ENV,
    pathway_config,
)
from pathway_tpu.tuning import profiles as profiles_mod

ARTIFACT_VERSION = 1

# validation re-runs a surviving candidate twice (SLO leg + chaos leg);
# walking every trial through that would double the search cost, so only
# the best few are eligible before the search declares failure
VALIDATE_TOP = 3


class TuneError(RuntimeError):
    """No candidate survived search + validation (or the search space
    was empty). The CLI maps this to a nonzero exit."""


@dataclass
class Trial:
    flags: dict
    scale: float
    metrics: dict | None
    score: float


@dataclass
class TuneResult:
    profile: str
    headline: str
    direction: str
    seed: int
    winner: dict | None  # env -> raw value (empty = defaults won)
    winner_score: float
    winner_metrics: dict | None
    baseline_score: float
    baseline_metrics: dict | None
    validation: dict = field(default_factory=dict)
    rejected: list = field(default_factory=list)
    trials: list = field(default_factory=list)


def candidate_axes(profile) -> dict[str, list[str]]:
    """env name → non-default candidate raw values, in declaration
    order. Every tunable env must carry a ``Tunable`` spec (GL204 keeps
    the specs well-formed)."""
    profile = profiles_mod.get_profile(profile)
    axes: dict[str, list[str]] = {}
    for env in profile.tunables:
        flag = _REGISTRY_BY_ENV.get(env)
        if flag is None or flag.tunable is None:
            raise TuneError(
                f"profile {profile.name!r}: {env} has no Tunable spec "
                "in FLAG_REGISTRY"
            )
        default = flag.render_default()
        cands = [
            c for c in flag.tunable.candidates()
            if flag.parse_raw(c) != flag.parse_raw(default)
        ]
        if cands:
            axes[env] = cands
    return axes


def _score(profile, metrics: dict | None) -> float:
    """Direction-normalized scalar: higher is always better; broken /
    aborted / non-terminal trials sink to -inf so halving drops them."""
    if not metrics or metrics.get("aborted") or not metrics.get(
        "terminal_ok", False
    ):
        return float("-inf")
    v = metrics.get(profile.headline)
    if v is None:
        return float("-inf")
    v = float(v)
    return v if profile.direction == "max" else -v


def _flags_key(flags: dict) -> str:
    return json.dumps(flags, sort_keys=True)


class Autotuner:
    """One profile-keyed search: deterministic given ``(profile,
    seed)``.

    ``evaluate(flags, scale, deadline_s) -> metrics`` defaults to
    :func:`pathway_tpu.tuning.profiles.run_trial`;
    ``validate(flags) -> (ok, reason, detail)`` defaults to the
    SLO + chaos drill. Both are injectable for device-free tests."""

    def __init__(
        self,
        profile,
        *,
        seed: int | None = None,
        max_trials: int | None = None,
        base_scale: float = 1.0,
        validation_scale: float | None = None,
        rounds: int = 3,
        evaluate=None,
        validate=None,
        resources=None,
    ):
        self.profile = profiles_mod.get_profile(profile)
        self.seed = int(
            pathway_config.tune_seed if seed is None else seed
        )
        cap = pathway_config.tune_trials if max_trials is None else max_trials
        self.max_trials = int(cap) if cap else 0  # 0 = schedule decides
        self.base_scale = float(base_scale)
        self.validation_scale = float(
            validation_scale if validation_scale is not None else base_scale
        )
        self.rounds = int(rounds)
        self.resources = resources
        self._evaluate = evaluate or self._real_evaluate
        self._validate = validate or self._real_validate
        self._best_wall: float | None = None
        self.trials: list[Trial] = []

    # -- trial plumbing ------------------------------------------------

    def _real_evaluate(self, flags, scale, deadline_s):
        return profiles_mod.run_trial(
            self.profile, flags, scale=scale, seed=self.seed,
            deadline_s=deadline_s, resources=self.resources,
        )

    def _deadline(self, scale: float) -> float | None:
        # early-abort budget: 4x the best completed trial's
        # scale-normalized wall (with floor headroom), stretched to the
        # current scale — an obviously-bad config stops burning time,
        # while halving's doubled traces get proportional room
        if self._best_wall is None:
            return None
        return max(4.0 * self._best_wall * scale, 2.0)

    def _run_trial(self, flags: dict, scale: float) -> Trial:
        try:
            metrics = self._evaluate(
                dict(flags), scale, self._deadline(scale)
            )
        except Exception as exc:  # a crashing config is a losing config
            metrics = {"error": f"{type(exc).__name__}: {exc}",
                       "terminal_ok": False}
        score = _score(self.profile, metrics)
        if (
            metrics and not metrics.get("aborted")
            and metrics.get("wall_s")
        ):
            w = float(metrics["wall_s"]) / max(float(scale), 1e-9)
            if self._best_wall is None or w < self._best_wall:
                self._best_wall = w
        t = Trial(dict(flags), float(scale), metrics, score)
        self.trials.append(t)
        return t

    # -- the search ----------------------------------------------------

    def _candidates(self) -> list[dict]:
        axes = candidate_axes(self.profile)
        cands = [
            {env: raw} for env, values in axes.items() for raw in values
        ]
        rng = np.random.default_rng(self.seed)
        rng.shuffle(cands)
        if self.max_trials:
            # budgeted run (CLI --smoke): baseline + the first cap-1
            # shuffled candidates — still deterministic per seed
            cands = cands[:max(self.max_trials - 1, 1)]
        return [{}] + cands

    def run(self) -> TuneResult:
        profile = self.profile
        cands = self._candidates()
        if len(cands) <= 1:
            raise TuneError(
                f"profile {profile.name!r}: empty search space"
            )
        # successive halving: evaluate the pool, keep the top half,
        # double the trace scale, repeat
        scale = self.base_scale
        pop = cands
        latest: dict[str, Trial] = {}
        for rnd in range(self.rounds):
            for flags in pop:
                latest[_flags_key(flags)] = self._run_trial(flags, scale)
            if len(pop) <= 2:
                break
            ranked = sorted(
                pop,
                key=lambda f: (
                    -latest[_flags_key(f)].score, len(f), _flags_key(f)
                ),
            )
            pop = ranked[:max(2, math.ceil(len(ranked) / 2))]
            scale *= 2.0
        baseline = latest[_flags_key({})]

        # compose the per-axis winners that individually beat baseline
        best_per_axis: dict[str, tuple[float, str]] = {}
        for key, t in latest.items():
            if len(t.flags) != 1 or t.score <= baseline.score:
                continue
            ((env, raw),) = t.flags.items()
            cur = best_per_axis.get(env)
            if cur is None or t.score > cur[0]:
                best_per_axis[env] = (t.score, raw)
        composed = {env: raw for env, (_, raw) in sorted(
            best_per_axis.items()
        )}
        if len(composed) > 1 and _flags_key(composed) not in latest:
            latest[_flags_key(composed)] = self._run_trial(composed, scale)

        # rank everything we measured; validate best-first
        ranked = sorted(
            latest.values(),
            key=lambda t: (-t.score, len(t.flags), _flags_key(t.flags)),
        )
        rejected: list[dict] = []
        winner: Trial | None = None
        validation: dict = {}
        for t in ranked[:VALIDATE_TOP]:
            if t.score == float("-inf"):
                break
            ok, reason, detail = self._validate(dict(t.flags))
            if ok:
                winner, validation = t, detail
                break
            rejected.append({
                "flags": dict(t.flags), "score": t.score, "reason": reason,
                "detail": detail,
            })
        if winner is None:
            raise TuneError(
                f"profile {profile.name!r}: no candidate survived "
                f"validation ({len(rejected)} rejected: "
                f"{[r['reason'] for r in rejected]})"
            )
        return TuneResult(
            profile=profile.name,
            headline=profile.headline,
            direction=profile.direction,
            seed=self.seed,
            winner=dict(winner.flags),
            winner_score=winner.score,
            winner_metrics=winner.metrics,
            baseline_score=baseline.score,
            baseline_metrics=baseline.metrics,
            validation=validation,
            rejected=rejected,
            trials=[
                {"flags": t.flags, "scale": t.scale, "score": t.score,
                 "metrics": t.metrics}
                for t in self.trials
            ],
        )

    # -- validation: SLO watchdog + chaos drill -------------------------

    def _real_validate(self, flags: dict):
        profile = self.profile
        detail: dict = {}
        # SLO leg: profile objectives armed, watchdog constructed inside
        # the trial's override scope, force-ticked after the trace
        slo_metrics = profiles_mod.run_trial(
            profile, {**flags, **profile.slo}, scale=self.validation_scale,
            seed=self.seed + 1, resources=self.resources, arm_slo=True,
        )
        detail["slo"] = slo_metrics
        if not slo_metrics.get("terminal_ok"):
            return False, "slo_leg_not_terminal", detail
        if slo_metrics.get("shed", 0) or slo_metrics.get("failures", 0):
            return False, "slo_leg_shed_or_failed", detail
        if slo_metrics.get("slo_alerting") or slo_metrics.get(
            "slo_breaches", 0
        ):
            return False, "slo_breach", detail
        # chaos drill: same trace with deterministic fault injection and
        # a restart/retry budget — the config must stay terminal and
        # never shed (faults fail single requests at worst)
        if profile.chaos_sites:
            chaos_metrics = profiles_mod.run_trial(
                profile,
                {
                    **flags,
                    "PATHWAY_TPU_CHAOS": str(
                        pathway_config.tune_chaos_rate
                    ),
                    "PATHWAY_TPU_CHAOS_SITES": profile.chaos_sites,
                    "PATHWAY_TPU_CHAOS_SEED": str(self.seed + 7),
                    "PATHWAY_TPU_SERVE_RESTARTS": "2",
                    "PATHWAY_TPU_SERVE_RETRIES": "4",
                },
                scale=self.validation_scale, seed=self.seed + 2,
                resources=self.resources,
            )
            detail["chaos"] = chaos_metrics
            if not chaos_metrics.get("terminal_ok"):
                return False, "chaos_not_terminal", detail
            if chaos_metrics.get("shed", 0):
                return False, "chaos_shed", detail
        return True, "", detail


# --------------------------------------------------------------------- #
# artifact persistence (the JSON `PATHWAY_TPU_TUNED_CONFIG` loads)


def to_artifact(result: TuneResult) -> dict:
    return {
        "version": ARTIFACT_VERSION,
        "profile": result.profile,
        "headline": result.headline,
        "direction": result.direction,
        "seed": result.seed,
        "flags": dict(result.winner or {}),
        "score": result.winner_score,
        "baseline_score": result.baseline_score,
        "metrics": result.winner_metrics,
        "baseline_metrics": result.baseline_metrics,
        "validation": result.validation,
    }


def save_artifact(result: TuneResult, path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_artifact(result), f, indent=2, sort_keys=True)
        f.write("\n")
    return path
