"""Workload profiles for the autotuner.

A :class:`WorkloadProfile` is a reusable, seeded trace shape — factored
out of the ``bench.py`` serving/ingest traces — plus the slice of the
flag surface worth searching for it and the SLO objectives a winning
config must hold. ``run_trial`` plays one profile against the REAL
serving/ingest stack in-process (a continuous ``TPUDecoderChat`` server
or a pipelined ``SentenceEmbedderModel``), with the candidate flags
applied through :func:`pathway_tpu.internals.config.flag_overrides`
(``construction=True`` — every consuming object is built inside the
scope), and scores it off the PR-7 metrics registry: tok/s, TTFT/e2e
p95, occupancy, prefix hit rate, shed/restart counts.

Trials are deterministic given ``(profile, scale, seed)``: arrivals and
prompt tails come from a profile-keyed ``np.random.default_rng``, and
decoding is greedy.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field

import numpy as np

from pathway_tpu.internals.config import flag_overrides

_REQ_TIMEOUT_S = 120.0


class _CharTok:
    """1-token-per-char toy tokenizer (the bench serving traces' shape):
    keeps trial prompts byte-countable and vocab tiny."""

    eos_id = None  # budget-bounded: every request costs max_new tokens

    def encode(self, text):
        return [(ord(c) % 96) + 1 for c in text]

    def decode(self, ids):
        return "".join(chr((int(i) % 96) + 32) for i in ids)


@dataclass(frozen=True)
class WorkloadProfile:
    """One named trace shape + its searchable flag slice.

    ``headline``/``direction`` name the metric a trial is ranked by
    (``"max"`` throughput-like, ``"min"`` latency-like). ``tunables``
    are the registry env names the tuner may vary — each must carry a
    ``Tunable`` spec. ``base_flags`` pin the scenario itself (e.g. the
    tenant scheduler ON for the burst profile) and apply to every arm,
    including the all-defaults baseline. ``slo`` arms the PR-9 watchdog
    objectives for the validation leg; ``chaos_sites`` names the sites
    the chaos drill arms (empty = no serving fault surface, skip the
    drill)."""

    name: str
    doc: str
    headline: str
    direction: str  # "max" | "min"
    tunables: tuple[str, ...]
    base_flags: dict = field(default_factory=dict)
    slo: dict = field(default_factory=dict)
    # the drill arms the request-scoped admission site only: dispatch
    # faults kill the whole serving loop and burn the restart budget,
    # which is a fleet-level recovery story, not a per-config one
    chaos_sites: str = "decode.admit"
    kind: str = "serving"  # "serving" | "ingest"
    # trace shape (serving)
    nreq: int = 24
    max_new: int = 12
    n_slots: int = 4
    chunk_steps: int = 4
    lam: float = 40.0  # Poisson arrival rate, requests/s
    head_len: int = 48
    tail_len: int = 8
    prompt_cap: int = 64
    burst: int = 0  # >0: arrivals come in back-to-back bursts this size
    tenants: tuple[str, ...] = ()
    # trace shape (ingest)
    rows: int = 96
    dup_rate: float = 0.5


PROFILES: dict[str, WorkloadProfile] = {
    p.name: p
    for p in [
        WorkloadProfile(
            name="long_doc_rag",
            doc="Long-document RAG: distinct ~88-token prompts, short "
                "answers — admission cost dominates, so chunked-prefill "
                "shape and the disagg prefill lane set the TTFT tail.",
            headline="ttft_p95_ms", direction="min",
            tunables=(
                "PATHWAY_TPU_PREFILL_CHUNK",
                "PATHWAY_TPU_CHUNKED_PREFILL",
                "PATHWAY_TPU_PREFILL_OVERLAP",
                "PATHWAY_TPU_DISAGG",
                "PATHWAY_TPU_DISAGG_PREFILL_BUDGET",
            ),
            slo={"PATHWAY_TPU_SLO_E2E_P95_MS": "30000"},
            nreq=20, max_new=8, n_slots=4, chunk_steps=4, lam=30.0,
            head_len=80, tail_len=8, prompt_cap=96,
        ),
        WorkloadProfile(
            name="shared_prefix_chat",
            doc="Chat/RAG serving with a shared system-prompt head and "
                "short distinct tails — the prefix KV cache, speculative "
                "depth and admission batching set steady-state tok/s.",
            headline="tok_s", direction="max",
            tunables=(
                "PATHWAY_TPU_PREFIX_CACHE",
                "PATHWAY_TPU_PREFIX_CACHE_MB",
                "PATHWAY_TPU_PREFIX_BLOCK",
                "PATHWAY_TPU_SPEC_DECODE",
                "PATHWAY_TPU_SPEC_DECODE_K",
                "PATHWAY_TPU_CHUNK_AUTOTUNE",
                "PATHWAY_TPU_BATCH_ADMIT",
            ),
            slo={"PATHWAY_TPU_SLO_E2E_P95_MS": "30000"},
            nreq=24, max_new=16, n_slots=4, chunk_steps=4, lam=40.0,
            head_len=48, tail_len=8, prompt_cap=64,
        ),
        WorkloadProfile(
            name="multi_tenant_burst",
            doc="Two tenants (prod:batch at 3:1 weight), arrivals in "
                "back-to-back bursts — fairness budgets and refill "
                "policy set the end-to-end tail.",
            headline="e2e_p95_ms", direction="min",
            tunables=(
                "PATHWAY_TPU_TENANT_BUDGET",
                "PATHWAY_TPU_EAGER_REFILL",
                "PATHWAY_TPU_BATCH_ADMIT",
                "PATHWAY_TPU_SPEC_DECODE",
            ),
            base_flags={
                "PATHWAY_TPU_TENANT_SCHED": "1",
                "PATHWAY_TPU_TENANT_WEIGHTS": "prod:3,batch:1",
            },
            slo={"PATHWAY_TPU_SLO_E2E_P95_MS": "30000"},
            nreq=24, max_new=12, n_slots=4, chunk_steps=4, lam=60.0,
            head_len=40, tail_len=8, prompt_cap=64, burst=6,
            tenants=("prod", "prod", "prod", "batch"),
        ),
        WorkloadProfile(
            name="retraction_heavy_ingest",
            doc="Churny ingest: half the rows are re-ingested duplicates "
                "of earlier ones — pipeline depth and queue bound set "
                "rows/s through the tokenize→h2d→dispatch stages.",
            headline="rows_per_s", direction="max",
            tunables=(
                "PATHWAY_TPU_PIPELINE_DEPTH",
                "PATHWAY_TPU_PIPELINE_QUEUE",
            ),
            chaos_sites="", kind="ingest",
            rows=96, dup_rate=0.5,
        ),
        WorkloadProfile(
            name="smoke",
            doc="Seconds-scale CI profile (`cli tune smoke --smoke`): a "
                "tiny shared-head trace over one axis, just enough to "
                "keep the search/validate/persist path from rotting.",
            headline="tok_s", direction="max",
            tunables=("PATHWAY_TPU_PREFILL_CHUNK",),
            nreq=6, max_new=8, n_slots=4, chunk_steps=4, lam=50.0,
            head_len=24, tail_len=8, prompt_cap=48,
        ),
    ]
}


def get_profile(profile) -> WorkloadProfile:
    if isinstance(profile, WorkloadProfile):
        return profile
    try:
        return PROFILES[str(profile)]
    except KeyError:
        raise KeyError(
            f"unknown workload profile {profile!r}; "
            f"available: {sorted(PROFILES)}"
        ) from None


# --------------------------------------------------------------------- #
# shared trial resources (built once per process — trials vary FLAGS,
# so the decoder weights can be shared across every candidate)

_DECODER_RES = None


def decoder_resources():
    """(params, cfg, tokenizer) for the serving profiles: a tiny seeded
    decoder, shared process-wide. ``run_trial(..., resources=)`` lets
    bench.py substitute its own checkpoint."""
    global _DECODER_RES
    if _DECODER_RES is None:
        import jax
        import jax.numpy as jnp

        from pathway_tpu.models import decoder as D

        cfg = D.DecoderConfig(
            vocab_size=128, hidden=32, layers=4, heads=4, intermediate=64,
            max_position=256, dtype=jnp.float32,
        )
        params = D.init_params(jax.random.PRNGKey(0), cfg)
        _DECODER_RES = (params, cfg, _CharTok())
    return _DECODER_RES


def _profile_rng(profile: WorkloadProfile, seed: int) -> np.random.Generator:
    return np.random.default_rng(
        (zlib.crc32(profile.name.encode()) << 8) ^ (int(seed) & 0xFFFFFFFF)
    )


def _prompts(profile: WorkloadProfile, nreq: int, rng) -> list[str]:
    if profile.head_len >= 40:
        head = "c" * (profile.head_len - 8) + "ontext: "
    else:
        head = "c" * profile.head_len
    out = []
    for k in range(nreq):
        tail = f"q{k:02d}" + "".join(
            chr(97 + int(c)) for c in rng.integers(0, 26, profile.tail_len)
        )
        out.append(head + tail[:profile.tail_len].ljust(profile.tail_len, "x"))
    return out


def _arrivals(profile: WorkloadProfile, nreq: int, rng) -> np.ndarray:
    gaps = rng.exponential(1.0 / profile.lam, nreq)
    if profile.burst > 0:
        # burst arrivals: every request inside a burst lands with its
        # burst head; the exponential gap survives only between bursts
        for k in range(nreq):
            if k % profile.burst:
                gaps[k] = 0.0
    return np.cumsum(gaps)


def _percentile_ms(samples_s: list[float], q: float) -> float:
    if not samples_s:
        return 0.0
    return round(float(np.percentile(np.asarray(samples_s) * 1e3, q)), 2)


def _serving_trial(
    profile: WorkloadProfile, nreq: int, resources, seed: int,
    deadline_s: float | None,
) -> dict:
    from pathway_tpu.engine import probes
    from pathway_tpu.xpacks.llm.llms import TPUDecoderChat

    params, cfg, tok = resources
    rng = _profile_rng(profile, seed)
    prompts = _prompts(profile, nreq, rng)
    arrivals = _arrivals(profile, nreq, rng)
    t_start = time.perf_counter()
    chat = TPUDecoderChat(
        params=params, cfg=cfg, tokenizer=tok,
        max_new_tokens=profile.max_new, temperature=0.0,
        max_prompt_tokens=profile.prompt_cap, continuous=True,
        n_slots=profile.n_slots, chunk_steps=profile.chunk_steps,
    )
    aborted = False
    latched = False
    try:
        # warm the executables outside the timed window
        for r in chat.submit_batch([prompts[0]]):
            r.done.wait(timeout=_REQ_TIMEOUT_S)
        probes.reset_prefix_stats()
        probes.reset_latency_metrics()
        t0 = time.perf_counter()
        reqs = []
        for k in range(nreq):
            if deadline_s is not None and (
                time.perf_counter() - t_start
            ) > deadline_s:
                aborted = True  # obviously-bad trial: stop feeding it
                break
            now = time.perf_counter() - t0
            if arrivals[k] > now:
                time.sleep(arrivals[k] - now)
            kw = {}
            if profile.tenants:
                kw["tenant"] = profile.tenants[k % len(profile.tenants)]
            try:
                reqs.append(chat.submit_batch([prompts[k]], **kw)[0])
            except RuntimeError:
                # serving loop latched dead (e.g. chaos drill exhausted
                # the restart budget): a losing config, not a crash
                latched = True
                break
        ttft, e2e, tokens, failures, terminal_ok = [], [], 0, 0, not latched
        for k, r in enumerate(reqs):
            if not r.done.wait(timeout=_REQ_TIMEOUT_S):
                terminal_ok = False
                continue
            if r.text is None:
                failures += 1
                continue
            tokens += len(r.tokens)
            if r.first_token_at is not None:
                ttft.append(r.first_token_at - t0 - arrivals[k])
            e2e.append(time.perf_counter() - t0 - arrivals[k])
        wall = max(time.perf_counter() - t0, 1e-9)
        st = dict(chat._server.stats)
        lat = probes.latency_summary(phase="decode")
        ps = probes.prefix_stats()
        slot_steps = int(st.get("slot_steps_total", 0))
        steps = int(st.get("steps", 0))
        return {
            "profile": profile.name,
            "requests": len(reqs),
            "tok_s": round(tokens / wall, 2),
            "ttft_p95_ms": _percentile_ms(ttft, 95),
            "ttft_p50_ms": _percentile_ms(ttft, 50),
            "e2e_p95_ms": _percentile_ms(e2e, 95),
            "e2e_p50_ms": (
                (lat.get("e2e_seconds") or {}).get("p50_ms")
                or _percentile_ms(e2e, 50)
            ),
            "occupancy": round(
                slot_steps / max(steps * profile.n_slots, 1), 4
            ),
            "prefix_hit_rate": ps.get("hit_rate", 0.0),
            "shed": int(st.get("shed", 0)),
            "restarts": int(st.get("restarts", 0)),
            "failures": failures,
            "terminal_ok": terminal_ok,
            "aborted": aborted,
            "wall_s": round(wall, 3),
        }
    finally:
        chat.close()


def _ingest_trial(
    profile: WorkloadProfile, rows: int, seed: int,
    deadline_s: float | None,
) -> dict:
    import dataclasses

    from pathway_tpu.models import MINILM_L6, SentenceEmbedderModel

    rng = _profile_rng(profile, seed)
    uniq = max(1, int(rows * (1.0 - profile.dup_rate)))
    texts = [
        "doc %03d " % k + "".join(
            chr(97 + int(c)) for c in rng.integers(0, 26, 24)
        )
        for k in range(uniq)
    ]
    # retraction-heavy stream: re-ingested duplicates interleave with
    # fresh rows, exactly the upsert/remove churn shape
    stream = [texts[int(rng.integers(0, uniq))] for _ in range(rows)]
    cfg = dataclasses.replace(
        MINILM_L6, layers=2, hidden=32, heads=4, intermediate=64,
        vocab_size=500, max_position=32,
    )
    model = SentenceEmbedderModel(cfg=cfg, max_length=16)
    aborted = False
    t_start = time.perf_counter()
    try:
        # warm (compile) outside the timed window
        model.embed_batch(stream[:4])
        t0 = time.perf_counter()
        handles, done = [], 0
        batch = 8
        for i in range(0, len(stream), batch):
            if deadline_s is not None and (
                time.perf_counter() - t_start
            ) > deadline_s:
                aborted = True
                break
            handles.append(model.embed_submit(stream[i:i + batch]))
            done += len(stream[i:i + batch])
        outs = model.embed_resolve(handles)
        wall = max(time.perf_counter() - t0, 1e-9)
        n_rows = int(sum(o.shape[0] for o in outs))
        return {
            "profile": profile.name,
            "requests": done,
            "rows_per_s": round(n_rows / wall, 2),
            "tok_s": 0.0,
            "shed": 0,
            "restarts": 0,
            "failures": 0,
            "terminal_ok": n_rows == done,
            "aborted": aborted,
            "wall_s": round(wall, 3),
        }
    finally:
        model.close()


def run_trial(
    profile,
    flags: dict,
    *,
    scale: float = 1.0,
    seed: int = 0,
    deadline_s: float | None = None,
    resources=None,
    arm_slo: bool = False,
) -> dict:
    """Play one profile trace under ``flags`` and return its metrics.

    ``flags`` (env name → raw value) apply via ``flag_overrides``
    on top of the profile's ``base_flags``, with ``construction=True``
    — the server/model/watchdog are all built inside the scope, so
    construction-read knobs really take effect and ``os.environ`` is
    never touched. ``scale`` multiplies the request count (successive
    halving re-runs survivors at larger scales); ``deadline_s`` is the
    early-abort budget — a trial past it stops submitting and comes
    back with ``aborted=True`` (the search scores it -inf).

    ``arm_slo=True`` additionally resets + constructs the PR-9 watchdog
    inside the scope (the profile's ``slo`` objectives must be part of
    ``flags``), force-ticks it after the trace, and reports
    ``slo_alerting`` / ``slo_breaches`` — the validation leg."""
    profile = get_profile(profile)
    merged = dict(profile.base_flags)
    merged.update(flags)
    with flag_overrides(merged, construction=True):
        if arm_slo:
            from pathway_tpu.engine import slo as slo_mod

            slo_mod.reset_watchdog()
        try:
            if profile.kind == "ingest":
                rows = max(16, int(round(profile.rows * scale)))
                metrics = _ingest_trial(profile, rows, seed, deadline_s)
            else:
                nreq = max(4, int(round(profile.nreq * scale)))
                metrics = _serving_trial(
                    profile, nreq,
                    resources or decoder_resources(), seed, deadline_s,
                )
            if arm_slo:
                wd = slo_mod.get_watchdog()
                wd.tick()
                state = wd.state()
                metrics["slo_alerting"] = list(state["alerting"])
                metrics["slo_breaches"] = int(state["breaches"])
            return metrics
        finally:
            if arm_slo:
                slo_mod.reset_watchdog()
