"""Fused KNN top-k Pallas kernel.

The XLA path (`ops.knn`) materializes the full (Q, N) score matrix in HBM
before `lax.top_k` — at corpus scale that matrix IS the HBM-bandwidth
bottleneck (N=1M, Q=256 → 1 GB per search). This kernel tiles the corpus
through VMEM and keeps a running (Q, K) top-k accumulator in VMEM scratch,
so HBM traffic is one read of the corpus and one (Q, K) write: the
streaming-RAG search shape (reference brute-force index:
``src/external_integration/brute_force_knn_integration.rs:53-140``,
re-designed TPU-first).

Selection inside the kernel is K rounds of masked max over the concatenated
(accumulator ‖ tile-scores) candidates — pure VPU ops (max / compare /
select / iota), no sort or gather, so it lowers cleanly on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _make_kernel(k: int, metric: str, tile: int, q_rows: int):
    def kernel(q_ref, c_ref, v_ref, out_vals_ref, out_idx_ref,
               acc_vals_ref, acc_idx_ref):
        step = pl.program_id(0)
        nsteps = pl.num_programs(0)

        @pl.when(step == 0)
        def _init():
            acc_vals_ref[:] = jnp.full((q_rows, k), _NEG_INF, jnp.float32)
            acc_idx_ref[:] = jnp.zeros((q_rows, k), jnp.int32)

        q = q_ref[:]                      # (Q, d) f32
        c = c_ref[:]                      # (tile, d) bf16
        valid = v_ref[:]                  # (tile, 1) bool/int32
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16), c,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                 # (Q, tile)
        if metric == "l2":
            qn = jnp.sum(q * q, axis=1, keepdims=True)              # (Q, 1)
            cf = c.astype(jnp.float32)
            cn = jnp.sum(cf * cf, axis=1, keepdims=True)            # (tile,1)
            scores = -(qn + cn.T - 2.0 * dots)
        else:
            scores = dots
        vmask = (valid[:, 0] != 0)[None, :]                         # (1,tile)
        scores = jnp.where(vmask, scores, _NEG_INF)

        base = step * tile
        tile_idx = base + jax.lax.broadcasted_iota(jnp.int32, (q_rows, tile), 1)

        cand_vals = jnp.concatenate([acc_vals_ref[:], scores], axis=1)
        cand_idx = jnp.concatenate([acc_idx_ref[:], tile_idx], axis=1)
        width = k + tile
        col = jax.lax.broadcasted_iota(jnp.int32, (q_rows, width), 1)

        new_vals = []
        new_idx = []
        for _ in range(k):
            m = jnp.max(cand_vals, axis=1, keepdims=True)           # (Q,1)
            is_max = cand_vals == m
            pos = jnp.min(jnp.where(is_max, col, width), axis=1, keepdims=True)
            sel = col == pos
            new_vals.append(m[:, 0])
            new_idx.append(jnp.sum(jnp.where(sel, cand_idx, 0), axis=1))
            cand_vals = jnp.where(sel, _NEG_INF, cand_vals)
        acc_vals_ref[:] = jnp.stack(new_vals, axis=1)
        acc_idx_ref[:] = jnp.stack(new_idx, axis=1).astype(jnp.int32)

        @pl.when(step == nsteps - 1)
        def _emit():
            out_vals_ref[:] = acc_vals_ref[:]
            out_idx_ref[:] = acc_idx_ref[:]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "tile", "interpret")
)
def fused_topk(corpus, valid, queries, k: int, metric: str = "cos",
               tile: int = 2048, interpret: bool = False):
    """corpus (N, d) bf16, valid (N,) bool, queries (Q, d) f32 →
    (scores (Q, k) f32, indices (Q, k) i32). N must be a multiple of
    ``tile`` (the index pads its capacity to pow2, so it is)."""
    n, d = corpus.shape
    q_rows = queries.shape[0]
    tile = min(tile, n)
    assert n % tile == 0, (n, tile)
    grid = (n // tile,)
    kernel = _make_kernel(k, metric, tile, q_rows)
    out_vals, out_idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((q_rows, d), lambda i: (0, 0)),
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((q_rows, k), lambda i: (0, 0)),
            pl.BlockSpec((q_rows, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q_rows, k), jnp.float32),
            jax.ShapeDtypeStruct((q_rows, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_rows, k), jnp.float32),
            pltpu.VMEM((q_rows, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries.astype(jnp.float32), corpus,
      valid.astype(jnp.int32).reshape(-1, 1))
    return out_vals, out_idx
