"""Fused KNN top-k Pallas kernel.

The XLA path (`ops.knn`) materializes the full (Q, N) score matrix in HBM
before `lax.top_k` — at corpus scale that matrix IS the HBM-bandwidth
bottleneck (N=1M, Q=256 → 1 GB per search). This kernel tiles the corpus
through VMEM and keeps a running (Q, K) top-k accumulator in VMEM scratch,
so HBM traffic is one read of the corpus and one (Q, K) write: the
streaming-RAG search shape (reference brute-force index:
``src/external_integration/brute_force_knn_integration.rs:53-140``,
re-designed TPU-first).

Selection inside the kernel is K rounds of masked max over the concatenated
(accumulator ‖ tile-scores) candidates — pure VPU ops (max / compare /
select / iota), no sort or gather, so it lowers cleanly on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _make_kernel(k: int, metric: str, tile: int, q_rows: int):
    def kernel(q_ref, c_ref, v_ref, out_vals_ref, out_idx_ref,
               acc_vals_ref, acc_idx_ref):
        step = pl.program_id(1)
        nsteps = pl.num_programs(1)

        @pl.when(step == 0)
        def _init():
            acc_vals_ref[:] = jnp.full((q_rows, k), _NEG_INF, jnp.float32)
            acc_idx_ref[:] = jnp.zeros((q_rows, k), jnp.int32)

        q = q_ref[:]                      # (Q, d) f32
        c = c_ref[:]                      # (tile, d) bf16
        valid = v_ref[:]                  # (tile, 1) bool/int32
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16), c,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                 # (Q, tile)
        if metric == "l2":
            qn = jnp.sum(q * q, axis=1, keepdims=True)              # (Q, 1)
            cf = c.astype(jnp.float32)
            cn = jnp.sum(cf * cf, axis=1, keepdims=True)            # (tile,1)
            scores = -(qn + cn.T - 2.0 * dots)
        else:
            scores = dots
        vmask = (valid[:, 0] != 0)[None, :]                         # (1,tile)
        scores = jnp.where(vmask, scores, _NEG_INF)

        base = step * tile
        tile_idx = base + jax.lax.broadcasted_iota(jnp.int32, (q_rows, tile), 1)

        cand_vals = jnp.concatenate([acc_vals_ref[:], scores], axis=1)
        cand_idx = jnp.concatenate([acc_idx_ref[:], tile_idx], axis=1)
        width = k + tile
        col = jax.lax.broadcasted_iota(jnp.int32, (q_rows, width), 1)
        col_k = jax.lax.broadcasted_iota(jnp.int32, (q_rows, k), 1)

        # k rounds of masked max, as a fori_loop so the (Q, k+tile) candidate
        # buffer is carried (reused) rather than unrolled k times — the
        # unrolled form blows the 16M scoped-VMEM stack at tile=2048, k=10.
        def round_body(j, carry):
            cand, out_v, out_i = carry
            m = jnp.max(cand, axis=1, keepdims=True)                # (Q,1)
            is_max = cand == m
            pos = jnp.min(jnp.where(is_max, col, width), axis=1, keepdims=True)
            sel = col == pos
            midx = jnp.sum(jnp.where(sel, cand_idx, 0), axis=1, keepdims=True)
            slot = col_k == j                                       # (Q,k)
            out_v = jnp.where(slot, m, out_v)
            out_i = jnp.where(slot, midx, out_i)
            cand = jnp.where(sel, _NEG_INF, cand)
            return cand, out_v, out_i

        _, new_vals, new_idx = jax.lax.fori_loop(
            0, k, round_body,
            (cand_vals,
             jnp.full((q_rows, k), _NEG_INF, jnp.float32),
             jnp.zeros((q_rows, k), jnp.int32)),
        )
        acc_vals_ref[:] = new_vals
        acc_idx_ref[:] = new_idx

        @pl.when(step == nsteps - 1)
        def _emit():
            out_vals_ref[:] = acc_vals_ref[:]
            out_idx_ref[:] = acc_idx_ref[:]

    return kernel


# Max query rows resident in one kernel instance. The selection loop's wide
# (q_tile, k+tile) temporaries consume vector registers proportional to
# q_tile x tile; q_tile=128 at tile=2048 spills ~129MB of scoped VMEM and
# fails to compile on v5e, while 16/32/64 all compile and run within 1% of
# each other (measured N=262144, Q=256).
_Q_TILE = 64


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "tile", "interpret")
)
def fused_topk(corpus, valid, queries, k: int, metric: str = "cos",
               tile: int = 2048, interpret: bool = False):
    """corpus (N, d) bf16, valid (N,) bool, queries (Q, d) f32 →
    (scores (Q, k) f32, indices (Q, k) i32). N must be a multiple of
    ``tile`` (the index pads its capacity to pow2, so it is). The query
    axis is tiled over the grid in blocks of ``_Q_TILE``."""
    n, d = corpus.shape
    q_rows = queries.shape[0]
    tile = min(tile, n)
    assert n % tile == 0, (n, tile)
    q_tile = min(_Q_TILE, q_rows)
    pad = (-q_rows) % q_tile
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, d), queries.dtype)]
        )
    q_padded = q_rows + pad
    grid = (q_padded // q_tile, n // tile)
    kernel = _make_kernel(k, metric, tile, q_tile)
    out_vals, out_idx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((q_tile, d), lambda qi, i: (qi, 0)),
            pl.BlockSpec((tile, d), lambda qi, i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda qi, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((q_tile, k), lambda qi, i: (qi, 0)),
            pl.BlockSpec((q_tile, k), lambda qi, i: (qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((q_padded, k), jnp.float32),
            jax.ShapeDtypeStruct((q_padded, k), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_tile, k), jnp.float32),
            pltpu.VMEM((q_tile, k), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(queries.astype(jnp.float32), corpus,
      valid.astype(jnp.int32).reshape(-1, 1))
    return out_vals[:q_rows], out_idx[:q_rows]
