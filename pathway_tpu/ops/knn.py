"""Brute-force KNN on TPU: HBM-resident corpus, jitted gemm + top-k.

The reference's brute-force index is a growable host ``Array2<f64>`` with
gemm-based distances (``src/external_integration/brute_force_knn_integration.rs``).
TPU-first redesign:

* the corpus lives **in HBM** as a capacity-doubling padded matrix — append is
  an on-device dynamic_update_slice, no host round-trip;
* distances are one MXU matmul: queries (padded to a bucket size) x corpus^T
  in bfloat16 with float32 accumulation, fused by XLA with the mask and the
  ``lax.top_k`` that follows — exactly the "keep the FLOPs on the MXU, fuse
  the elementwise" recipe;
* deletes are O(1) swaps with the last row (index is unordered);
* static shapes: (capacity, query-bucket, k) are compile-time constants, so
  streams of ragged batches reuse cached executables.
"""

from __future__ import annotations

import functools
import math

from pathway_tpu.ops import next_pow2
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp


_NEG_INF = -1e30


def knn_scores(corpus, valid_mask, queries, metric: str):
    """Masked similarity scores, higher is better; one MXU gemm.
    corpus (N,d) bf16, queries (Q,d) f32 -> (Q,N) f32. Shared by the
    single-chip kernel below and parallel/sharded_knn's per-shard kernel."""
    q = queries.astype(jnp.bfloat16)
    c = corpus
    dots = jax.lax.dot_general(
        q,
        c,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Q, N)
    if metric == "l2":
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        cn = jnp.sum(c.astype(jnp.float32) ** 2, axis=1)[None, :]
        scores = -(qn + cn - 2.0 * dots)  # negative squared L2
    else:  # cosine / dot on normalized vectors
        scores = dots
    return jnp.where(valid_mask[None, :], scores, _NEG_INF)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _search_kernel(corpus, valid_mask, queries, k: int, metric: str):
    return jax.lax.top_k(knn_scores(corpus, valid_mask, queries, metric), k)


def _use_pallas(capacity: int) -> bool:
    """The fused Pallas kernel pays off once the (Q, N) score matrix would be
    HBM-traffic-bound; below that XLA's fused gemm+top_k is fine. TPU only."""
    import os

    if os.environ.get("PATHWAY_DISABLE_PALLAS"):
        return False
    if capacity < 8192:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False




class BruteForceKnnIndex:
    """Single-device TPU KNN index (one instance per worker, like the
    reference's ``ExternalIndexFactory::make_instance``)."""

    def __init__(
        self,
        dimensions: int,
        reserved_space: int = 1024,
        metric: str = "cos",
        auxiliary_space: int = 0,
        dtype=jnp.bfloat16,
    ):
        self.dim = dimensions
        self.metric = "l2" if str(metric).lower().startswith("l2") else "cos"
        self.capacity = next_pow2(reserved_space, 16)
        self.dtype = dtype
        self._corpus = jnp.zeros((self.capacity, self.dim), dtype=dtype)
        self._valid = jnp.zeros((self.capacity,), dtype=bool)
        self.n = 0
        self._keys: list[Any] = []
        self._slot_of: dict[Any, int] = {}

    # ------------------------------------------------------------------ sizing
    def _grow(self, needed: int) -> None:
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        if new_cap == self.capacity:
            return
        corpus = jnp.zeros((new_cap, self.dim), dtype=self.dtype)
        corpus = jax.lax.dynamic_update_slice(corpus, self._corpus, (0, 0))
        valid = jnp.zeros((new_cap,), dtype=bool)
        valid = jax.lax.dynamic_update_slice(valid, self._valid, (0,))
        self._corpus, self._valid = corpus, valid
        self.capacity = new_cap

    # ------------------------------------------------------------------ update
    def _prep(self, vectors: np.ndarray) -> np.ndarray:
        v = np.asarray(vectors, dtype=np.float32)
        if v.ndim == 1:
            v = v[None, :]
        if self.metric == "cos":
            norms = np.linalg.norm(v, axis=1, keepdims=True)
            norms[norms == 0] = 1.0
            v = v / norms
        return v

    def _append(self, keys: list, v) -> None:
        """Shared append: v is an already-normalised (m, d) device array."""
        m = len(keys)
        self._grow(self.n + m)
        start = self.n
        self._corpus = jax.lax.dynamic_update_slice(
            self._corpus, v.astype(self.dtype), (start, 0)
        )
        self._valid = jax.lax.dynamic_update_slice(
            self._valid, jnp.ones((m,), dtype=bool), (start,)
        )
        for i, key in enumerate(keys):
            self._slot_of[key] = start + i
            self._keys.append(key)
        self.n += m

    def add(self, keys: list, vectors: np.ndarray) -> None:
        if not keys:
            return
        self._append(keys, jnp.asarray(self._prep(vectors)))

    def add_device(self, keys: list, vectors) -> None:
        """Fast path: vectors already on device (e.g. straight out of the
        embedder) — normalise and append without a host round-trip."""
        if not keys:
            return
        v = jnp.asarray(vectors, dtype=jnp.float32)
        if v.ndim == 1:
            v = v[None, :]
        if self.metric == "cos":
            v = v / jnp.clip(jnp.linalg.norm(v, axis=1, keepdims=True), 1e-9, None)
        self._append(keys, v)

    def remove(self, keys: list) -> None:
        for key in keys:
            slot = self._slot_of.pop(key, None)
            if slot is None:
                continue
            last = self.n - 1
            if slot != last:
                last_key = self._keys[last]
                row = jax.lax.dynamic_slice(self._corpus, (last, 0), (1, self.dim))
                self._corpus = jax.lax.dynamic_update_slice(self._corpus, row, (slot, 0))
                self._keys[slot] = last_key
                self._slot_of[last_key] = slot
            self._valid = self._valid.at[last].set(False)
            self._keys.pop()
            self.n -= 1

    # ------------------------------------------------------------------ search
    def search(self, queries: np.ndarray, k: int) -> list[list[tuple[Any, float]]]:
        """Return per-query [(key, score)] sorted by decreasing score."""
        if self.n == 0:
            q = np.asarray(queries)
            nq = 1 if q.ndim == 1 else len(q)
            return [[] for _ in range(nq)]
        q = self._prep(queries)
        nq = len(q)
        bucket = next_pow2(nq, 16)
        if bucket > nq:
            q = np.concatenate([q, np.zeros((bucket - nq, self.dim), np.float32)])
        k_eff = min(k, self.capacity)
        if _use_pallas(self.capacity):
            from pathway_tpu.ops.pallas_knn import fused_topk

            scores, idx = fused_topk(
                self._corpus, self._valid, jnp.asarray(q), k_eff, self.metric
            )
        else:
            scores, idx = _search_kernel(
                self._corpus, self._valid, jnp.asarray(q), k_eff, self.metric
            )
        scores = np.asarray(scores)[:nq]
        idx = np.asarray(idx)[:nq]
        out = []
        for qi in range(nq):
            row = []
            for j in range(k_eff):
                s = float(scores[qi, j])
                if s <= _NEG_INF / 2:
                    break
                slot = int(idx[qi, j])
                if slot < len(self._keys):
                    row.append((self._keys[slot], s))
            out.append(row)
        return out

    def __len__(self) -> int:
        return self.n
