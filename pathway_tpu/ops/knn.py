"""Brute-force KNN on TPU: HBM-resident corpus, jitted gemm + top-k.

The reference's brute-force index is a growable host ``Array2<f64>`` with
gemm-based distances (``src/external_integration/brute_force_knn_integration.rs``).
TPU-first redesign:

* the corpus lives **in HBM** as a capacity-doubling padded matrix — append is
  an on-device dynamic_update_slice, no host round-trip;
* distances are one MXU matmul: queries (padded to a bucket size) x corpus^T
  in bfloat16 with float32 accumulation, fused by XLA with the mask and the
  ``lax.top_k`` that follows — exactly the "keep the FLOPs on the MXU, fuse
  the elementwise" recipe;
* deletes are O(1) swaps with the last row (index is unordered);
* static shapes: (capacity, query-bucket, k) are compile-time constants, so
  streams of ragged batches reuse cached executables.
"""

from __future__ import annotations

import functools
import math

from pathway_tpu.engine.probes import record_device_dispatch, record_stage
from pathway_tpu.ops import canonical_metric, next_pow2, prep_host_vectors
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp


_NEG_INF = -1e30


def knn_scores(corpus, valid_mask, queries, metric: str,
               f32_scores: bool = False):
    """Masked similarity scores, higher is better; one MXU gemm.
    corpus (N,d) bf16, queries (Q,d) f32 -> (Q,N) f32. Shared by the
    single-chip kernel below and parallel/sharded_knn's per-shard kernel.

    Accumulation is f32 either way (``preferred_element_type``); the
    default casts OPERANDS to bf16 for the MXU fast path, which is where
    the ~4% recall@10 vs f32 host truth actually goes. ``f32_scores=True``
    (PATHWAY_TPU_KNN_F32_SCORES, or ``BruteForceKnnIndex(f32_scores=...)``)
    keeps queries f32 and upcasts the corpus for the dot — recall-first at
    roughly half the gemm throughput."""
    if f32_scores:
        q = queries.astype(jnp.float32)
        c = corpus.astype(jnp.float32)
    else:
        q = queries.astype(jnp.bfloat16)
        c = corpus
    dots = jax.lax.dot_general(
        q,
        c,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (Q, N)
    if metric == "l2":
        qn = jnp.sum(queries.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        cn = jnp.sum(c.astype(jnp.float32) ** 2, axis=1)[None, :]
        scores = -(qn + cn - 2.0 * dots)  # negative squared L2
    else:  # cosine / dot on normalized vectors
        scores = dots
    return jnp.where(valid_mask[None, :], scores, _NEG_INF)


def _normalize(v):
    """Device-side unit-normalise (zero vectors map to ~0, not NaN)."""
    return v / jnp.clip(jnp.linalg.norm(v, axis=1, keepdims=True), 1e-9, None)


_TOPK_BLOCK = 8192


def topk_scores(scores, k: int):
    """top-k over (Q, N) scores; for large N a two-stage blocked reduction
    — ``lax.top_k`` cost grows superlinearly in row length (sorting
    networks), so per-block top-k followed by top-k over the block winners
    is MUCH faster at 10^6-row corpora (measured seconds -> milliseconds).

    A ragged tail (``N % _TOPK_BLOCK != 0``) pads the last block with
    ``_NEG_INF`` instead of falling back to the superlinear full-row
    ``lax.top_k``: shapes here are trace-time constants, so the pad is a
    static concat compiled into the executable. Pad slots can never win a
    top-k spot against any real score, and downstream resolvers already
    treat ``score <= _NEG_INF / 2`` as an empty slot."""
    Q, N = scores.shape
    if N <= 2 * _TOPK_BLOCK:
        return jax.lax.top_k(scores, k)
    pad = (-N) % _TOPK_BLOCK
    if pad:
        scores = jnp.concatenate(
            [scores, jnp.full((Q, pad), _NEG_INF, dtype=scores.dtype)],
            axis=1,
        )
    nb = (N + pad) // _TOPK_BLOCK
    kb = min(k, _TOPK_BLOCK)
    bs, bi = jax.lax.top_k(scores.reshape(Q, nb, _TOPK_BLOCK), kb)
    flat_s = bs.reshape(Q, nb * kb)
    fs, fi = jax.lax.top_k(flat_s, k)
    within = jnp.take_along_axis(bi.reshape(Q, nb * kb), fi, axis=1)
    idx = (fi // kb) * _TOPK_BLOCK + within
    return fs, idx


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "normalize", "f32_scores")
)
def _search_kernel(corpus, valid_mask, queries, k: int, metric: str,
                   normalize: bool = False, f32_scores: bool = False):
    """One fused dispatch for the whole search: cast, normalise (optional),
    gemm + top_k. Queries arrive ALREADY padded to their pow2 bucket —
    padding outside the jit makes the executable cache key on the BUCKET,
    not the raw query count (nq=3 and nq=5 share the bucket-16 binary)."""
    q = queries.astype(jnp.float32)
    if normalize:
        q = _normalize(q)
    return topk_scores(
        knn_scores(corpus, valid_mask, q, metric, f32_scores=f32_scores), k
    )


def _write_rows(corpus, valid, n_dev, v, m):
    """Shared in-kernel append body: write ``v`` (f32, already normalized
    as required) at the device cursor, mark the first ``m`` rows valid,
    advance the cursor by ``m``. Both append kernels trace through this so
    the write/cursor invariant has exactly one home."""
    vmask = jnp.arange(v.shape[0]) < m
    corpus = jax.lax.dynamic_update_slice(
        corpus, v.astype(corpus.dtype), (n_dev, 0)
    )
    valid = jax.lax.dynamic_update_slice(valid, vmask, (n_dev,))
    return corpus, valid, n_dev + m


@functools.partial(
    jax.jit, donate_argnums=(0, 1, 2), static_argnames=("normalize",)
)
def _append_kernel(corpus, valid, n_dev, v, m, normalize: bool):
    """One fused dispatch for the whole append: normalise (optional), cast,
    write the corpus rows + valid flags, and advance the device-resident
    write cursor. Donating corpus/valid makes the update in-place in HBM.
    The cursor lives ON DEVICE (``n_dev``): shipping a fresh start offset
    from the host each call would cost one h2d transfer per append — ~12ms
    on a tunneled dev host, dwarfing the update itself.

    ``v`` is padded to a pow2 row bucket with ``m`` the real count:
    streaming commits have ragged sizes, and one executable per BUCKET (not
    per size) keeps XLA from recompiling mid-stream. Pad rows land beyond
    the cursor with valid=False and are overwritten by the next append."""
    v = v.astype(jnp.float32)
    if normalize:
        v = _normalize(v)
    return _write_rows(corpus, valid, n_dev, v, m)


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=("embed", "cfg", "pad_id"),
)
def _embed_append_kernel(corpus, valid, n_dev, params, ids, mask, m, *,
                         embed, cfg, pad_id=0):
    """Embed + append in ONE dispatch: token ids go in, corpus rows come
    out, and the (normalized) embeddings are returned for queries riding
    the stream. On a relayed chip every dispatch enqueue pays tunnel
    latency, so halving the per-batch dispatch count matters as much as
    the kernels themselves.

    ``ids`` may be any integer dtype (int16 halves the h2d transfer for
    vocabularies under 32k — every BERT-family vocab); ``mask=None``
    derives the attention mask on device as ``ids != pad_id``, removing
    the mask transfer entirely. On a bandwidth-constrained link the
    ids-only int16 form cuts per-batch host bytes 4x."""
    ids = ids.astype(jnp.int32)
    if mask is None:
        mask = (ids != pad_id).astype(jnp.int32)
    emb = embed(params, ids, mask, cfg)  # (B, d) f32, unit-normalized
    corpus, valid, n_dev = _write_rows(corpus, valid, n_dev, emb, m)
    return corpus, valid, n_dev, emb


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2),
    static_argnames=(
        "embed", "cfg", "pad_id", "query_rows", "k", "metric", "f32_scores"
    ),
)
def _embed_append_query_kernel(corpus, valid, n_dev, params, ids, mask, m, *,
                               embed, cfg, pad_id, query_rows, k, metric,
                               f32_scores=False):
    """Ingest AND ride-along query in one dispatch: embed the batch, append
    it, then search the first ``query_rows`` fresh embeddings against the
    corpus *as updated by this very append* (self-inclusive as-of-now
    semantics — identical to dispatching a search right after the append).
    On a relayed chip each extra dispatch costs ~ms-level fixed overhead,
    more than the whole corpus scan itself, so a streaming pipeline with
    queries riding the ingest stream should prefer this over
    ``search_device`` after ``add_embed``."""
    ids = ids.astype(jnp.int32)
    if mask is None:
        mask = (ids != pad_id).astype(jnp.int32)
    emb = embed(params, ids, mask, cfg)
    corpus, valid, n_dev = _write_rows(corpus, valid, n_dev, emb, m)
    # emb is already unit-normalized (embed contract), so cos needs no
    # renormalise here
    scores, idx = topk_scores(
        knn_scores(
            corpus, valid, emb[:query_rows], metric, f32_scores=f32_scores
        ),
        k,
    )
    return corpus, valid, n_dev, emb, scores, idx


_M_SCALARS: dict[int, Any] = {}


def _m_scalar(m: int):
    """Cached device scalar for the append row count — a fresh h2d transfer
    per append would cost a full round trip on a tunneled host. Bounded: a
    bulk loader with wildly varied commit sizes must not pin device buffers
    for the process lifetime."""
    s = _M_SCALARS.get(m)
    if s is None:
        if len(_M_SCALARS) >= 256:
            _M_SCALARS.clear()
        s = jnp.asarray(m, jnp.int32)
        _M_SCALARS[m] = s
    return s


class BruteForceKnnIndex:
    """Single-device TPU KNN index (one instance per worker, like the
    reference's ``ExternalIndexFactory::make_instance``)."""

    def __init__(
        self,
        dimensions: int,
        reserved_space: int = 1024,
        metric: str = "cos",
        auxiliary_space: int = 0,
        dtype=jnp.bfloat16,
        f32_scores: bool | None = None,
    ):
        from pathway_tpu.internals.config import pathway_config

        self.dim = dimensions
        self.metric = canonical_metric(metric)
        # None defers to PATHWAY_TPU_KNN_F32_SCORES (recall-first scoring
        # with f32 operands vs the default bf16 MXU fast path)
        self.f32_scores = (
            pathway_config.knn_f32_scores
            if f32_scores is None else bool(f32_scores)
        )
        self.capacity = next_pow2(reserved_space, 16)
        self.dtype = dtype
        self._corpus = jnp.zeros((self.capacity, self.dim), dtype=dtype)
        self._valid = jnp.zeros((self.capacity,), dtype=bool)
        self._n_dev = jnp.zeros((), dtype=jnp.int32)  # device write cursor
        self.n = 0
        self._keys: list[Any] = []
        self._slot_of: dict[Any, int] = {}

    # ------------------------------------------------------------------ sizing
    def _grow(self, needed: int) -> None:
        new_cap = self.capacity
        while new_cap < needed:
            new_cap *= 2
        if new_cap == self.capacity:
            return
        corpus = jnp.zeros((new_cap, self.dim), dtype=self.dtype)
        corpus = jax.lax.dynamic_update_slice(corpus, self._corpus, (0, 0))
        valid = jnp.zeros((new_cap,), dtype=bool)
        valid = jax.lax.dynamic_update_slice(valid, self._valid, (0,))
        self._corpus, self._valid = corpus, valid
        self.capacity = new_cap

    # ------------------------------------------------------------------ update
    def _prep(self, vectors: np.ndarray) -> np.ndarray:
        return prep_host_vectors(vectors, self.metric)

    def _append(self, keys: list, v, normalize: bool) -> None:
        """Shared append: v is a (m, d) array; normalised on device iff
        ``normalize`` (host callers pre-normalise in _prep). Rows pad to a
        pow2 bucket so ragged streaming commits reuse one executable per
        bucket size."""
        m = len(keys)
        # growth is driven by REAL rows only — growing for transient pad
        # rows would double capacity (and recompile every kernel) exactly
        # when reserved_space was sized to the corpus. If the pad bucket
        # would overflow remaining capacity, shrink it to fit (only happens
        # on the final boundary commit).
        self._grow(self.n + m)
        start = self.n
        bucket = min(next_pow2(m, 16), self.capacity - self.n)
        if not isinstance(v, jax.Array):
            v_host = np.asarray(v, dtype=np.float32)
            if bucket > m:
                v_host = np.pad(v_host, ((0, bucket - m), (0, 0)))
            v = jnp.asarray(v_host)
        elif bucket > m:
            v = jnp.pad(v, ((0, bucket - m), (0, 0)))
        self._corpus, self._valid, self._n_dev = _append_kernel(
            self._corpus, self._valid, self._n_dev, v,
            _m_scalar(m), normalize=normalize,
        )
        record_device_dispatch("knn_append")
        self._record_keys(keys, start)

    def add(self, keys: list, vectors: np.ndarray) -> None:
        if not keys:
            return
        self._append(keys, self._prep(vectors), normalize=False)

    def add_device(self, keys: list, vectors) -> None:
        """Fast path: vectors already on device (e.g. straight out of the
        embedder) — normalise and append without a host round-trip."""
        if not keys:
            return
        v = jnp.asarray(vectors)
        if v.ndim == 1:
            v = v[None, :]
        self._append(keys, v, normalize=self.metric == "cos")

    def _record_keys(self, keys: list, start: int) -> None:
        """Host-side half of an append: key -> slot bookkeeping (one home
        for both the plain and the fused ingest paths). zip/update/extend
        keep the whole batch in C — this sits on the per-batch ingest path."""
        import time

        t0 = time.perf_counter()
        self._slot_of.update(zip(keys, range(start, start + len(keys))))
        self._keys.extend(keys)
        self.n += len(keys)
        # "append" = the host-side index bookkeeping share of the ingest
        # wall; the vector write itself rides the fused device dispatch
        record_stage("append", time.perf_counter() - t0)

    def add_embed(self, keys: list, params, input_ids, attention_mask,
                  cfg, embed, pad_id: int = 0, query_rows: int = 0,
                  k: int = 0):
        """Fastest ingest path: embed the tokenized batch AND append the
        vectors in one fused dispatch (see ``_embed_append_kernel``).
        ``embed(params, ids, mask, cfg)`` must return unit-normalized
        (rows, d) float32 — e.g. ``models.embedder.embed_fn``. Returns the
        embeddings (device array) for downstream queries.

        ``attention_mask=None`` derives the mask on device from
        ``input_ids != pad_id`` — pass int16 ids and no mask to cut the
        per-batch host->device bytes 4x (the win on a remote/tunneled
        chip, where ingest is link-bound before it is compute-bound).

        ``query_rows=q, k=n`` additionally searches the first ``q`` fresh
        embeddings against the just-updated corpus INSIDE the same
        dispatch and returns ``(emb, scores, idx)`` instead of ``emb`` —
        the streaming ingest-with-live-queries shape with zero extra
        dispatches (a separate ``search_device`` costs 2 more).

        The write covers ALL ``input_ids.shape[0]`` token rows (pad rows
        land beyond the cursor, valid=False, and are overwritten by the
        next append), so capacity must fit ``n + rows``. Size
        ``reserved_space`` with one token-bucket of headroom: growing here
        for transient pad rows recompiles every capacity-shaped kernel
        mid-stream — hence the warning."""
        m = len(keys)
        if m == 0:
            # keep the arity of the documented return shape so callers can
            # unpack unconditionally
            return (None, None, None) if query_rows else None
        rows = input_ids.shape[0]
        if rows < m:
            raise ValueError(f"{m} keys but only {rows} token rows")
        if query_rows:
            # degenerate top-k (k=0) and out-of-range query slices would
            # silently produce empty/garbage results from the fused kernel
            if k < 1:
                raise ValueError(
                    f"query_rows={query_rows} requires k >= 1 (got {k})"
                )
            if not 0 <= query_rows <= rows:
                raise ValueError(
                    f"query_rows={query_rows} must be within the {rows} "
                    f"token rows"
                )
        if self.n + rows > self.capacity:
            import warnings

            warnings.warn(
                f"add_embed growing capacity ({self.capacity} -> fit "
                f"{self.n + rows}) for a padded batch; every "
                f"capacity-shaped kernel recompiles. Size reserved_space "
                f"with one token-bucket of headroom to avoid this.",
                stacklevel=2,
            )
            self._grow(self.n + rows)
        start = self.n
        if query_rows:
            (self._corpus, self._valid, self._n_dev, emb, scores,
             idx) = _embed_append_query_kernel(
                self._corpus, self._valid, self._n_dev,
                params, input_ids, attention_mask, _m_scalar(m),
                embed=embed, cfg=cfg, pad_id=pad_id,
                query_rows=query_rows, k=min(k, self.capacity),
                metric=self.metric, f32_scores=self.f32_scores,
            )
            record_device_dispatch("knn_embed_append_query")
            self._record_keys(keys, start)
            return emb, scores, idx
        self._corpus, self._valid, self._n_dev, emb = _embed_append_kernel(
            self._corpus, self._valid, self._n_dev,
            params, input_ids, attention_mask, _m_scalar(m),
            embed=embed, cfg=cfg, pad_id=pad_id,
        )
        record_device_dispatch("knn_embed_append")
        self._record_keys(keys, start)
        return emb

    def remove(self, keys: list) -> None:
        for key in keys:
            slot = self._slot_of.pop(key, None)
            if slot is None:
                continue
            last = self.n - 1
            if slot != last:
                last_key = self._keys[last]
                row = jax.lax.dynamic_slice(self._corpus, (last, 0), (1, self.dim))
                self._corpus = jax.lax.dynamic_update_slice(self._corpus, row, (slot, 0))
                self._keys[slot] = last_key
                self._slot_of[last_key] = slot
            self._valid = self._valid.at[last].set(False)
            self._keys.pop()
            self.n -= 1
            self._n_dev = self._n_dev - 1  # keep the device cursor in step

    # ------------------------------------------------------------------ search
    def search_device(self, queries, k: int):
        """Dispatch-only search: queries may live on device (straight out of
        the embedder); returns device ``(scores (Qb,k), idx (Qb,k))`` with the
        query axis padded to its pow2 bucket. No host synchronisation — a
        streaming pipeline can dispatch many searches and drain results with
        one ``jax.device_get`` (device→host fetches dominate end-to-end
        latency when the host is remote from the chip)."""
        # pad the query axis to its pow2 bucket BEFORE the jit boundary:
        # host arrays pad for free in numpy; device arrays pay one tiny
        # cached pad op — either way the big gemm+top_k executable is
        # shared per bucket instead of per raw query count
        is_device = isinstance(queries, jax.Array)
        q = queries if is_device else np.asarray(queries, dtype=np.float32)
        if q.ndim == 1:
            q = q[None, :]
        nq = q.shape[0]
        bucket = next_pow2(nq, 16)
        if bucket > nq:
            pad_spec = ((0, bucket - nq), (0, 0))
            q = jnp.pad(q, pad_spec) if is_device else np.pad(q, pad_spec)
        if not is_device:
            q = jnp.asarray(q)
        k_eff = min(k, self.capacity)
        normalize = self.metric == "cos"
        scores, idx = _search_kernel(self._corpus, self._valid, q, k_eff,
                                     self.metric, normalize=normalize,
                                     f32_scores=self.f32_scores)
        record_device_dispatch("knn_search")
        return scores, idx

    def resolve(self, scores, idx, nq: int, k: int) -> list[list[tuple[Any, float]]]:
        """Map fetched (host) score/index arrays back to [(key, score)] rows."""
        scores = np.asarray(scores)[:nq]
        idx = np.asarray(idx)[:nq]
        out = []
        for qi in range(nq):
            row = []
            for j in range(scores.shape[1]):
                s = float(scores[qi, j])
                if s <= _NEG_INF / 2:
                    break
                slot = int(idx[qi, j])
                if slot < len(self._keys):
                    row.append((self._keys[slot], s))
            out.append(row)
        return out

    def search(self, queries: np.ndarray, k: int) -> list[list[tuple[Any, float]]]:
        """Return per-query [(key, score)] sorted by decreasing score."""
        if not isinstance(queries, (np.ndarray, jax.Array)):
            queries = np.asarray(queries)
        nq = 1 if queries.ndim == 1 else queries.shape[0]
        if self.n == 0:
            return [[] for _ in range(nq)]
        # one round trip for both result arrays
        scores, idx = jax.device_get(self.search_device(queries, k))
        record_device_dispatch("knn_drain")
        return self.resolve(scores, idx, nq, k)

    def __len__(self) -> int:
        return self.n
