"""Ingest-time compressed late-interaction doc-token bank.

The rerank cascade's cheap stage (PR 3) re-encodes every (query, doc)
pair through the first N transformer layers at QUERY time — O(query +
doc) encoder FLOPs per candidate, paid again on every query. The
KaLM-Reranker observation: that cost belongs at INGEST. Each document is
encoded once through the full encoder when it enters the index; its
per-token states are projected to a small ``dc``-dim space
(``PATHWAY_TPU_LATE_DIM``), L2-normalized and stored int8-quantized
(per-token symmetric scales, the PR-6 KV-quant idiom) in a
device-resident bank alongside the IVF vectors. The query-time cheap
stage becomes late-interaction MaxSim over the gathered bank rows:

    maxsim(q, d) = sum_s  max_t  <q_s, d_t>          (unit vectors)

one (S, dc) x (dc, T) gemm per candidate — O(query tokens) per doc,
independent of encoder depth. At ``dc``=32 a bank token costs
``dc + 4`` bytes; the ``late_bank`` HBM component tracks the footprint.

This module holds the pure/jitted pieces — projection, quantized
token-state encoding, dequant + MaxSim — shared by the fused query
kernel (``ops/fused_query.py``), the embedder token-level submit path
(``models/embedder.py``) and the bench. Bank LIFECYCLE (append /
retraction / compaction mirroring the IVF row lifecycle) lives with the
row owners: :class:`~pathway_tpu.ops.fused_query.FusedRAGPipeline`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pathway_tpu.models.transformer import TransformerConfig, encode

# symmetric int8 quantization constants — same contract as the KV-quant
# path (models/decoder.py): |x| / scale <= 127 by construction, all-zero
# rows (padding) quantize to exact zeros via the scale floor
_LATE_QMAX = 127.0
_LATE_SCALE_FLOOR = 1e-8


def late_projection(hidden: int, dc: int, seed: int = 0) -> jax.Array:
    """Deterministic ``(hidden, dc)`` down-projection for token states.

    A fixed random projection (seeded, 1/sqrt(hidden) scale) — the same
    matrix at ingest and query time by construction, with no checkpoint
    to version. Random projections approximately preserve inner products
    (Johnson–Lindenstrauss), which is all MaxSim consumes."""
    w = jax.random.normal(jax.random.PRNGKey(seed), (hidden, dc), jnp.float32)
    return w / jnp.sqrt(jnp.float32(hidden))


def _project_tokens(hidden, mask, proj):
    """(B, S, H) token states -> (B, S, dc) unit vectors, padding zeroed."""
    t = hidden.astype(jnp.float32) @ proj.astype(jnp.float32)
    t = t / jnp.clip(jnp.linalg.norm(t, axis=-1, keepdims=True), 1e-9, None)
    return t * mask.astype(jnp.float32)[:, :, None]


def _quant_tokens(t):
    """Per-token symmetric int8 quant over the dc axis: ``(payload int8,
    scale f32 (..., 1))`` with ``t ~= payload * scale``."""
    amax = jnp.max(jnp.abs(t), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / _LATE_QMAX, _LATE_SCALE_FLOOR)
    return jnp.round(t / scale).astype(jnp.int8), scale


@functools.partial(jax.jit, static_argnames=("cfg", "flash"))
def doc_token_states(params, input_ids, attention_mask, proj,
                     cfg: TransformerConfig, flash: bool = False):
    """One fused executable: full-depth encode -> project -> normalize ->
    int8 quant. Returns ``(payload int8 (B, S, dc), scale f32 (B, S, 1))``
    — the bank rows for a batch of documents. Runs ONCE per document at
    ingest; queries only ever dequantize."""
    hidden = encode(params, input_ids, attention_mask, cfg, flash=flash)
    return _quant_tokens(_project_tokens(hidden, attention_mask, proj))


def query_token_states(hidden, q_mask, proj):
    """Query-side (B, S, dc) unit token states from ALREADY-computed
    encoder states — the fused kernel encodes the query once and feeds
    both the pooled retrieval embedding and this projection, so MaxSim
    adds zero encoder passes."""
    return _project_tokens(hidden, q_mask, proj)


def maxsim_scores(q_tok, q_mask, bank_q, bank_scale, d_lens):
    """Late-interaction MaxSim: ``sum_s max_t <q_s, d_t>``.

    q_tok (Qb, S, dc) unit query tokens (padding rows already zero),
    q_mask (Qb, S), bank_q int8 (Qb, k, T, dc) + bank_scale (Qb, k, T, 1)
    the gathered candidate rows, d_lens (Qb, k) live doc-token counts.
    Returns (Qb, k) f32. Doc positions >= d_lens are masked out of the
    max with a large-negative fill (not -inf: a zero-length doc must
    yield a finite very-bad score, and the caller's padded-candidate
    masking uses finite ``_NEG_INF`` sentinels downstream)."""
    d = bank_q.astype(jnp.float32) * bank_scale          # (Qb, k, T, dc)
    sim = jnp.einsum("qsd,qktd->qkst", q_tok.astype(jnp.float32), d)
    t_live = (
        jnp.arange(d.shape[2])[None, None, :] < d_lens[:, :, None]
    )                                                    # (Qb, k, T)
    sim = jnp.where(t_live[:, :, None, :], sim, -1e9)
    best = jnp.max(sim, axis=3)                          # (Qb, k, S)
    q_live = q_mask.astype(jnp.float32)[:, None, :]      # (Qb, 1, S)
    return jnp.sum(jnp.where(q_live > 0, best, 0.0), axis=2)


def maxsim_flops(q_seq: int, doc_seq: int, dc: int, pairs: int) -> float:
    """Model FLOPs of the MaxSim stage over ``pairs`` candidates: the
    (S, dc) x (dc, T) similarity gemm per pair. The per-query projection
    (S x H x dc, amortized over k candidates) is charged by the caller."""
    return float(pairs) * 2.0 * q_seq * doc_seq * dc


def projection_flops(q_seq: int, hidden: int, dc: int, queries: int) -> float:
    """FLOPs of projecting ``queries`` queries' token states to dc."""
    return float(queries) * 2.0 * q_seq * hidden * dc


def bank_row_bytes(doc_seq: int, dc: int) -> int:
    """Bank bytes per document row: int8 payload + f32 per-token scale."""
    return doc_seq * dc + doc_seq * 4
