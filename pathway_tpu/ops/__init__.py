"""pathway_tpu.ops — jitted TPU kernels (KNN distance+top-k) and the shared
padding discipline.

Padding policy: everything entering a jitted call is padded to a power-of-two
bucket so each (batch, seq) shape compiles once and the executable is reused
for the stream's life.
"""

import math


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor). ``floor`` must be a power of
    two; it sets the minimum bucket so tiny batches share one executable."""
    return max(floor, 1 << math.ceil(math.log2(max(n, 1))))
