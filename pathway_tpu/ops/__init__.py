"""pathway_tpu.ops — jitted TPU kernels (KNN distance+top-k) and the shared
padding discipline.

Padding policy: everything entering a jitted call is padded to a power-of-two
bucket so each (batch, seq) shape compiles once and the executable is reused
for the stream's life.
"""

import math


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor). ``floor`` must be a power of
    two; it sets the minimum bucket so tiny batches share one executable."""
    return max(floor, 1 << math.ceil(math.log2(max(n, 1))))


def canonical_metric(metric) -> str:
    """Normalise a metric name: anything starting with "l2" means squared
    L2; everything else is cosine/dot on normalised vectors."""
    return "l2" if str(metric).lower().startswith("l2") else "cos"


def prep_host_vectors(vectors, metric: str):
    """Host-side (numpy) prep shared by the vector indexes: (m, d) float32,
    unit-normalised for cosine (zero vectors pass through unscaled)."""
    import numpy as np

    v = np.asarray(vectors, dtype=np.float32)
    if v.ndim == 1:
        v = v[None, :]
    if metric == "cos":
        norms = np.linalg.norm(v, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        v = v / norms
    return v
