"""TPU compute kernels (JAX/XLA/Pallas) used by the engine and stdlib."""
