"""IVF-Flat approximate KNN on TPU — the ANN index, TPU-first.

The reference's approximate vector index is uSearch HNSW
(``src/external_integration/usearch_integration.rs``): a pointer-chasing
graph walk, inherently host-bound and irregular. The TPU-native ANN is
inverted-file (IVF): cluster the corpus into ``n_cells`` centroids
(mini-batch k-means — MXU gemms), store vectors cell-major in HBM, and
search by scoring the query against centroids (one small gemm), picking the
top ``nprobe`` cells, and running the exact gemm+top-k only over those
cells' members. Everything is dense, batched, statically shaped — the shape
of work the MXU wants — and compute drops by ~``n_cells / nprobe`` vs
brute force at recall governed by nprobe.

Layout: ``(n_cells, cell_capacity, d)`` bf16 + validity mask; appends are
on-device dynamic_update_slice writes into (cell, slot); deletes invalidate
slots (free-listed). Cell capacity doubles on overflow (rare recompiles,
like the brute-force index's capacity doubling).
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.ops import canonical_metric, next_pow2, prep_host_vectors

_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("n_iters",))
def kmeans_fit(vectors, centroids0, n_iters: int = 10):
    """Mini-batch-free k-means over ``vectors`` (N, d) f32 starting from
    ``centroids0`` (C, d); returns refined (C, d) f32 centroids. Dead
    centroids keep their previous position."""

    def step(centroids, _):
        scores = jnp.einsum("nd,cd->nc", vectors, centroids,
                            preferred_element_type=jnp.float32)
        n_norm = jnp.sum(vectors * vectors, axis=1, keepdims=True)
        c_norm = jnp.sum(centroids * centroids, axis=1)[None, :]
        assign = jnp.argmin(n_norm + c_norm - 2.0 * scores, axis=1)  # (N,)
        one_hot = jax.nn.one_hot(assign, centroids.shape[0],
                                 dtype=jnp.float32)  # (N, C)
        sums = jnp.einsum("nc,nd->cd", one_hot, vectors,
                          preferred_element_type=jnp.float32)
        counts = jnp.sum(one_hot, axis=0)[:, None]
        new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                        centroids)
        return new, None

    centroids, _ = jax.lax.scan(step, centroids0, None, length=n_iters)
    return centroids


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_slots(cells, valid, vecs, cell_arr, slot_arr):
    """One scatter dispatch for a whole append batch: vecs (m, d) into
    (cell_arr[i], slot_arr[i]) positions."""
    cells = cells.at[cell_arr, slot_arr].set(vecs.astype(cells.dtype))
    valid = valid.at[cell_arr, slot_arr].set(True)
    return cells, valid


@functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "metric")
)
def _ivf_search(cells, valid, centroids, queries, k: int, nprobe: int,
                metric: str):
    """queries (Q, d) f32 → (scores (Q, k), cell_ids (Q, k), slots (Q, k))."""
    q = queries.astype(jnp.float32)
    # 1. centroid scores: (Q, C) — pick top nprobe cells per query
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        cn = jnp.sum(centroids * centroids, axis=1)[None, :]
        cent_scores = -(qn + cn - 2.0 * q @ centroids.T)
    else:
        cent_scores = q @ centroids.T
    _, probe = jax.lax.top_k(cent_scores, nprobe)          # (Q, nprobe)

    # 2. gather probed cells and score members
    cand = jnp.take(cells, probe, axis=0)                  # (Q, np, cap, d)
    cand_valid = jnp.take(valid, probe, axis=0)            # (Q, np, cap)
    dots = jnp.einsum("qd,qpcd->qpc", q.astype(jnp.bfloat16),
                      cand, preferred_element_type=jnp.float32)
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1)[:, None, None]
        cn = jnp.sum(cand.astype(jnp.float32) ** 2, axis=3)
        scores = -(qn + cn - 2.0 * dots)
    else:
        scores = dots
    scores = jnp.where(cand_valid, scores, _NEG_INF)       # (Q, np, cap)

    Q, npr, cap = scores.shape
    flat = scores.reshape(Q, npr * cap)
    top_scores, flat_idx = jax.lax.top_k(flat, k)          # (Q, k)
    probe_idx = flat_idx // cap
    slots = flat_idx % cap
    cell_ids = jnp.take_along_axis(probe, probe_idx, axis=1)
    return top_scores, cell_ids, slots


class IvfFlatIndex:
    """Single-device IVF-Flat ANN index (one instance per worker)."""

    def __init__(
        self,
        dimensions: int,
        n_cells: int = 64,
        nprobe: int = 8,
        metric: str = "cos",
        cell_capacity: int = 64,
        train_after: int | None = None,
        dtype=jnp.bfloat16,
    ):
        self.dim = dimensions
        self.metric = canonical_metric(metric)
        self.n_cells = n_cells
        self.nprobe = min(nprobe, n_cells)
        self.cell_cap = next_pow2(cell_capacity, 16)
        self.dtype = dtype
        # retrain once this many vectors have arrived (None: n_cells * 16)
        self.train_after = (
            n_cells * 16 if train_after is None else train_after
        )
        self._trained = False
        self._cells = jnp.zeros(
            (n_cells, self.cell_cap, dimensions), dtype=dtype
        )
        self._valid = jnp.zeros((n_cells, self.cell_cap), dtype=bool)
        self._centroids = None  # (C, d) f32; lazily seeded
        self.n = 0
        self._keys: dict[tuple[int, int], Any] = {}   # (cell, slot) -> key
        self._loc: dict[Any, tuple[int, int]] = {}    # key -> (cell, slot)
        self._fill: list[int] = [0] * n_cells         # next free slot hint
        self._free: list[list[int]] = [[] for _ in range(n_cells)]
        self._pending: list[np.ndarray] = []          # vectors seen pre-train

    # ------------------------------------------------------------- internals
    def _prep(self, vectors) -> np.ndarray:
        return prep_host_vectors(vectors, self.metric)

    def _seed_centroids(self, v: np.ndarray) -> None:
        if self._centroids is not None:
            return
        reps = int(np.ceil(self.n_cells / max(len(v), 1)))
        seed = np.tile(v, (reps, 1))[: self.n_cells]
        jitter = np.random.default_rng(0).normal(
            scale=1e-3, size=seed.shape
        )
        self._centroids = jnp.asarray(seed + jitter, dtype=jnp.float32)

    def _maybe_train(self) -> None:
        if self._trained or self.n < self.train_after:
            return
        sample = np.concatenate(self._pending)[-self.train_after * 4:]
        self._centroids = kmeans_fit(
            jnp.asarray(sample, dtype=jnp.float32), self._centroids
        )
        self._trained = True
        self._pending.clear()
        self._rebuild()

    def _rebuild(self) -> None:
        """Re-assign every stored vector to the new centroids."""
        items = [(key, (c, s)) for key, (c, s) in self._loc.items()]
        if not items:
            return
        host_cells = np.asarray(self._cells, dtype=np.float32)
        vecs = np.stack([host_cells[c, s] for _, (c, s) in items])
        keys = [key for key, _ in items]
        self._cells = jnp.zeros_like(self._cells)
        self._valid = jnp.zeros_like(self._valid)
        self._keys.clear()
        self._loc.clear()
        self._fill = [0] * self.n_cells
        self._free = [[] for _ in range(self.n_cells)]
        self.n = 0
        self._insert(keys, vecs, record_pending=False)

    def _grow_cells(self) -> None:
        new_cap = self.cell_cap * 2
        cells = jnp.zeros((self.n_cells, new_cap, self.dim), dtype=self.dtype)
        cells = jax.lax.dynamic_update_slice(cells, self._cells, (0, 0, 0))
        valid = jnp.zeros((self.n_cells, new_cap), dtype=bool)
        valid = jax.lax.dynamic_update_slice(valid, self._valid, (0, 0))
        self._cells, self._valid = cells, valid
        self.cell_cap = new_cap

    def _alloc_slot(self, cell: int) -> int:
        if self._free[cell]:
            return self._free[cell].pop()
        if self._fill[cell] >= self.cell_cap:
            self._grow_cells()
        slot = self._fill[cell]
        self._fill[cell] += 1
        return slot

    def _insert(self, keys: list, v: np.ndarray,
                record_pending: bool = True) -> None:
        self._seed_centroids(v)
        scores = np.asarray(
            jnp.asarray(v, jnp.float32) @ self._centroids.T
        )
        if self.metric == "l2":
            vn = np.sum(v * v, axis=1, keepdims=True)
            cn = np.asarray(
                jnp.sum(self._centroids * self._centroids, axis=1)
            )[None, :]
            scores = -(vn + cn - 2.0 * scores)
        cells_of = np.argmax(scores, axis=1)
        slots = np.empty(len(keys), dtype=np.int32)
        for i, key in enumerate(keys):
            cell = int(cells_of[i])
            slot = self._alloc_slot(cell)
            slots[i] = slot
            self._keys[(cell, slot)] = key
            self._loc[key] = (cell, slot)
            self.n += 1
        self._cells, self._valid = _write_slots(
            self._cells, self._valid, jnp.asarray(v),
            jnp.asarray(cells_of.astype(np.int32)), jnp.asarray(slots),
        )
        if record_pending and not self._trained:
            self._pending.append(v)

    # ---------------------------------------------------------------- public
    def add(self, keys: list, vectors) -> None:
        if not keys:
            return
        self._insert(keys, self._prep(vectors))
        self._maybe_train()

    def remove(self, keys: list) -> None:
        cells, slots = [], []
        for key in keys:
            loc = self._loc.pop(key, None)
            if loc is None:
                continue
            cell, slot = loc
            cells.append(cell)
            slots.append(slot)
            self._keys.pop((cell, slot), None)
            self._free[cell].append(slot)
            self.n -= 1
        if cells:  # one dispatch for the whole removal batch
            self._valid = self._valid.at[
                jnp.asarray(cells, jnp.int32), jnp.asarray(slots, jnp.int32)
            ].set(False)

    def search(self, queries, k: int) -> list[list[tuple[Any, float]]]:
        if self.n == 0:
            q = np.asarray(queries)
            nq = 1 if q.ndim == 1 else len(q)
            return [[] for _ in range(nq)]
        q = self._prep(queries)
        nq = len(q)
        bucket = next_pow2(nq, 16)
        if bucket > nq:
            q = np.concatenate([q, np.zeros((bucket - nq, self.dim),
                                            np.float32)])
        k_eff = min(k, self.nprobe * self.cell_cap)
        scores, cell_ids, slots = jax.device_get(
            _ivf_search(
                self._cells, self._valid, self._centroids,
                jnp.asarray(q), k_eff, self.nprobe, self.metric,
            )
        )
        out = []
        for qi in range(nq):
            row = []
            for j in range(k_eff):
                s = float(scores[qi, j])
                if s <= _NEG_INF / 2:
                    break
                key = self._keys.get((int(cell_ids[qi, j]),
                                      int(slots[qi, j])))
                if key is not None:
                    row.append((key, s))
                if len(row) >= k:
                    break
            out.append(row)
        return out

    def __len__(self) -> int:
        return self.n
