"""IVF-Flat approximate KNN on TPU — the ANN index, TPU-first.

The reference's approximate vector index is uSearch HNSW
(``src/external_integration/usearch_integration.rs``): a pointer-chasing
graph walk, inherently host-bound and irregular. The TPU-native ANN is
inverted-file (IVF): cluster the corpus into ``n_cells`` centroids
(mini-batch k-means — MXU gemms), store vectors cell-major in HBM, and
search by scoring the query against centroids (one small gemm), picking the
top ``nprobe`` cells, and running the exact gemm+top-k only over those
cells' members. Everything is dense, batched, statically shaped — the shape
of work the MXU wants — and compute drops by ~``n_cells / nprobe`` vs
brute force at recall governed by nprobe.

Layout: ``(n_cells, cell_capacity, d)`` bf16 + validity mask; appends are
on-device dynamic_update_slice writes into (cell, slot); deletes invalidate
slots (free-listed). Cell capacity doubles on overflow (rare recompiles,
like the brute-force index's capacity doubling).
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.ops import canonical_metric, next_pow2, prep_host_vectors

_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=("n_iters", "block"))
def kmeans_fit(vectors, centroids0, n_iters: int = 10, block: int = 8192):
    """Mini-batch-free k-means over ``vectors`` (N, d) f32 starting from
    ``centroids0`` (C, d); returns refined (C, d) f32 centroids. Dead
    centroids keep their previous position. Assignment and accumulation
    run BLOCKED over rows: the (N, C) score/one-hot temps of the naive
    form are ~17 GB at N=256k, C=16k (measured OOM) — blocking caps them
    at (block, C)."""
    n, dim = vectors.shape
    c = centroids0.shape[0]
    pad = (-n) % block
    if pad:
        vectors = jnp.pad(vectors, ((0, pad), (0, 0)))
    weights = (jnp.arange(n + pad) < n).astype(jnp.float32)
    vb = vectors.reshape(-1, block, dim)
    wb = weights.reshape(-1, block)

    def step(centroids, _):
        c_norm = jnp.sum(centroids * centroids, axis=1)[None, :]

        def blk(inner, inp):
            sums, counts = inner
            v, w = inp
            scores = jnp.einsum("nd,cd->nc", v, centroids,
                                preferred_element_type=jnp.float32)
            n_norm = jnp.sum(v * v, axis=1, keepdims=True)
            assign = jnp.argmin(n_norm + c_norm - 2.0 * scores, axis=1)
            oh = jax.nn.one_hot(assign, c, dtype=jnp.float32) * w[:, None]
            sums = sums + jnp.einsum("nc,nd->cd", oh, v,
                                     preferred_element_type=jnp.float32)
            counts = counts + jnp.sum(oh, axis=0)
            return (sums, counts), None

        (sums, counts), _ = jax.lax.scan(
            blk,
            (jnp.zeros((c, dim), jnp.float32), jnp.zeros((c,), jnp.float32)),
            (vb, wb),
        )
        counts = counts[:, None]
        new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                        centroids)
        return new, None

    centroids, _ = jax.lax.scan(step, centroids0, None, length=n_iters)
    return centroids


# a row tries up to its 32 nearest cells (capped at nprobe per index —
# see _insert) before the index resorts to growing EVERY cell's
# capacity: the grow path doubles the dominant HBM tensor (and its
# eager update can't donate), so spilling further is vastly cheaper
# than growing for skewed/clustered data (cluster-core cells saturate
# at ~5x the mean fill). Spilled rows stay FINDABLE because a row's
# cell is within its own top-nprobe cells, which a query near it probes.
_SPILL_CANDIDATES = 32


@functools.partial(jax.jit, donate_argnums=(0,))
def _zeros_like_donated(x):
    """Zero a buffer IN PLACE (donation reuses the argument's HBM)."""
    return jnp.zeros_like(x)


# row-block size for cell assignment: the (block, n_cells) score matrix
# is the dominant temp — 8k rows x 32k cells x 4B = 1 GB regardless of
# how big an insert batch the caller hands us (an unblocked 512k-row
# batch against 16k cells needed a 34 GB score matrix: measured OOM)
_ASSIGN_BLOCK = 8192


@functools.partial(jax.jit, static_argnames=("metric", "top_c"))
def _assign_cells_block(v, centroids, metric: str,
                        top_c: int = _SPILL_CANDIDATES):
    scores = v @ centroids.T
    if metric == "l2":
        vn = jnp.sum(v * v, axis=1, keepdims=True)
        cn = jnp.sum(centroids * centroids, axis=1)[None, :]
        scores = -(vn + cn - 2.0 * scores)
    _, idx = jax.lax.top_k(scores, min(top_c, centroids.shape[0]))
    return idx.astype(jnp.int32)


def _assign_cells(v, centroids, metric: str, top_c: int = _SPILL_CANDIDATES):
    """Top-``top_c`` nearest centroids per insert-batch row, (m, top_c)
    int32, best first. Inserts SPILL to the next-nearest cell when the best
    one is full — growing every cell's capacity for one hot cell would
    multiply HBM use (a dense (cells, cap, d) layout pays capacity
    globally). Blocked over rows so arbitrarily large insert batches keep
    a bounded score-matrix footprint."""
    m = v.shape[0]
    if m <= _ASSIGN_BLOCK:
        return _assign_cells_block(v, centroids, metric, top_c)
    outs = []
    for s in range(0, m, _ASSIGN_BLOCK):
        outs.append(
            _assign_cells_block(
                v[s : s + _ASSIGN_BLOCK], centroids, metric, top_c
            )
        )
    return jnp.concatenate(outs, axis=0)


@functools.partial(
    jax.jit, donate_argnums=(0, 1), donate_argnames=("scales",)
)
def _write_slots(cells, valid, vecs, cell_arr, slot_arr, scales=None):
    """One scatter dispatch for a whole append batch: vecs (m, d) into
    (cell_arr[i], slot_arr[i]) positions. With ``scales`` (int8 storage)
    each row is symmetric-quantized on device: q = round(v / s),
    s = max|v| / 127 — the scale lands in the parallel (C, cap) array."""
    if scales is not None:
        v = vecs.astype(jnp.float32)
        s = jnp.max(jnp.abs(v), axis=1) / 127.0
        s = jnp.maximum(s, 1e-12)
        q = jnp.clip(jnp.round(v / s[:, None]), -127, 127).astype(jnp.int8)
        cells = cells.at[cell_arr, slot_arr].set(q)
        scales = scales.at[cell_arr, slot_arr].set(s.astype(scales.dtype))
        valid = valid.at[cell_arr, slot_arr].set(True)
        return cells, valid, scales
    cells = cells.at[cell_arr, slot_arr].set(vecs.astype(cells.dtype))
    valid = valid.at[cell_arr, slot_arr].set(True)
    return cells, valid, None


@functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "metric")
)
def _ivf_search(cells, valid, centroids, queries, k: int, nprobe: int,
                metric: str, scales=None):
    """queries (Q, d) f32 → (scores (Q, k), cell_ids (Q, k), slots (Q, k)).

    With ``scales`` (int8 cells) the member scoring runs on the int8 MXU
    path: queries symmetric-quantize per row, the candidate dot products
    accumulate in int32, and the result rescales by qscale*cellscale —
    measured ~1.9x the bf16 gemm rate in isolation, and HALF the HBM bytes
    per probed row (the actual limiter of batched ANN at scale)."""
    q = queries.astype(jnp.float32)
    # 1. centroid scores: (Q, C) — pick top nprobe cells per query
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        cn = jnp.sum(centroids * centroids, axis=1)[None, :]
        cent_scores = -(qn + cn - 2.0 * q @ centroids.T)
    else:
        cent_scores = q @ centroids.T
    _, probe = jax.lax.top_k(cent_scores, nprobe)          # (Q, nprobe)

    # 2. gather probed cells and score members
    cand = jnp.take(cells, probe, axis=0)                  # (Q, np, cap, d)
    cand_valid = jnp.take(valid, probe, axis=0)            # (Q, np, cap)
    if scales is not None:
        qs = jnp.maximum(jnp.max(jnp.abs(q), axis=1) / 127.0, 1e-12)
        qi = jnp.clip(
            jnp.round(q / qs[:, None]), -127, 127
        ).astype(jnp.int8)
        di = jnp.einsum("qd,qpcd->qpc", qi, cand,
                        preferred_element_type=jnp.int32)
        cand_scales = jnp.take(scales, probe, axis=0)      # (Q, np, cap)
        dots = (
            di.astype(jnp.float32)
            * qs[:, None, None]
            * cand_scales.astype(jnp.float32)
        )
    else:
        dots = jnp.einsum("qd,qpcd->qpc", q.astype(jnp.bfloat16),
                          cand, preferred_element_type=jnp.float32)
    if metric == "l2":
        qn = jnp.sum(q * q, axis=1)[:, None, None]
        if scales is not None:
            cn = jnp.sum(
                (cand.astype(jnp.float32)
                 * cand_scales.astype(jnp.float32)[..., None]) ** 2,
                axis=3,
            )
        else:
            cn = jnp.sum(cand.astype(jnp.float32) ** 2, axis=3)
        scores = -(qn + cn - 2.0 * dots)
    else:
        scores = dots
    scores = jnp.where(cand_valid, scores, _NEG_INF)       # (Q, np, cap)

    from pathway_tpu.ops.knn import topk_scores

    Q, npr, cap = scores.shape
    flat = scores.reshape(Q, npr * cap)
    top_scores, flat_idx = topk_scores(flat, k)            # (Q, k)
    probe_idx = flat_idx // cap
    slots = flat_idx % cap
    cell_ids = jnp.take_along_axis(probe, probe_idx, axis=1)
    return top_scores, cell_ids, slots


class IvfFlatIndex:
    """Single-device IVF-Flat ANN index (one instance per worker)."""

    def __init__(
        self,
        dimensions: int,
        n_cells: int = 64,
        nprobe: int = 8,
        metric: str = "cos",
        cell_capacity: int = 64,
        train_after: int | None = None,
        dtype=jnp.bfloat16,
    ):
        self.dim = dimensions
        self.metric = canonical_metric(metric)
        self.n_cells = n_cells
        self.nprobe = min(nprobe, n_cells)
        # round to a sublane multiple, NOT a pow2: pow2 rounding silently
        # grew cell_capacity=640 to 1024 — +60% on the dominant HBM
        # tensor, which is exactly what capacity budgets are sized against
        self.cell_cap = max(16, -(-int(cell_capacity) // 16) * 16)
        self.dtype = dtype
        # retrain once this many vectors have arrived (None: n_cells * 16)
        self.train_after = (
            n_cells * 16 if train_after is None else train_after
        )
        self._trained = False
        self._cells = jnp.zeros(
            (n_cells, self.cell_cap, dimensions), dtype=dtype
        )
        # int8 storage: per-slot symmetric-quantization scale (the member
        # vector is q * scale). None for float/bf16 cells.
        self._scales = (
            jnp.zeros((n_cells, self.cell_cap), dtype=jnp.float32)
            if dtype == jnp.int8
            else None
        )
        self._valid = jnp.zeros((n_cells, self.cell_cap), dtype=bool)
        self._centroids = None  # (C, d) f32; lazily seeded
        self.n = 0
        self._keys: dict[tuple[int, int], Any] = {}   # (cell, slot) -> key
        self._loc: dict[Any, tuple[int, int]] = {}    # key -> (cell, slot)
        self._fill: list[int] = [0] * n_cells         # next free slot hint
        self._free: list[list[int]] = [[] for _ in range(n_cells)]
        # pre-train vectors + their keys, kept HOST-side: the post-training
        # rebuild re-inserts from here — fetching the device cell tensor
        # back would move GBs over a relayed link
        self._pending: list[np.ndarray] = []
        self._pending_keys: list[list] = []

    # ------------------------------------------------------------- internals
    def _prep(self, vectors) -> np.ndarray:
        return prep_host_vectors(vectors, self.metric)

    @staticmethod
    def _on_device(v) -> bool:
        return isinstance(v, jax.Array)

    def _seed_centroids(self, v) -> None:
        if self._centroids is not None:
            return
        reps = int(np.ceil(self.n_cells / max(len(v), 1)))
        jitter = np.random.default_rng(0).normal(
            scale=1e-3, size=(self.n_cells, self.dim)
        ).astype(np.float32)
        if self._on_device(v):
            seed = jnp.tile(v, (reps, 1))[: self.n_cells]
            self._centroids = seed.astype(jnp.float32) + jnp.asarray(jitter)
        else:
            seed = np.tile(v, (reps, 1))[: self.n_cells]
            self._centroids = jnp.asarray(
                seed + jitter, dtype=jnp.float32
            )

    def _maybe_train(self) -> None:
        if self._trained or self.n < self.train_after:
            return
        if any(self._on_device(p) for p in self._pending):
            sample = jnp.concatenate(
                [jnp.asarray(p) for p in self._pending]
            )[-self.train_after * 4:]
        else:
            sample = jnp.asarray(
                np.concatenate(self._pending)[-self.train_after * 4:],
                dtype=jnp.float32,
            )
        self._centroids = kmeans_fit(
            sample.astype(jnp.float32), self._centroids
        )
        # drop the training sample BEFORE the rebuild: at big-corpus
        # scales the cells tensor + rebuild working set need every spare
        # byte of HBM, and this frame would otherwise pin the sample copy
        del sample
        self._trained = True
        self._rebuild()

    def _rebuild(self) -> None:
        """Re-assign every pre-training vector to the trained centroids —
        from the pending copies (host np for the host ingest path, device
        chunks for ``add_device`` — no device readback either way)."""
        if not self._pending:
            return
        # LATEST copy per key wins (a key removed and re-added pre-training
        # has several pending rows; re-inserting all of them would leave
        # stale vectors live under the same key), and keys removed outright
        # are dropped
        latest: dict[Any, tuple[int, int]] = {}
        for ai, ks in enumerate(self._pending_keys):
            for ri, k in enumerate(ks):
                latest[k] = (ai, ri)
        if any(self._on_device(p) for p in self._pending):
            # device path: re-insert chunk by chunk with device gathers
            # (a per-row host stack would fetch GBs over the link)
            live = set(self._loc)
            chunks = self._pending
            keysets = self._pending_keys
            self._pending = []
            self._pending_keys = []
            self._reset_cells()
            for ai, (chunk, ks) in enumerate(zip(chunks, keysets)):
                sel = [
                    ri
                    for ri, k in enumerate(ks)
                    if k in live and latest[k] == (ai, ri)
                ]
                if not sel:
                    continue
                self._insert(
                    [ks[ri] for ri in sel],
                    jnp.asarray(chunk)[jnp.asarray(sel, jnp.int32)],
                    record_pending=False,
                )
            return
        keys = [k for k in latest if k in self._loc]
        vecs = (
            np.stack([self._pending[latest[k][0]][latest[k][1]] for k in keys])
            if keys
            else np.zeros((0, self.dim), np.float32)
        )
        self._pending.clear()
        self._pending_keys.clear()
        self._reset_cells()
        if len(keys):
            self._insert(keys, vecs, record_pending=False)

    def _reset_cells(self) -> None:
        # donated zeroing: plain zeros_like would allocate the NEW cell
        # tensor while the old one is still referenced — a transient 2x
        # of the dominant HBM object (measured OOM at a 8.5 GiB tensor)
        self._cells = _zeros_like_donated(self._cells)
        self._valid = _zeros_like_donated(self._valid)
        if self._scales is not None:
            self._scales = _zeros_like_donated(self._scales)
        self._keys.clear()
        self._loc.clear()
        self._fill = [0] * self.n_cells
        self._free = [[] for _ in range(self.n_cells)]
        self.n = 0

    def _grow_cells(self) -> None:
        new_cap = self.cell_cap * 2
        new_bytes = (
            self.n_cells * new_cap * self.dim
            * jnp.zeros((), self.dtype).dtype.itemsize
        )
        if new_bytes > 7 << 30:
            # the grow path temporarily holds old + new cell tensors (the
            # eager update below cannot donate); past ~7 GiB the doubled
            # tensor cannot fit HBM anyway — fail with an actionable
            # message instead of an opaque device OOM
            raise RuntimeError(
                f"IVF cell capacity exhausted at {self.n} rows "
                f"(n_cells={self.n_cells}, cell_capacity={self.cell_cap}, "
                f"spill={_SPILL_CANDIDATES}): growing would need "
                f"{new_bytes / (1 << 30):.1f} GiB; raise cell_capacity "
                f"or n_cells up front"
            )
        cells = jnp.zeros((self.n_cells, new_cap, self.dim), dtype=self.dtype)
        cells = jax.lax.dynamic_update_slice(cells, self._cells, (0, 0, 0))
        valid = jnp.zeros((self.n_cells, new_cap), dtype=bool)
        valid = jax.lax.dynamic_update_slice(valid, self._valid, (0, 0))
        if self._scales is not None:
            scales = jnp.zeros((self.n_cells, new_cap), dtype=jnp.float32)
            self._scales = jax.lax.dynamic_update_slice(
                scales, self._scales, (0, 0)
            )
        self._cells, self._valid = cells, valid
        self.cell_cap = new_cap

    def _alloc_slot(self, cell: int) -> int | None:
        """Next free slot in ``cell``, or None when it is full (caller
        spills to the next candidate cell)."""
        if self._free[cell]:
            return self._free[cell].pop()
        if self._fill[cell] >= self.cell_cap:
            return None
        slot = self._fill[cell]
        self._fill[cell] += 1
        return slot

    def _insert(self, keys: list, v: np.ndarray,
                record_pending: bool = True) -> None:
        self._seed_centroids(v)
        # cell assignment on DEVICE (one small gemm + top-k per batch; the
        # host-side matmul dominated million-row builds), one fetch of the
        # int32 candidate matrix (m, top_c) best-first
        # spill reach is capped at nprobe: a row in its rank-k cell is
        # only findable when queries probe >= k cells, so spilling past
        # nprobe would trade silent recall loss for capacity
        top_c = max(4, min(_SPILL_CANDIDATES, self.nprobe))
        cand = np.asarray(
            jax.device_get(
                _assign_cells(
                    jnp.asarray(v, jnp.float32), self._centroids,
                    self.metric, top_c=top_c,
                )
            )
        )
        if any(self._free):
            cells_used, slots = self._alloc_rows_slow(cand)
        else:
            cells_used, slots = self._alloc_rows_bulk(cand)
        for i, key in enumerate(keys):
            cell, slot = int(cells_used[i]), int(slots[i])
            self._keys[(cell, slot)] = key
            self._loc[key] = (cell, slot)
        self.n += len(keys)
        self._cells, self._valid, scales = _write_slots(
            self._cells, self._valid, jnp.asarray(v),
            jnp.asarray(cells_used), jnp.asarray(slots),
            scales=self._scales,
        )
        if scales is not None:
            self._scales = scales
        if record_pending and not self._trained:
            self._pending.append(v)
            self._pending_keys.append(list(keys))

    def _alloc_rows_slow(self, cand: np.ndarray):
        """Per-row allocation honoring free lists (post-remove inserts)."""
        m = len(cand)
        cells_used = np.empty(m, dtype=np.int32)
        slots = np.empty(m, dtype=np.int32)
        for i in range(m):
            slot = None
            cell = int(cand[i, 0])
            for c in cand[i]:
                slot = self._alloc_slot(int(c))
                if slot is not None:
                    cell = int(c)
                    break
            if slot is None:
                # every nearby cell is full: grow capacity (rare — spill
                # absorbs ordinary imbalance)
                self._grow_cells()
                slot = self._alloc_slot(cell)
            cells_used[i] = cell
            slots[i] = slot
        return cells_used, slots

    def _alloc_rows_bulk(self, cand: np.ndarray):
        """Vectorized slot allocation for bulk builds (no free lists): per
        spill round, group rows by candidate cell and hand out consecutive
        slots up to capacity — a python-loop-per-row allocator measured
        ~250s on a million-row build; this is ~100x faster."""
        m = len(cand)
        cells_used = np.full(m, -1, dtype=np.int32)
        slots = np.full(m, -1, dtype=np.int32)
        fill = np.asarray(self._fill, dtype=np.int64)
        remaining = np.arange(m)
        for c_idx in range(cand.shape[1]):
            if not len(remaining):
                break
            cells = cand[remaining, c_idx].astype(np.int64)
            order = np.argsort(cells, kind="stable")
            sc = cells[order]
            uniq, starts = np.unique(sc, return_index=True)
            counts = np.diff(np.append(starts, len(sc)))
            take = np.minimum(counts, np.maximum(self.cell_cap - fill[uniq], 0))
            pos = np.arange(len(sc)) - np.repeat(starts, counts)
            ok = pos < np.repeat(take, counts)
            rows = remaining[order[ok]]
            cells_used[rows] = sc[ok]
            slots[rows] = (np.repeat(fill[uniq], counts) + pos)[ok]
            fill[uniq] += take
            remaining = remaining[order[~ok]]
        self._fill = fill.tolist()
        if len(remaining):
            # all candidate cells full for these rows: grow and finish on
            # the per-row path
            c2, s2 = self._alloc_rows_slow(cand[remaining])
            cells_used[remaining] = c2
            slots[remaining] = s2
        return cells_used, slots

    # ---------------------------------------------------------------- public
    def add(self, keys: list, vectors) -> None:
        if not keys:
            return
        v = self._prep(vectors)
        if len(keys) != len(v):
            raise ValueError(
                f"{len(keys)} keys for {len(v)} vectors"
            )
        self._insert(keys, v)
        self._maybe_train()

    def add_device(self, keys: list, vectors) -> None:
        """Fast path for vectors already ON DEVICE (e.g. straight out of
        the embedder, or generated on-chip): normalizes, assigns cells,
        and writes slots without moving the vectors over the host link;
        pre-training pending copies stay device-resident too. Only the
        tiny (m, spill) candidate matrix is fetched per batch."""
        if not keys:
            return
        v = jnp.asarray(vectors, jnp.float32)
        if v.ndim == 1:
            v = v[None, :]
        if len(keys) != v.shape[0]:
            raise ValueError(
                f"{len(keys)} keys for {v.shape[0]} vectors"
            )
        if self.metric == "cos":
            nrm = jnp.linalg.norm(v, axis=1, keepdims=True)
            v = v / jnp.maximum(nrm, 1e-12)
        self._insert(keys, v)
        self._maybe_train()

    def remove(self, keys: list) -> None:
        cells, slots = [], []
        for key in keys:
            loc = self._loc.pop(key, None)
            if loc is None:
                continue
            cell, slot = loc
            cells.append(cell)
            slots.append(slot)
            self._keys.pop((cell, slot), None)
            self._free[cell].append(slot)
            self.n -= 1
        if cells:  # one dispatch for the whole removal batch
            self._valid = self._valid.at[
                jnp.asarray(cells, jnp.int32), jnp.asarray(slots, jnp.int32)
            ].set(False)

    def search_device(self, queries, k: int):
        """Dispatch-only search: returns device ``(scores, cell_ids,
        slots)`` with the query axis padded to its pow2 bucket; NO host
        sync, so a pipeline can dispatch many searches and drain once
        (mirrors ``BruteForceKnnIndex.search_device``). The query bucket
        floor is 1 (not 16): the probed-cell gather costs HBM traffic per
        PADDED query row, so single-query streams must not pay 16x."""
        if self._centroids is None:
            raise ValueError(
                "search_device on an empty IvfFlatIndex (no vectors added); "
                "search() returns empty rows for this case"
            )
        q = self._prep(queries)
        nq = len(q)
        bucket = next_pow2(nq, 1)
        if bucket > nq:
            q = np.concatenate(
                [q, np.zeros((bucket - nq, self.dim), np.float32)]
            )
        k_eff = min(k, self.nprobe * self.cell_cap)
        return _ivf_search(
            self._cells, self._valid, self._centroids,
            jnp.asarray(q), k_eff, self.nprobe, self.metric,
            scales=self._scales,
        )

    def resolve(self, scores, idx_cells, idx_slots, nq: int,
                k: int) -> list[list[tuple[Any, float]]]:
        """Map fetched (host) search arrays back to [(key, score)] rows."""
        scores = np.asarray(scores)
        cell_ids = np.asarray(idx_cells)
        slots = np.asarray(idx_slots)
        out = []
        for qi in range(nq):
            row = []
            for j in range(scores.shape[1]):
                s = float(scores[qi, j])
                if s <= _NEG_INF / 2:
                    break
                key = self._keys.get((int(cell_ids[qi, j]),
                                      int(slots[qi, j])))
                if key is not None:
                    row.append((key, s))
                if len(row) >= k:
                    break
            out.append(row)
        return out

    def search(self, queries, k: int) -> list[list[tuple[Any, float]]]:
        from pathway_tpu.engine.probes import record_retrieval_backend

        if self.n == 0:
            q = np.asarray(queries)
            nq = 1 if q.ndim == 1 else len(q)
            record_retrieval_backend("ivf", nq)
            return [[] for _ in range(nq)]
        q = self._prep(queries)  # idempotent; search_device re-prep is a no-op
        record_retrieval_backend("ivf", len(q))
        scores, cell_ids, slots = jax.device_get(self.search_device(q, k))
        return self.resolve(scores, cell_ids, slots, len(q), k)

    def __len__(self) -> int:
        return self.n
