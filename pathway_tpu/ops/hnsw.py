"""Host-side HNSW graph index.

Reference parity: the uSearch HNSW integration
(``src/external_integration/usearch_integration.rs:163`` — connectivity /
expansion_add / expansion_search knobs). This engine's PRIMARY ANN is the
TPU-native IVF (``ops/ivf.py``) — a gemm-shaped probe that rides the MXU,
which is how approximate search *should* look on this hardware. The HNSW
here completes the reference's named index family for workloads that want
a graph index semantics-for-semantics (incremental insert, no training
step, sub-linear host-side search with no device round trip at all): a
small-vector/side-table index next to a TPU pipeline.

Pure numpy; scoring batches each candidate frontier's neighbors into one
matrix-vector product. Deletions are mask-style (usearch semantics):
removed keys stop appearing in results; their graph nodes keep serving as
routing waypoints.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np


class HnswIndex:
    """Hierarchical Navigable Small World graph over host vectors.

    ``connectivity`` = M (per-node degree cap above level 0; level 0
    allows 2M), ``expansion_add`` / ``expansion_search`` = ef during
    construction / query. ``metric``: "cos" (vectors unit-normalized,
    score = dot) or "l2sq" (score = -squared distance) — both
    bigger-is-better, matching ``BruteForceKnnIndex.search``.
    """

    def __init__(self, dimensions: int, metric: str = "cos",
                 connectivity: int = 16, expansion_add: int = 128,
                 expansion_search: int = 64, seed: int = 0):
        if metric not in ("cos", "l2sq", "l2"):
            metric = "cos"
        self.dim = dimensions
        self.metric = "l2sq" if metric in ("l2sq", "l2") else "cos"
        self.M = max(2, int(connectivity) or 16)
        self.M0 = 2 * self.M
        self.ef_add = max(self.M + 1, int(expansion_add) or 128)
        self.ef_search = max(1, int(expansion_search) or 64)
        self._ml = 1.0 / math.log(self.M)
        self._rng = np.random.default_rng(seed)
        self._vecs = np.empty((0, dimensions), np.float32)
        self._n = 0  # live prefix of the (geometrically grown) _vecs
        self._keys: list[Any] = []
        self._slot_of: dict[Any, int] = {}
        self._levels: list[int] = []
        # per node: list of neighbor-lists, one per level 0..node_level
        self._nbrs: list[list[list[int]]] = []
        self._deleted: set[int] = set()
        self._entry: int | None = None
        self._max_level = -1

    def __len__(self) -> int:
        return len(self._keys) - len(self._deleted)

    # ---- scoring ---------------------------------------------------------
    def _scores(self, idxs: np.ndarray, q: np.ndarray) -> np.ndarray:
        sub = self._vecs[idxs]
        if self.metric == "cos":
            return sub @ q
        d = sub - q[None, :]
        return -np.einsum("ij,ij->i", d, d)

    def _norm(self, v: np.ndarray) -> np.ndarray:
        if self.metric != "cos":
            return v
        n = np.linalg.norm(v, axis=-1, keepdims=True)
        return v / np.maximum(n, 1e-12)

    # ---- construction ----------------------------------------------------
    def add(self, keys: list, vectors) -> None:
        vecs = self._norm(np.asarray(vectors, np.float32).reshape(
            len(keys), self.dim
        ))
        start = len(self._keys)
        need = start + len(keys)
        if need > len(self._vecs):
            # geometric growth: streaming per-step adds must not copy the
            # whole matrix per batch (O(N^2) ingestion otherwise)
            cap = max(need, 2 * len(self._vecs), 1024)
            grown = np.empty((cap, self.dim), np.float32)
            grown[:start] = self._vecs[:start]
            self._vecs = grown
        self._vecs[start:need] = vecs
        self._n = need
        for off, key in enumerate(keys):
            old = self._slot_of.get(key)
            if old is not None:
                # usearch upsert semantics: the old vector stops matching
                self._deleted.add(old)
            i = start + off
            self._slot_of[key] = i
            self._keys.append(key)
            self._insert(i)

    def _insert(self, i: int) -> None:
        level = int(-math.log(max(self._rng.random(), 1e-12)) * self._ml)
        self._levels.append(level)
        self._nbrs.append([[] for _ in range(level + 1)])
        if self._entry is None:
            self._entry = i
            self._max_level = level
            return
        q = self._vecs[i]
        eps = [self._entry]
        # greedy descent through levels above the node's own
        for lvl in range(self._max_level, level, -1):
            eps = [self._greedy(q, eps[0], lvl)]
        # ef-search + connect at each level the node lives on; the ef
        # result set seeds the NEXT level's search (algorithm 1, HNSW)
        for lvl in range(min(level, self._max_level), -1, -1):
            cand = self._ef_select(q, eps, lvl, self.ef_add)
            m = self.M0 if lvl == 0 else self.M
            chosen = self._select_heuristic(cand, m)
            self._nbrs[i][lvl] = list(chosen)
            for c in chosen:
                lst = self._nbrs[c][lvl]
                lst.append(i)
                cap = self.M0 if lvl == 0 else self.M
                if len(lst) > cap:
                    # re-select the over-full node's links with the same
                    # diversity heuristic (keeps long-range edges alive)
                    sc = self._scores(np.asarray(lst), self._vecs[c])
                    ranked = sorted(zip(sc.tolist(), lst), reverse=True)
                    self._nbrs[c][lvl] = self._select_heuristic(ranked, cap)
            eps = [c for _, c in cand]
        if level > self._max_level:
            self._max_level = level
            self._entry = i

    def _select_heuristic(self, cand: list[tuple[float, int]],
                          m: int) -> list[int]:
        """HNSW select-neighbors heuristic (algorithm 4): keep a candidate
        only if it is closer to the query than to every already-kept
        neighbor — preserving diverse/long-range edges instead of a
        mutually-clustered closest-m set; backfill if underfull. The
        candidate-pairwise scores come from ONE matmul (the per-pair
        loop was the construction bottleneck on host)."""
        if len(cand) <= 1:
            return [c for _, c in cand[:m]]
        ids = [c for _, c in cand]
        V = self._vecs[ids]
        if self.metric == "cos":
            pair = V @ V.T
        else:
            sq = np.einsum("ij,ij->i", V, V)
            pair = -(sq[:, None] + sq[None, :] - 2.0 * (V @ V.T))
        chosen_pos: list[int] = []
        for p, (s, _c) in enumerate(cand):
            if len(chosen_pos) >= m:
                break
            if chosen_pos and float(pair[p, chosen_pos].max()) > s:
                continue
            chosen_pos.append(p)
        if len(chosen_pos) < m:
            picked = set(chosen_pos)
            for p in range(len(cand)):
                if p not in picked:
                    chosen_pos.append(p)
                    picked.add(p)
                    if len(chosen_pos) >= m:
                        break
        return [ids[p] for p in chosen_pos]

    # ---- search ----------------------------------------------------------
    def _greedy(self, q: np.ndarray, ep: int, lvl: int) -> int:
        best = ep
        best_s = float(self._scores(np.asarray([ep]), q)[0])
        improved = True
        while improved:
            improved = False
            nb = self._nbrs[best][lvl] if lvl < len(self._nbrs[best]) else []
            if not nb:
                break
            sc = self._scores(np.asarray(nb), q)
            j = int(np.argmax(sc))
            if sc[j] > best_s:
                best, best_s = nb[j], float(sc[j])
                improved = True
        return best

    def _ef_select(self, q: np.ndarray, eps: list[int], lvl: int,
                   ef: int) -> list[tuple[float, int]]:
        """Best-first expansion keeping the top ``ef`` (score, idx),
        sorted by decreasing score. Deleted nodes still route."""
        import heapq

        seen = set(eps)
        init = self._scores(np.asarray(eps), q)
        # max-heap of frontier, min-heap of the kept set
        frontier = [(-float(s), e) for s, e in zip(init, eps)]
        heapq.heapify(frontier)
        kept = [(float(s), e) for s, e in zip(init, eps)]
        heapq.heapify(kept)
        while frontier:
            neg_s, e = heapq.heappop(frontier)
            if len(kept) >= ef and -neg_s < kept[0][0]:
                break
            nb = [
                n for n in (
                    self._nbrs[e][lvl] if lvl < len(self._nbrs[e]) else []
                )
                if n not in seen
            ]
            if not nb:
                continue
            seen.update(nb)
            sc = self._scores(np.asarray(nb), q)
            for s, n in zip(sc, nb):
                s = float(s)
                if len(kept) < ef:
                    heapq.heappush(kept, (s, n))
                    heapq.heappush(frontier, (-s, n))
                elif s > kept[0][0]:
                    heapq.heapreplace(kept, (s, n))
                    heapq.heappush(frontier, (-s, n))
        return sorted(kept, reverse=True)

    def remove(self, keys: list) -> None:
        for key in keys:
            i = self._slot_of.pop(key, None)
            if i is not None:
                self._deleted.add(i)

    def search(self, queries, k: int) -> list[list[tuple[Any, float]]]:
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        q = self._norm(q)
        out: list[list[tuple[Any, float]]] = []
        for row in q:
            if self._entry is None:
                out.append([])
                continue
            ep = self._entry
            for lvl in range(self._max_level, 0, -1):
                ep = self._greedy(row, ep, lvl)
            ef = max(self.ef_search, k)
            cand = self._ef_select(row, [ep], 0, ef + len(self._deleted))
            hits = [
                (self._keys[i], s)
                for s, i in cand
                if i not in self._deleted
                and self._slot_of.get(self._keys[i]) == i
            ]
            out.append(hits[:k])
        return out
