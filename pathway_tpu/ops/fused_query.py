"""Fused RAG query pipeline — ONE dispatch from query text to results.

The reference answers a query in stages (embed the query, search the
index, gather documents, rerank — ``xpacks/llm/vector_store.py:440``,
``question_answering.py``), each a separate host round trip. On a remote /
relayed TPU every stage costs a full dispatch RTT, so the stages dominate
end-to-end latency. TPU-first redesign: keep everything the query touches
RESIDENT in HBM — the embedding corpus (the brute-force index matrix) AND
the documents' token ids — and compile the whole pipeline into a single
executable:

    tokenize (host, C++)  →  [ encode+pool+normalize  →  gemm + top-k  →
    gather doc tokens  →  assemble [CLS] q [SEP] d [SEP] pairs  →
    cross-encoder  ]  →  one fetch

The bracketed section is one jit; a query costs exactly one round trip
whether it retrieves or retrieves-and-reranks.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.engine.probes import record_cascade, record_device_dispatch
from pathway_tpu.internals.config import pathway_config
from pathway_tpu.models.embedder import embed_fn, mean_pool
from pathway_tpu.models.tokenizer import PAD_ID, SEP_ID
from pathway_tpu.models.transformer import TransformerConfig, encode
from pathway_tpu.ops import next_pow2
from pathway_tpu.ops.knn import BruteForceKnnIndex, knn_scores, topk_scores
from pathway_tpu.ops.late_bank import (
    doc_token_states,
    late_projection,
    maxsim_flops,
    maxsim_scores,
    projection_flops,
    query_token_states,
)

_NEG_INF = -1e30


def _encoder_flops(cfg: TransformerConfig, seq: int, n_layers: int,
                   pairs: int) -> float:
    """Model FLOPs of ``pairs`` sequences of length ``seq`` through
    ``n_layers`` encoder layers (same accounting as bench.py's
    ``flops_per_doc``: qkv+attn-out+mlp gemms + 2 S^2 attention gemms)."""
    h, i = cfg.hidden, cfg.intermediate
    per_layer = 2 * seq * h * (3 * h + h + 2 * i) + 4 * seq * seq * h
    return float(pairs) * n_layers * per_layer


@functools.partial(
    jax.jit, static_argnames=("cfg", "k", "metric", "f32_scores")
)
def _fused_retrieve(params, q_ids, q_mask, corpus, valid,
                    cfg: TransformerConfig, k: int, metric: str,
                    f32_scores: bool = False):
    """Query encode + pool + normalise + corpus gemm + top-k, one dispatch.
    q_ids/q_mask: (Qb, S). Returns (scores (Qb, k), idx (Qb, k))."""
    emb = embed_fn(params, q_ids, q_mask, cfg)  # (Qb, H) unit vectors
    return topk_scores(
        knn_scores(corpus, valid, emb, metric, f32_scores=f32_scores), k
    )


def _assemble_pairs(q_ids_row, q_len, doc_tokens, doc_lens, pair_seq: int):
    """Build (k, pair_seq) cross-encoder inputs on device:
    ``[CLS] q [SEP] d [SEP]`` with masks and BERT segment ids. ``q_ids_row``
    is already ``[CLS] q [SEP]`` of true length ``q_len``; ``doc_tokens``
    (k, dseq) carry bare doc tokens of ``doc_lens`` each."""
    k, dseq = doc_tokens.shape
    j = jnp.arange(pair_seq)[None, :]                      # (1, P)
    q_pad = jnp.pad(q_ids_row, (0, max(pair_seq - q_ids_row.shape[0], 0)))
    q_part = q_pad[:pair_seq][None, :]                     # (1, P)
    dpos = jnp.clip(j - q_len, 0, dseq - 1)                # (1, P)
    d_vals = jnp.take_along_axis(
        doc_tokens, jnp.broadcast_to(dpos, (k, pair_seq)), axis=1
    )                                                      # (k, P)
    end = q_len + doc_lens[:, None]                        # (k, 1) SEP slot
    pair = jnp.where(
        j < q_len,
        jnp.broadcast_to(q_part, (k, pair_seq)),
        jnp.where(
            j < end, d_vals, jnp.where(j == end, SEP_ID, PAD_ID)
        ),
    )
    mask = (j <= end).astype(jnp.int32)
    ttype = ((j >= q_len) & (j <= end)).astype(jnp.int32)
    return pair.astype(jnp.int32), mask, ttype


@functools.partial(
    jax.jit,
    static_argnames=("e_cfg", "r_cfg", "k", "metric", "pair_seq"),
)
def _fused_retrieve_rerank(e_params, q_ids, q_mask, corpus, valid,
                           doc_tokens, doc_lens, r_params, r_head,
                           e_cfg: TransformerConfig,
                           r_cfg: TransformerConfig,
                           k: int, metric: str, pair_seq: int):
    """One dispatch: embed query -> top-k over the corpus -> gather the
    hit documents' token ids -> cross-encode (query, doc) pairs -> rerank.
    Single query (q_ids (1, S)). Returns (knn_scores (k,), idx (k,),
    rerank_scores (k,), order (k,))."""
    emb = embed_fn(e_params, q_ids, q_mask, e_cfg)           # (1, H)
    scores, idx = topk_scores(
        knn_scores(corpus, valid, emb, metric), k
    )                                                        # (1, k)
    idx0 = idx[0]
    d_tok = jnp.take(doc_tokens, idx0, axis=0)               # (k, dseq)
    d_len = jnp.take(doc_lens, idx0)                         # (k,)
    q_len = jnp.sum(q_mask[0]).astype(jnp.int32)
    pair, mask, ttype = _assemble_pairs(
        q_ids[0], q_len, d_tok, d_len, pair_seq
    )
    hidden = encode(r_params, pair, mask, r_cfg, ttype)
    cls = hidden[:, 0, :]
    pooled = jnp.tanh(
        cls @ r_params["pooler"]["w"].astype(jnp.float32)
        + r_params["pooler"]["b"].astype(jnp.float32)
    )
    r_scores = (pooled @ r_head["w"] + r_head["b"])[:, 0]    # (k,)
    # hits beyond the live corpus (padded capacity) must sort last
    r_scores = jnp.where(scores[0] <= _NEG_INF / 2, _NEG_INF, r_scores)
    order = jnp.argsort(-r_scores)
    return scores[0], idx0, r_scores, order


def _pair_scores(r_params, r_head, pair, mask, ttype,
                 r_cfg: TransformerConfig, n_layers: int | None = None):
    """Cross-encoder scores for a flat (B, P) pair batch: encode (optionally
    truncated to ``n_layers``) -> tanh pooler on [CLS] -> scalar head."""
    hidden = encode(r_params, pair, mask, r_cfg, ttype, n_layers=n_layers)
    cls = hidden[:, 0, :]
    pooled = jnp.tanh(
        cls @ r_params["pooler"]["w"].astype(jnp.float32)
        + r_params["pooler"]["b"].astype(jnp.float32)
    )
    return (pooled @ r_head["w"] + r_head["b"])[:, 0]


def _retrieve_and_assemble(e_params, q_ids, q_mask, corpus, valid,
                           doc_tokens, doc_lens,
                           e_cfg: TransformerConfig, k: int, metric: str,
                           pair_seq: int):
    """Shared front half of the batched rerank kernels: embed queries,
    top-k the corpus, gather hit docs, assemble (Qb, k, P) pair inputs."""
    emb = embed_fn(e_params, q_ids, q_mask, e_cfg)            # (Qb, H)
    scores, idx = topk_scores(
        knn_scores(corpus, valid, emb, metric), k
    )                                                         # (Qb, k)
    d_tok = jnp.take(doc_tokens, idx, axis=0)                 # (Qb, k, dseq)
    d_len = jnp.take(doc_lens, idx)                           # (Qb, k)
    q_len = jnp.sum(q_mask, axis=1).astype(jnp.int32)         # (Qb,)
    pair, mask, ttype = jax.vmap(
        functools.partial(_assemble_pairs, pair_seq=pair_seq)
    )(q_ids, q_len, d_tok, d_len)                             # (Qb, k, P)
    return scores, idx, pair, mask, ttype


@functools.partial(
    jax.jit,
    static_argnames=("e_cfg", "r_cfg", "k", "metric", "pair_seq"),
)
def _fused_retrieve_rerank_batch(e_params, q_ids, q_mask, corpus, valid,
                                 doc_tokens, doc_lens, r_params, r_head,
                                 e_cfg: TransformerConfig,
                                 r_cfg: TransformerConfig,
                                 k: int, metric: str, pair_seq: int):
    """Multi-query generalisation of :func:`_fused_retrieve_rerank` — the
    whole (Qb, k) candidate matrix cross-encodes as ONE flat batch, so a
    micro-batching tick of Qb queries still costs one dispatch. Returns
    (knn_scores (Qb, k), idx (Qb, k), rerank_scores (Qb, k), order (Qb, k))."""
    scores, idx, pair, mask, ttype = _retrieve_and_assemble(
        e_params, q_ids, q_mask, corpus, valid, doc_tokens, doc_lens,
        e_cfg, k, metric, pair_seq,
    )
    qb = q_ids.shape[0]
    flat = lambda a: a.reshape(qb * k, pair_seq)  # noqa: E731
    r_scores = _pair_scores(
        r_params, r_head, flat(pair), flat(mask), flat(ttype), r_cfg
    ).reshape(qb, k)
    # hits beyond the live corpus (padded capacity) must sort last
    r_scores = jnp.where(scores <= _NEG_INF / 2, _NEG_INF, r_scores)
    order = jnp.argsort(-r_scores, axis=1)
    return scores, idx, r_scores, order


@functools.partial(
    jax.jit,
    static_argnames=(
        "e_cfg", "r_cfg", "k", "metric", "pair_seq",
        "depth", "keep", "seed_weight",
    ),
)
def _fused_retrieve_rerank_cascade(e_params, q_ids, q_mask, corpus, valid,
                                   doc_tokens, doc_lens, r_params, r_head,
                                   e_cfg: TransformerConfig,
                                   r_cfg: TransformerConfig,
                                   k: int, metric: str, pair_seq: int,
                                   depth: int, keep: int,
                                   seed_weight: float):
    """Cascaded early-exit rerank, still ONE dispatch: a truncated-depth
    cheap pass (first ``depth`` layers + the score head, seeded with the
    retrieval score) ranks all k candidates; only the top ``keep``
    survivors pay the full cross-encoder. Survivor selection happens on
    device (``lax.top_k`` + gather), so the cheap and full stages share a
    single executable and a single round trip.

    Returns (knn_scores (Qb, k), idx (Qb, k), rerank_scores (Qb, k),
    order (Qb, k)). ``order`` lists survivors first (by full-depth score)
    then the rest (by cheap score); ``rerank_scores`` holds full-depth
    scores at survivor positions and cheap scores elsewhere — the two
    ranges are internally ordered but not mutually calibrated."""
    scores, idx, pair, mask, ttype = _retrieve_and_assemble(
        e_params, q_ids, q_mask, corpus, valid, doc_tokens, doc_lens,
        e_cfg, k, metric, pair_seq,
    )
    qb = q_ids.shape[0]
    flat = lambda a, n: a.reshape(qb * n, pair_seq)  # noqa: E731
    cheap = _pair_scores(
        r_params, r_head, flat(pair, k), flat(mask, k), flat(ttype, k),
        r_cfg, n_layers=depth,
    ).reshape(qb, k)
    # seed with the ranking signal retrieval already paid for
    cheap = cheap + jnp.float32(seed_weight) * scores.astype(jnp.float32)
    cheap = jnp.where(scores <= _NEG_INF / 2, _NEG_INF, cheap)
    _, surv = jax.lax.top_k(cheap, keep)                      # (Qb, keep)
    gather = lambda a: jnp.take_along_axis(  # noqa: E731
        a, surv[:, :, None], axis=1
    )
    full = _pair_scores(
        r_params, r_head,
        flat(gather(pair), keep), flat(gather(mask), keep),
        flat(gather(ttype), keep), r_cfg,
    ).reshape(qb, keep)
    surv_knn = jnp.take_along_axis(scores, surv, axis=1)
    full = jnp.where(surv_knn <= _NEG_INF / 2, _NEG_INF, full)
    rows = jnp.arange(qb)[:, None]
    r_scores = cheap.at[rows, surv].set(full)
    # survivors first, ranked by full-depth score; the cascaded-out rest
    # follow in cheap-score order
    surv_sorted = jnp.take_along_axis(surv, jnp.argsort(-full, axis=1), axis=1)
    # survivor slots drop to -inf, STRICTLY below the _NEG_INF of padded
    # candidates — otherwise (live docs < keep) they tie and the argsort
    # re-includes survivor indices, so ``order`` stops being a permutation
    rest = cheap.at[rows, surv].set(-jnp.inf)
    rest_order = jnp.argsort(-rest, axis=1)                   # survivors last
    order = jnp.concatenate([surv_sorted, rest_order[:, : k - keep]], axis=1)
    return scores, idx, r_scores, order


@functools.partial(
    jax.jit,
    static_argnames=(
        "e_cfg", "r_cfg", "k", "metric", "pair_seq", "keep", "seed_weight",
    ),
)
def _fused_retrieve_maxsim_cascade(e_params, q_ids, q_mask, corpus, valid,
                                   doc_tokens, doc_lens, bank_q, bank_scale,
                                   late_proj, r_params, r_head,
                                   e_cfg: TransformerConfig,
                                   r_cfg: TransformerConfig,
                                   k: int, metric: str, pair_seq: int,
                                   keep: int, seed_weight: float):
    """Late-interaction cascade, still ONE dispatch: the cheap stage is
    MaxSim over the candidates' ingest-time token banks instead of a
    truncated encoder pass, so it pays one (S, dc) x (dc, T) gemm per
    candidate — no query-time encoder FLOPs at all for the cascaded-out
    rest. The query encodes ONCE: the same token states feed the pooled
    retrieval embedding and the projected query tokens MaxSim dots
    against. Survivor selection, the full-depth pass and the order
    construction are IDENTICAL to :func:`_fused_retrieve_rerank_cascade`
    (the two kernels differ only in where ``cheap`` comes from).

    Returns (knn_scores (Qb, k), idx (Qb, k), rerank_scores (Qb, k),
    order (Qb, k)) with the same survivors-first contract."""
    hidden = encode(e_params, q_ids, q_mask, e_cfg)           # (Qb, S, H)
    pooled = mean_pool(hidden, q_mask)
    emb = pooled / jnp.clip(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9, None
    )
    scores, idx = topk_scores(
        knn_scores(corpus, valid, emb, metric), k
    )                                                         # (Qb, k)
    d_tok = jnp.take(doc_tokens, idx, axis=0)                 # (Qb, k, dseq)
    d_len = jnp.take(doc_lens, idx)                           # (Qb, k)
    q_len = jnp.sum(q_mask, axis=1).astype(jnp.int32)
    pair, mask, ttype = jax.vmap(
        functools.partial(_assemble_pairs, pair_seq=pair_seq)
    )(q_ids, q_len, d_tok, d_len)
    q_tok = query_token_states(hidden, q_mask, late_proj)     # (Qb, S, dc)
    b_q = jnp.take(bank_q, idx, axis=0)                       # (Qb, k, T, dc)
    b_s = jnp.take(bank_scale, idx, axis=0)                   # (Qb, k, T, 1)
    cheap = maxsim_scores(q_tok, q_mask, b_q, b_s, d_len)     # (Qb, k)
    # seed with the ranking signal retrieval already paid for
    cheap = cheap + jnp.float32(seed_weight) * scores.astype(jnp.float32)
    cheap = jnp.where(scores <= _NEG_INF / 2, _NEG_INF, cheap)
    qb = q_ids.shape[0]
    flat = lambda a, n: a.reshape(qb * n, pair_seq)  # noqa: E731
    _, surv = jax.lax.top_k(cheap, keep)                      # (Qb, keep)
    gather = lambda a: jnp.take_along_axis(  # noqa: E731
        a, surv[:, :, None], axis=1
    )
    full = _pair_scores(
        r_params, r_head,
        flat(gather(pair), keep), flat(gather(mask), keep),
        flat(gather(ttype), keep), r_cfg,
    ).reshape(qb, keep)
    surv_knn = jnp.take_along_axis(scores, surv, axis=1)
    full = jnp.where(surv_knn <= _NEG_INF / 2, _NEG_INF, full)
    rows = jnp.arange(qb)[:, None]
    r_scores = cheap.at[rows, surv].set(full)
    surv_sorted = jnp.take_along_axis(surv, jnp.argsort(-full, axis=1), axis=1)
    # survivor slots drop to -inf, STRICTLY below the _NEG_INF of padded
    # candidates (same permutation guarantee as the encoder cascade)
    rest = cheap.at[rows, surv].set(-jnp.inf)
    rest_order = jnp.argsort(-rest, axis=1)
    order = jnp.concatenate([surv_sorted, rest_order[:, : k - keep]], axis=1)
    return scores, idx, r_scores, order


class FusedRAGPipeline:
    """HBM-resident retrieval (+ optional rerank) with one-dispatch queries.

    ``add(keys, texts)`` embeds documents into the brute-force corpus AND
    stores their token ids on device; ``retrieve``/``retrieve_rerank`` then
    cost exactly one round trip. ``*_device`` variants return handles so a
    stream of queries can pipeline dispatches and drain once."""

    def __init__(self, embedder, reranker=None, *,
                 llm_reranker=None,
                 reserved_space: int = 1024, metric: str = "cos",
                 doc_seq: int = 96, pair_seq: int = 160):
        self.embedder = embedder          # SentenceEmbedderModel
        self.reranker = reranker          # CrossEncoderModel | None
        # optional listwise LLM final stage (PATHWAY_TPU_LLM_RERANK):
        # reorders cascade survivors host-side after the fused dispatch
        # resolves; doc texts are kept host-side for its prompts
        self.llm_reranker = llm_reranker  # ListwiseLLMReranker | None
        self._text_by_key: dict = {}
        self.metric = metric
        self.doc_seq = doc_seq
        self.pair_seq = pair_seq
        # the rerank pair is [CLS] q [SEP] d [SEP]: a query longer than
        # pair_seq - doc_seq - 1 would silently crowd the document out of
        # the cross-encoder input, so rerank queries truncate to this
        # budget (and it must leave room for a real query)
        self._rerank_q_budget = pair_seq - doc_seq - 1
        if self._rerank_q_budget < 8:
            raise ValueError(
                f"pair_seq={pair_seq} leaves only {self._rerank_q_budget} "
                f"query tokens next to doc_seq={doc_seq}; raise pair_seq "
                "or lower doc_seq"
            )
        self.index = BruteForceKnnIndex(
            dimensions=embedder.cfg.hidden,
            reserved_space=reserved_space, metric=metric,
        )
        # mesh-resident retrieval (PATHWAY_TPU_MESH): mirror the corpus
        # into a sharded IVF (one shard per device, ICI top-k merge) and
        # answer plain ``retrieve`` from it, so QueryServer queries scan
        # 1/dp of the corpus per chip. Exhaustive probing (nprobe ==
        # n_cells) keeps recall at 1.0 — the win here is the shard split,
        # not IVF pruning. Rerank keeps the fused dense path (its doc
        # gather + cross-encode is one dispatch against the dense slots).
        self.sharded_index = None
        from pathway_tpu.stdlib.indexing.nearest_neighbors import (
            mesh_retrieval_active,
        )

        if mesh_retrieval_active():
            import jax as _jax

            from pathway_tpu.parallel.mesh import make_mesh
            from pathway_tpu.parallel.sharded_ivf import ShardedIvfIndex

            devices = _jax.devices()
            self.sharded_index = ShardedIvfIndex(
                make_mesh(devices, dp=len(devices), tp=1),
                dimensions=embedder.cfg.hidden,
                n_cells=16, nprobe=16,
                metric="l2" if metric in ("l2", "l2sq") else "cos",
            )
        cap = self.index.capacity
        self._doc_tokens = jnp.zeros((cap, doc_seq), dtype=jnp.int32)
        self._doc_lens = jnp.zeros((cap,), dtype=jnp.int32)
        # longest stored doc-token row, tracked on host so the pair-packing
        # bucket is computable without a device round trip; monotone (not
        # lowered on remove) so it stays a safe upper bound
        self._max_doc_len = 0
        # late-interaction doc-token bank (PATHWAY_TPU_LATE_INTERACTION):
        # int8 per-token states + f32 scales, device-resident next to the
        # corpus. Allocated lazily at the first add/query with the flag
        # on — flag-off pipelines pay zero HBM — and dc freezes at that
        # first allocation. `_bank_valid` (host) tracks which slots hold
        # a current bank row, so rows ingested with the flag off backfill
        # lazily at query time instead of silently scoring garbage.
        self._bank_q = None       # (cap, doc_seq, dc) int8
        self._bank_scale = None   # (cap, doc_seq, 1) f32
        self._bank_valid = None   # (cap,) bool, host
        self._late_proj = None    # (H, dc) f32, shared ingest/query
        self._late_dim = 0

    # ------------------------------------------------------------- ingest
    def _doc_token_rows(self, texts: list[str]):
        tok = self.embedder.tokenizer
        ids = np.zeros((len(texts), self.doc_seq), dtype=np.int32)
        lens = np.zeros((len(texts),), dtype=np.int32)
        for i, t in enumerate(texts):
            seq = tok.tokenize_ids(t, self.doc_seq + 2)[1:-1]  # strip specials
            seq = seq[: self.doc_seq]
            ids[i, : len(seq)] = seq
            lens[i] = len(seq)
        return ids, lens

    def add(self, keys: list, texts: list[str]) -> None:
        if not keys:
            return
        start = self.index.n
        # fused embed+append: one dispatch from token ids to corpus rows
        # (the vectors never leave HBM; no transport cast, no separate
        # append enqueue)
        from pathway_tpu.models.embedder import embed_fn
        from pathway_tpu.models.tokenizer import pad_to_buckets

        m = self.embedder
        ids, mask = m.tokenizer(list(texts), max_length=m.max_length)
        ids, mask = pad_to_buckets(ids, mask)
        self.index.add_embed(
            keys, m.params, jnp.asarray(ids), jnp.asarray(mask), m.cfg,
            embed_fn,
        )
        if self.index.capacity != self._doc_tokens.shape[0]:
            grow = self.index.capacity - self._doc_tokens.shape[0]
            self._doc_tokens = jnp.pad(self._doc_tokens, ((0, grow), (0, 0)))
            self._doc_lens = jnp.pad(self._doc_lens, (0, grow))
        ids, lens = self._doc_token_rows(list(texts))
        if self.llm_reranker is not None:
            self._text_by_key.update(zip(keys, texts))
        if lens.size:
            self._max_doc_len = max(self._max_doc_len, int(lens.max()))
        self._doc_tokens = jax.lax.dynamic_update_slice(
            self._doc_tokens, jnp.asarray(ids), (start, 0)
        )
        self._doc_lens = jax.lax.dynamic_update_slice(
            self._doc_lens, jnp.asarray(lens), (start,)
        )
        if pathway_config.late_interaction or self._bank_q is not None:
            self._late_alloc()
            if pathway_config.late_interaction:
                # ingest-time bank build: ONE fused full-depth encode per
                # batch; queries will only ever gather + dequantize
                bq, bs = self._late_bank_rows(ids, lens)
                self._bank_q = jax.lax.dynamic_update_slice(
                    self._bank_q, bq, (start, 0, 0)
                )
                self._bank_scale = jax.lax.dynamic_update_slice(
                    self._bank_scale, bs, (start, 0, 0)
                )
                self._bank_valid[start:start + len(lens)] = True
            else:
                # flag flipped off mid-stream: new rows backfill on the
                # next late-interaction query
                self._bank_valid[start:start + len(lens)] = False
            self._record_late_bank()
        if self.sharded_index is not None:
            # mirror the just-embedded rows into the sharded IVF (slot
            # map, not [start:start+n] — upserts may have moved rows)
            slots = [self.index._slot_of[key] for key in keys]
            vecs = np.asarray(
                jnp.take(self.index._corpus, jnp.asarray(slots), axis=0),
                np.float32,
            )
            self.sharded_index.add(list(keys), vecs)

    # ------------------------------------------------------------ queries
    def _tokenize_queries(self, texts: list[str], max_length: int | None = None):
        """Tokenize + bucket-pad queries. Returns device arrays plus the
        true max query length (a host int, read from the numpy mask BEFORE
        transfer so pair-bucket selection costs no device round trip)."""
        m = self.embedder
        ids, mask = m.tokenizer(texts, max_length=max_length or m.max_length)
        from pathway_tpu.models.tokenizer import pad_to_buckets

        q_max = int(mask.sum(axis=1).max()) if mask.size else 2
        ids, mask = pad_to_buckets(ids, mask, row_lo=1)
        return jnp.asarray(ids), jnp.asarray(mask), q_max

    def _pair_bucket(self, q_max: int) -> int:
        """Static pair width for this query batch: the pow2 bucket of the
        true worst-case pair length ``q_len + max_doc_len + 1`` (capped at
        the configured ``pair_seq``, which also stays the kill-switch
        width when ``PATHWAY_TPU_PAIR_BUCKETS=0``). Executables cache per
        bucket, so short corpora stop paying ``pair_seq``-wide attention."""
        if not pathway_config.pair_buckets:
            return self.pair_seq
        need = q_max + min(self._max_doc_len, self.doc_seq) + 1
        return min(self.pair_seq, next_pow2(need, 16))

    def _cascade_plan(self, k: int):
        """(depth, survivors, seed_weight) for a cascade over k candidates,
        env-overridable with auto defaults: half the encoder depth for the
        cheap pass, half the candidates surviving (floor 8)."""
        c = pathway_config
        layers = self.reranker.cfg.layers
        depth = c.rerank_cascade_depth or max(1, layers // 2)
        depth = max(1, min(depth, layers))
        keep = c.rerank_cascade_survivors or max(8, k // 2)
        keep = max(1, min(keep, k))
        return depth, keep, c.rerank_seed_weight

    def _record_cascade(self, qb: int, k: int, keep: int, depth: int,
                        pair_seq: int) -> None:
        r_cfg = self.reranker.cfg
        record_cascade(
            "cheap", qb * k, _encoder_flops(r_cfg, pair_seq, depth, qb * k)
        )
        record_cascade(
            "full", qb * keep,
            _encoder_flops(r_cfg, pair_seq, r_cfg.layers, qb * keep),
        )

    # ------------------------------------------- late-interaction bank
    def _late_alloc(self) -> None:
        """Allocate the bank (first use) or grow it alongside the index's
        capacity doublings, keeping slot alignment with ``_doc_tokens``."""
        if self._bank_q is None:
            self._late_dim = int(pathway_config.late_dim)
            self._late_proj = late_projection(
                self.embedder.cfg.hidden, self._late_dim
            )
            cap = self.index.capacity
            self._bank_q = jnp.zeros(
                (cap, self.doc_seq, self._late_dim), dtype=jnp.int8
            )
            self._bank_scale = jnp.zeros(
                (cap, self.doc_seq, 1), dtype=jnp.float32
            )
            self._bank_valid = np.zeros((cap,), dtype=bool)
            return
        if self.index.capacity != self._bank_q.shape[0]:
            grow = self.index.capacity - self._bank_q.shape[0]
            self._bank_q = jnp.pad(self._bank_q, ((0, grow), (0, 0), (0, 0)))
            self._bank_scale = jnp.pad(
                self._bank_scale, ((0, grow), (0, 0), (0, 0))
            )
            self._bank_valid = np.pad(self._bank_valid, (0, grow))

    def _late_bank_rows(self, ids: np.ndarray, lens: np.ndarray):
        """Bank rows for a batch of already-tokenized docs: ONE fused
        encode->project->quant dispatch. Rows pad to the pow2 bucket so
        ingest batch sizes reuse executables; the doc-token width stays
        exactly ``doc_seq`` (the bank's storage width)."""
        rows = ids.shape[0]
        rb = next_pow2(max(rows, 1), 1)
        ids_p = np.zeros((rb, self.doc_seq), dtype=np.int32)
        ids_p[:rows] = ids
        # empty docs keep one live (PAD) position: an all-masked row
        # would NaN the encoder softmax; d_len=0 hides it from MaxSim
        live = np.maximum(lens, 1)
        mask_p = np.zeros((rb, self.doc_seq), dtype=np.int32)
        mask_p[:rows] = (
            np.arange(self.doc_seq)[None, :] < live[:, None]
        ).astype(np.int32)
        record_device_dispatch("late_bank_build")
        bq, bs = doc_token_states(
            self.embedder.params, jnp.asarray(ids_p), jnp.asarray(mask_p),
            self._late_proj, self.embedder.cfg,
        )
        return bq[:rows], bs[:rows]

    def _ensure_late_bank(self) -> None:
        """Backfill bank rows for live slots ingested while the flag was
        off (or before this pipeline ran late-interaction at all), in
        bounded batches — each one fused dispatch. After this every live
        slot's bank row is current."""
        self._late_alloc()
        n = self.index.n
        missing = np.flatnonzero(~self._bank_valid[:n])
        if not missing.size:
            return
        for i in range(0, missing.size, 256):
            sl = missing[i:i + 256]
            dev_sl = jnp.asarray(sl)
            ids = np.asarray(jnp.take(self._doc_tokens, dev_sl, axis=0))
            lens = np.asarray(jnp.take(self._doc_lens, dev_sl))
            bq, bs = self._late_bank_rows(ids, lens)
            self._bank_q = self._bank_q.at[dev_sl].set(bq)
            self._bank_scale = self._bank_scale.at[dev_sl].set(bs)
            self._bank_valid[sl] = True
        self._record_late_bank()

    def _record_late_bank(self) -> None:
        """Record the bank's LIVE footprint on the HBM ledger, per device
        (``late_bank`` component). Live rows, not allocated capacity, so
        retraction visibly lowers the gauge — the same observable the
        retraction/compaction tests pin."""
        from pathway_tpu.engine.probes import record_hbm
        from pathway_tpu.models.decoder import _device_bytes

        cap = self._bank_q.shape[0]
        live = int(self._bank_valid.sum())
        per_dev: dict[str, int] = {}
        for arr in (self._bank_q, self._bank_scale):
            for dev, nb in _device_bytes(arr).items():
                per_dev[dev] = per_dev.get(dev, 0) + nb
        frac = (live / cap) if cap else 0.0
        for dev, nb in per_dev.items():
            record_hbm("late_bank", int(nb * frac), device=dev)

    def _maxsim_args(self, arrays):
        """Interleave the bank arrays into the shared ``_rerank_args``
        bundle, backfilling any stale slots first."""
        self._ensure_late_bank()
        return arrays[:7] + (
            self._bank_q, self._bank_scale, self._late_proj,
        ) + arrays[7:]

    def _record_maxsim(self, qb: int, k: int, keep: int,
                       pair_seq: int) -> None:
        """Cascade-ledger attribution for the MaxSim stage: the per-pair
        similarity gemm plus the per-query projection, and the full-depth
        pass over survivors — so ``cascade_stats()`` can report the
        pair-FLOPs collapse vs the encoder cheap stage."""
        r_cfg = self.reranker.cfg
        q_seq = min(self.embedder.max_length, self._rerank_q_budget)
        record_cascade(
            "maxsim", qb * k,
            maxsim_flops(q_seq, self.doc_seq, self._late_dim, qb * k)
            + projection_flops(
                q_seq, self.embedder.cfg.hidden, self._late_dim, qb
            ),
        )
        record_cascade(
            "full", qb * keep,
            _encoder_flops(r_cfg, pair_seq, r_cfg.layers, qb * keep),
        )

    def remove(self, keys: list) -> None:
        """Remove documents, keeping the token store aligned with the
        index's swap-with-last slot moves. Use THIS, not ``index.remove``,
        for pipelines with a reranker — the raw index call would leave
        another document's tokens in the vacated slot."""
        for key in keys:
            slot = self.index._slot_of.get(key)
            if slot is None:
                continue
            last = self.index.n - 1
            if slot != last:
                self._doc_tokens = self._doc_tokens.at[slot].set(
                    self._doc_tokens[last]
                )
                self._doc_lens = self._doc_lens.at[slot].set(
                    self._doc_lens[last]
                )
            self._doc_lens = self._doc_lens.at[last].set(0)
            if self._bank_q is not None:
                # bank rows compact with the same swap-with-last move;
                # the vacated tail slot loses validity (and its bytes
                # leave the late_bank gauge below)
                if slot != last:
                    self._bank_q = self._bank_q.at[slot].set(
                        self._bank_q[last]
                    )
                    self._bank_scale = self._bank_scale.at[slot].set(
                        self._bank_scale[last]
                    )
                    self._bank_valid[slot] = self._bank_valid[last]
                self._bank_valid[last] = False
            self.index.remove([key])
            self._text_by_key.pop(key, None)
        if self._bank_q is not None:
            self._record_late_bank()
        if self.sharded_index is not None:
            self.sharded_index.remove(list(keys))

    def retrieve_device(self, texts: list[str], k: int):
        ids, mask, _ = self._tokenize_queries(texts)
        k_eff = min(k, self.index.capacity)
        record_device_dispatch("fused_retrieve")
        return _fused_retrieve(
            self.embedder.params, ids, mask, self.index._corpus,
            self.index._valid, self.embedder.cfg, k_eff, self.metric,
            f32_scores=self.index.f32_scores,
        )

    def retrieve(self, texts: list[str], k: int):
        """[(key, score)] per query — ONE dispatch round trip (under a
        serving mesh: one sharded-IVF dispatch, every chip scanning its
        shard, plus the query-embed dispatch)."""
        if self.sharded_index is not None:
            ids, mask, _ = self._tokenize_queries(texts)
            record_device_dispatch("sharded_ivf_search")
            emb = np.asarray(
                embed_fn(self.embedder.params, ids, mask, self.embedder.cfg),
                np.float32,
            )[: len(texts)]
            return self.sharded_index.search(emb, k)
        from pathway_tpu.engine.probes import record_retrieval_backend

        scores, idx = jax.device_get(self.retrieve_device(texts, k))
        record_retrieval_backend("dense", len(texts))
        return self.index.resolve(scores, idx, len(texts), k)

    def _rerank_args(self, texts: list[str], k: int):
        """Tokenize rerank queries and bundle the (device args, statics)
        shared by the single/batch/cascade rerank kernels."""
        if self.reranker is None:
            raise ValueError("construct FusedRAGPipeline with a reranker")
        ids, mask, q_max = self._tokenize_queries(
            texts,
            max_length=min(self.embedder.max_length, self._rerank_q_budget),
        )
        k_eff = min(k, self.index.capacity)
        pair_seq = self._pair_bucket(q_max)
        arrays = (
            self.embedder.params, ids, mask, self.index._corpus,
            self.index._valid, self._doc_tokens, self._doc_lens,
            self.reranker.params, self.reranker.head,
        )
        return arrays, k_eff, pair_seq

    def retrieve_rerank_device(self, text: str, k: int):
        arrays, k_eff, pair_seq = self._rerank_args([text], k)
        if pathway_config.rerank_cascade:
            depth, keep, seed_w = self._cascade_plan(k_eff)
            if pathway_config.late_interaction:
                record_device_dispatch("fused_rerank_maxsim")
                args = self._maxsim_args(arrays)
                self._record_maxsim(1, k_eff, keep, pair_seq)
                scores, idx, r_scores, order = _fused_retrieve_maxsim_cascade(
                    *args, self.embedder.cfg, self.reranker.cfg,
                    k_eff, self.metric, pair_seq, keep, seed_w,
                )
                return scores[0], idx[0], r_scores[0], order[0]
            record_device_dispatch("fused_rerank_cascade")
            self._record_cascade(1, k_eff, keep, depth, pair_seq)
            scores, idx, r_scores, order = _fused_retrieve_rerank_cascade(
                *arrays, self.embedder.cfg, self.reranker.cfg,
                k_eff, self.metric, pair_seq, depth, keep, seed_w,
            )
            return scores[0], idx[0], r_scores[0], order[0]
        record_device_dispatch("fused_retrieve_rerank")
        return _fused_retrieve_rerank(
            *arrays, self.embedder.cfg, self.reranker.cfg,
            k_eff, self.metric, pair_seq,
        )

    def retrieve_rerank(self, text: str, k: int):
        """[(key, rerank_score)] best-first — ONE dispatch round trip for
        embed + search + gather + cross-encode (cascaded or not)."""
        scores, idx, r_scores, order = jax.device_get(
            self.retrieve_rerank_device(text, k)
        )
        row = self._resolve_rerank_row(scores, idx, r_scores, order)
        return self._llm_rerank_rows([text], [row])[0]

    def _llm_rerank_rows(self, texts: list[str], rows: list[list]):
        """Optional listwise LLM final stage over resolved rerank rows.

        Each row is ``[(key, score)]`` best-first from the cross-encoder;
        the LLM permutes the ORDER while each doc keeps its cross-encoder
        score (RankLLM semantics — the listwise pass ranks, it does not
        re-score). No-op unless a reranker is attached AND the flag is on.
        """
        if self.llm_reranker is None or not pathway_config.llm_rerank:
            return rows
        docs_lists = [
            [self._text_by_key.get(key, "") for key, _ in row] for row in rows
        ]
        record_cascade("llm_rerank", sum(len(r) for r in rows))
        perms = self.llm_reranker.rerank_batch(list(texts), docs_lists)
        return [[row[j] for j in perm] for row, perm in zip(rows, perms)]

    def _resolve_rerank_row(self, scores, idx, r_scores, order):
        out = []
        for j in order:
            if scores[j] <= _NEG_INF / 2:
                continue
            slot = int(idx[j])
            if slot < len(self.index._keys):
                out.append((self.index._keys[slot], float(r_scores[j])))
        return out

    def retrieve_rerank_batch_device(self, texts: list[str], k: int):
        """Batched fused retrieve+rerank: the whole query batch costs ONE
        dispatch (the micro-batching server's tick primitive). Returns
        (knn_scores, idx, rerank_scores, order), each (Qb', k) with Qb'
        the pow2 row bucket — callers slice ``[:len(texts)]``."""
        arrays, k_eff, pair_seq = self._rerank_args(texts, k)
        if pathway_config.rerank_cascade:
            depth, keep, seed_w = self._cascade_plan(k_eff)
            if pathway_config.late_interaction:
                record_device_dispatch("fused_rerank_maxsim")
                args = self._maxsim_args(arrays)
                self._record_maxsim(len(texts), k_eff, keep, pair_seq)
                return _fused_retrieve_maxsim_cascade(
                    *args, self.embedder.cfg, self.reranker.cfg,
                    k_eff, self.metric, pair_seq, keep, seed_w,
                )
            record_device_dispatch("fused_rerank_cascade")
            self._record_cascade(len(texts), k_eff, keep, depth, pair_seq)
            return _fused_retrieve_rerank_cascade(
                *arrays, self.embedder.cfg, self.reranker.cfg,
                k_eff, self.metric, pair_seq, depth, keep, seed_w,
            )
        record_device_dispatch("fused_retrieve_rerank")
        return _fused_retrieve_rerank_batch(
            *arrays, self.embedder.cfg, self.reranker.cfg,
            k_eff, self.metric, pair_seq,
        )

    def retrieve_rerank_batch(self, texts: list[str], k: int):
        """Per-query [(key, rerank_score)] best-first lists for a batch of
        queries — still one dispatch round trip for the whole batch."""
        scores, idx, r_scores, order = jax.device_get(
            self.retrieve_rerank_batch_device(texts, k)
        )
        rows = [
            self._resolve_rerank_row(scores[i], idx[i], r_scores[i], order[i])
            for i in range(len(texts))
        ]
        return self._llm_rerank_rows(texts, rows)
