"""Fused RAG query pipeline — ONE dispatch from query text to results.

The reference answers a query in stages (embed the query, search the
index, gather documents, rerank — ``xpacks/llm/vector_store.py:440``,
``question_answering.py``), each a separate host round trip. On a remote /
relayed TPU every stage costs a full dispatch RTT, so the stages dominate
end-to-end latency. TPU-first redesign: keep everything the query touches
RESIDENT in HBM — the embedding corpus (the brute-force index matrix) AND
the documents' token ids — and compile the whole pipeline into a single
executable:

    tokenize (host, C++)  →  [ encode+pool+normalize  →  gemm + top-k  →
    gather doc tokens  →  assemble [CLS] q [SEP] d [SEP] pairs  →
    cross-encoder  ]  →  one fetch

The bracketed section is one jit; a query costs exactly one round trip
whether it retrieves or retrieves-and-reranks.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from pathway_tpu.models.embedder import embed_fn
from pathway_tpu.models.tokenizer import PAD_ID, SEP_ID
from pathway_tpu.models.transformer import TransformerConfig, encode
from pathway_tpu.ops.knn import BruteForceKnnIndex, knn_scores, topk_scores

_NEG_INF = -1e30


@functools.partial(
    jax.jit, static_argnames=("cfg", "k", "metric")
)
def _fused_retrieve(params, q_ids, q_mask, corpus, valid,
                    cfg: TransformerConfig, k: int, metric: str):
    """Query encode + pool + normalise + corpus gemm + top-k, one dispatch.
    q_ids/q_mask: (Qb, S). Returns (scores (Qb, k), idx (Qb, k))."""
    emb = embed_fn(params, q_ids, q_mask, cfg)  # (Qb, H) unit vectors
    return topk_scores(knn_scores(corpus, valid, emb, metric), k)


def _assemble_pairs(q_ids_row, q_len, doc_tokens, doc_lens, pair_seq: int):
    """Build (k, pair_seq) cross-encoder inputs on device:
    ``[CLS] q [SEP] d [SEP]`` with masks and BERT segment ids. ``q_ids_row``
    is already ``[CLS] q [SEP]`` of true length ``q_len``; ``doc_tokens``
    (k, dseq) carry bare doc tokens of ``doc_lens`` each."""
    k, dseq = doc_tokens.shape
    j = jnp.arange(pair_seq)[None, :]                      # (1, P)
    q_pad = jnp.pad(q_ids_row, (0, max(pair_seq - q_ids_row.shape[0], 0)))
    q_part = q_pad[:pair_seq][None, :]                     # (1, P)
    dpos = jnp.clip(j - q_len, 0, dseq - 1)                # (1, P)
    d_vals = jnp.take_along_axis(
        doc_tokens, jnp.broadcast_to(dpos, (k, pair_seq)), axis=1
    )                                                      # (k, P)
    end = q_len + doc_lens[:, None]                        # (k, 1) SEP slot
    pair = jnp.where(
        j < q_len,
        jnp.broadcast_to(q_part, (k, pair_seq)),
        jnp.where(
            j < end, d_vals, jnp.where(j == end, SEP_ID, PAD_ID)
        ),
    )
    mask = (j <= end).astype(jnp.int32)
    ttype = ((j >= q_len) & (j <= end)).astype(jnp.int32)
    return pair.astype(jnp.int32), mask, ttype


@functools.partial(
    jax.jit,
    static_argnames=("e_cfg", "r_cfg", "k", "metric", "pair_seq"),
)
def _fused_retrieve_rerank(e_params, q_ids, q_mask, corpus, valid,
                           doc_tokens, doc_lens, r_params, r_head,
                           e_cfg: TransformerConfig,
                           r_cfg: TransformerConfig,
                           k: int, metric: str, pair_seq: int):
    """One dispatch: embed query -> top-k over the corpus -> gather the
    hit documents' token ids -> cross-encode (query, doc) pairs -> rerank.
    Single query (q_ids (1, S)). Returns (knn_scores (k,), idx (k,),
    rerank_scores (k,), order (k,))."""
    emb = embed_fn(e_params, q_ids, q_mask, e_cfg)           # (1, H)
    scores, idx = topk_scores(
        knn_scores(corpus, valid, emb, metric), k
    )                                                        # (1, k)
    idx0 = idx[0]
    d_tok = jnp.take(doc_tokens, idx0, axis=0)               # (k, dseq)
    d_len = jnp.take(doc_lens, idx0)                         # (k,)
    q_len = jnp.sum(q_mask[0]).astype(jnp.int32)
    pair, mask, ttype = _assemble_pairs(
        q_ids[0], q_len, d_tok, d_len, pair_seq
    )
    hidden = encode(r_params, pair, mask, r_cfg, ttype)
    cls = hidden[:, 0, :]
    pooled = jnp.tanh(
        cls @ r_params["pooler"]["w"].astype(jnp.float32)
        + r_params["pooler"]["b"].astype(jnp.float32)
    )
    r_scores = (pooled @ r_head["w"] + r_head["b"])[:, 0]    # (k,)
    # hits beyond the live corpus (padded capacity) must sort last
    r_scores = jnp.where(scores[0] <= _NEG_INF / 2, _NEG_INF, r_scores)
    order = jnp.argsort(-r_scores)
    return scores[0], idx0, r_scores, order


class FusedRAGPipeline:
    """HBM-resident retrieval (+ optional rerank) with one-dispatch queries.

    ``add(keys, texts)`` embeds documents into the brute-force corpus AND
    stores their token ids on device; ``retrieve``/``retrieve_rerank`` then
    cost exactly one round trip. ``*_device`` variants return handles so a
    stream of queries can pipeline dispatches and drain once."""

    def __init__(self, embedder, reranker=None, *,
                 reserved_space: int = 1024, metric: str = "cos",
                 doc_seq: int = 96, pair_seq: int = 160):
        self.embedder = embedder          # SentenceEmbedderModel
        self.reranker = reranker          # CrossEncoderModel | None
        self.metric = metric
        self.doc_seq = doc_seq
        self.pair_seq = pair_seq
        # the rerank pair is [CLS] q [SEP] d [SEP]: a query longer than
        # pair_seq - doc_seq - 1 would silently crowd the document out of
        # the cross-encoder input, so rerank queries truncate to this
        # budget (and it must leave room for a real query)
        self._rerank_q_budget = pair_seq - doc_seq - 1
        if self._rerank_q_budget < 8:
            raise ValueError(
                f"pair_seq={pair_seq} leaves only {self._rerank_q_budget} "
                f"query tokens next to doc_seq={doc_seq}; raise pair_seq "
                "or lower doc_seq"
            )
        self.index = BruteForceKnnIndex(
            dimensions=embedder.cfg.hidden,
            reserved_space=reserved_space, metric=metric,
        )
        cap = self.index.capacity
        self._doc_tokens = jnp.zeros((cap, doc_seq), dtype=jnp.int32)
        self._doc_lens = jnp.zeros((cap,), dtype=jnp.int32)

    # ------------------------------------------------------------- ingest
    def _doc_token_rows(self, texts: list[str]):
        tok = self.embedder.tokenizer
        ids = np.zeros((len(texts), self.doc_seq), dtype=np.int32)
        lens = np.zeros((len(texts),), dtype=np.int32)
        for i, t in enumerate(texts):
            seq = tok.tokenize_ids(t, self.doc_seq + 2)[1:-1]  # strip specials
            seq = seq[: self.doc_seq]
            ids[i, : len(seq)] = seq
            lens[i] = len(seq)
        return ids, lens

    def add(self, keys: list, texts: list[str]) -> None:
        if not keys:
            return
        start = self.index.n
        # fused embed+append: one dispatch from token ids to corpus rows
        # (the vectors never leave HBM; no transport cast, no separate
        # append enqueue)
        from pathway_tpu.models.embedder import embed_fn
        from pathway_tpu.models.tokenizer import pad_to_buckets

        m = self.embedder
        ids, mask = m.tokenizer(list(texts), max_length=m.max_length)
        ids, mask = pad_to_buckets(ids, mask)
        self.index.add_embed(
            keys, m.params, jnp.asarray(ids), jnp.asarray(mask), m.cfg,
            embed_fn,
        )
        if self.index.capacity != self._doc_tokens.shape[0]:
            grow = self.index.capacity - self._doc_tokens.shape[0]
            self._doc_tokens = jnp.pad(self._doc_tokens, ((0, grow), (0, 0)))
            self._doc_lens = jnp.pad(self._doc_lens, (0, grow))
        ids, lens = self._doc_token_rows(list(texts))
        self._doc_tokens = jax.lax.dynamic_update_slice(
            self._doc_tokens, jnp.asarray(ids), (start, 0)
        )
        self._doc_lens = jax.lax.dynamic_update_slice(
            self._doc_lens, jnp.asarray(lens), (start,)
        )

    # ------------------------------------------------------------ queries
    def _tokenize_queries(self, texts: list[str], max_length: int | None = None):
        m = self.embedder
        ids, mask = m.tokenizer(texts, max_length=max_length or m.max_length)
        from pathway_tpu.models.tokenizer import pad_to_buckets

        ids, mask = pad_to_buckets(ids, mask, row_lo=1)
        return jnp.asarray(ids), jnp.asarray(mask)

    def remove(self, keys: list) -> None:
        """Remove documents, keeping the token store aligned with the
        index's swap-with-last slot moves. Use THIS, not ``index.remove``,
        for pipelines with a reranker — the raw index call would leave
        another document's tokens in the vacated slot."""
        for key in keys:
            slot = self.index._slot_of.get(key)
            if slot is None:
                continue
            last = self.index.n - 1
            if slot != last:
                self._doc_tokens = self._doc_tokens.at[slot].set(
                    self._doc_tokens[last]
                )
                self._doc_lens = self._doc_lens.at[slot].set(
                    self._doc_lens[last]
                )
            self._doc_lens = self._doc_lens.at[last].set(0)
            self.index.remove([key])

    def retrieve_device(self, texts: list[str], k: int):
        ids, mask = self._tokenize_queries(texts)
        k_eff = min(k, self.index.capacity)
        return _fused_retrieve(
            self.embedder.params, ids, mask, self.index._corpus,
            self.index._valid, self.embedder.cfg, k_eff, self.metric,
        )

    def retrieve(self, texts: list[str], k: int):
        """[(key, score)] per query — ONE dispatch round trip."""
        scores, idx = jax.device_get(self.retrieve_device(texts, k))
        return self.index.resolve(scores, idx, len(texts), k)

    def retrieve_rerank_device(self, text: str, k: int):
        if self.reranker is None:
            raise ValueError("construct FusedRAGPipeline with a reranker")
        ids, mask = self._tokenize_queries(
            [text],
            max_length=min(self.embedder.max_length, self._rerank_q_budget),
        )
        k_eff = min(k, self.index.capacity)
        return _fused_retrieve_rerank(
            self.embedder.params, ids, mask, self.index._corpus,
            self.index._valid, self._doc_tokens, self._doc_lens,
            self.reranker.params, self.reranker.head,
            self.embedder.cfg, self.reranker.cfg,
            k_eff, self.metric, self.pair_seq,
        )

    def retrieve_rerank(self, text: str, k: int):
        """[(key, rerank_score)] best-first — ONE dispatch round trip for
        embed + search + gather + cross-encode."""
        scores, idx, r_scores, order = jax.device_get(
            self.retrieve_rerank_device(text, k)
        )
        out = []
        for j in order:
            if scores[j] <= _NEG_INF / 2:
                continue
            slot = int(idx[j])
            if slot < len(self.index._keys):
                out.append((self.index._keys[slot], float(r_scores[j])))
        return out
