"""Micro-batched query serving over a :class:`FusedRAGPipeline`.

Per-query dispatch wastes the device when queries arrive concurrently:
``_fused_retrieve`` / ``_fused_retrieve_rerank_batch`` already take
``(Qb, S)`` query batches, so N requests landing in the same short window
can share ONE dispatch instead of paying N round trips. The
:class:`QueryServer` mirrors the continuous decode server in
``xpacks/llm/llms.py`` (lock + deque + wake event + daemon loop with a
failure sweep) and the ingest ``StageWorker`` contract in
``engine/async_runtime.py`` (bounded admission, blocking backpressure):

* ``submit`` enqueues a retrieve or retrieve-rerank request and returns a
  handle; ``queue_bound`` admission blocks when the server is saturated.
* the loop coalesces everything that arrived within one ``tick_ms``
  window (or up to ``max_batch``, whichever first) and issues one batched
  device dispatch per ``(kind, k)`` group — homogeneous load is exactly
  one dispatch per tick.
* results resolve back per request; ``stats()`` reports ticks, the
  batch-size histogram and coalescing rate the bench's Poisson phase
  plots.

The server is opt-in: code that never constructs one keeps today's
per-call query path byte-for-byte.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from pathway_tpu.analysis.annotations import guarded_by
from pathway_tpu.analysis.runtime import make_lock
from pathway_tpu.internals.config import pathway_config


class QueryRequest:
    """One in-flight query. ``done`` fires once ``result`` / ``error`` is
    set; timestamps are ``time.monotonic()`` for latency accounting."""

    __slots__ = (
        "kind", "text", "k", "done", "result", "error",
        "submitted_at", "finished_at", "span",
    )

    def __init__(self, kind: str, text: str, k: int):
        from pathway_tpu.engine import tracing

        self.kind = kind                # "retrieve" | "rerank"
        self.text = text
        self.k = k
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.submitted_at = time.monotonic()
        self.finished_at = 0.0
        self.span = tracing.NULL_SPAN  # replaced by QueryServer.submit

    def wait(self, timeout: float | None = None):
        if not self.done.wait(timeout):
            raise TimeoutError("query did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result

    @property
    def latency_s(self) -> float:
        return max(0.0, self.finished_at - self.submitted_at)


@guarded_by(
    _queue="_cond", _stop="_cond", failed="_cond",
    _ticks="_stats_lock", _dispatches="_stats_lock",
    _requests="_stats_lock", _batch_hist="_stats_lock",
    _restarts="_stats_lock", _group_failures="_stats_lock",
    _leaked_thread="_stats_lock",
)
class QueryServer:
    """Coalesces concurrent retrieve / retrieve-rerank requests into
    batched fused dispatches (one per ``(kind, k)`` group per tick)."""

    def __init__(self, pipeline, *, tick_ms: float | None = None,
                 max_batch: int | None = None,
                 queue_bound: int | None = None):
        cfg = pathway_config
        self._pipe = pipeline
        self.tick_s = (cfg.query_tick_ms if tick_ms is None else tick_ms) / 1e3
        self.max_batch = max_batch or cfg.query_max_batch
        self.queue_bound = queue_bound or cfg.query_queue
        self._cond = threading.Condition(make_lock("query_server.cond"))
        self._queue: deque[QueryRequest] = deque()
        self._stop = False
        self.failed: BaseException | None = None
        self._stats_lock = make_lock("query_server.stats")
        self._ticks = 0
        self._dispatches = 0
        self._requests = 0
        self._batch_hist: dict[int, int] = {}
        self._restarts = 0
        self._group_failures = 0
        self._leaked_thread = 0
        # fault-tolerance knobs, read once (kill switches): budget == 0
        # keeps the historical latch-on-first-error behavior exactly
        from pathway_tpu.engine import chaos

        self._restart_budget = int(cfg.serve_restarts)
        self._supervised = self._restart_budget > 0
        self._restarts_left = self._restart_budget
        self._chaos_tick = chaos.site("query.tick")
        # tags this server's request spans in the global trace ring
        self._trace_tag = f"query:{id(self):x}"
        self._thread = threading.Thread(
            target=self._loop, name="query-server", daemon=True
        )
        self._thread.start()

    def recent_traces(self, n: int | None = None) -> list[dict]:
        """Completed per-request spans of THIS server (oldest first),
        from the bounded global trace ring (``PATHWAY_TPU_TRACE_RING``).
        Empty under ``PATHWAY_TPU_METRICS=0``."""
        from pathway_tpu.engine import tracing

        return tracing.recent_traces(server=self._trace_tag, n=n)

    # ------------------------------------------------------------ submit
    def submit(self, text: str, k: int, *, rerank: bool = False) -> QueryRequest:
        """Enqueue a query; blocks (backpressure) while ``queue_bound``
        requests already wait. Returns a handle to ``wait()`` on."""
        kind = "rerank" if rerank else "retrieve"
        if rerank and self._pipe.reranker is None:
            raise ValueError("pipeline has no reranker")
        from pathway_tpu.engine import tracing

        req = QueryRequest(kind, text, k)
        req.span = tracing.start_span(
            "query", server=self._trace_tag, query_kind=kind, k=k,
        )
        with self._cond:
            while (
                len(self._queue) >= self.queue_bound
                and not self._stop and self.failed is None
            ):
                self._cond.wait(timeout=0.1)
            if self.failed is not None:
                raise RuntimeError("query server failed") from self.failed
            if self._stop:
                raise RuntimeError("query server is shut down")
            self._queue.append(req)
            self._cond.notify_all()
        return req

    def query(self, text: str, k: int, *, rerank: bool = False,
              timeout: float | None = 60.0):
        """Synchronous convenience: submit + wait."""
        return self.submit(text, k, rerank=rerank).wait(timeout)

    # -------------------------------------------------------------- loop
    def _drain_tick(self) -> list[QueryRequest]:
        """Block until work exists, then hold the tick window open so
        concurrent arrivals coalesce; returns up to ``max_batch``."""
        with self._cond:
            while not self._queue and not self._stop:
                self._cond.wait()
            if self._stop and not self._queue:
                return []
            deadline = self._queue[0].submitted_at + self.tick_s
            while (
                len(self._queue) < self.max_batch and not self._stop
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch = [
                self._queue.popleft()
                for _ in range(min(len(self._queue), self.max_batch))
            ]
            self._cond.notify_all()  # unblock backpressured submitters
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._drain_tick()
            if not batch:
                with self._cond:
                    stopping = self._stop
                if stopping:
                    return
                continue
            try:
                self._serve(batch)
            except BaseException as exc:  # noqa: BLE001 - sweep to callers
                now = time.monotonic()
                for req in batch:
                    req.error = exc
                    req.finished_at = now
                    req.span.finish(error=True)
                    req.done.set()
                if self._supervised and self._restarts_left > 0:
                    # supervised restart: the crashed tick's batch failed
                    # above, but queued/future requests keep being served
                    # until the budget runs out — then latch as before
                    self._restarts_left -= 1
                    from pathway_tpu.engine import probes
                    from pathway_tpu.internals.errors import (
                        get_global_error_log,
                    )

                    get_global_error_log().log(
                        f"query server tick crashed "
                        f"({type(exc).__name__}: {exc}); supervised restart"
                    )
                    probes.REGISTRY.counter_add(
                        "serve_restarts", server=self._trace_tag
                    )
                    with self._stats_lock:
                        self._restarts += 1
                    continue
                with self._cond:
                    self.failed = exc
                    self._stop = True
                    pending = list(self._queue)
                    self._queue.clear()
                    self._cond.notify_all()
                for req in pending:
                    req.error = exc
                    req.finished_at = now
                    req.span.finish(error=True)
                    req.done.set()
                return

    def _serve(self, batch: list[QueryRequest]) -> None:
        # one batched dispatch per (kind, k) group — requests for the same
        # k share candidates semantics with the per-call path, so batching
        # never changes a request's result
        groups: dict[tuple[str, int], list[QueryRequest]] = {}
        for req in batch:
            req.span.event("admit", batch=len(batch))
            groups.setdefault((req.kind, req.k), []).append(req)
        failed_groups = 0
        for (kind, k), reqs in groups.items():
            try:
                if self._chaos_tick is not None:
                    self._chaos_tick.maybe_fail()
                texts = [r.text for r in reqs]
                if kind == "rerank":
                    results = self._pipe.retrieve_rerank_batch(texts, k)
                else:
                    results = self._pipe.retrieve(texts, k)
            except BaseException as exc:  # noqa: BLE001 - group isolation
                if not self._supervised:
                    raise
                # group-scoped isolation: only THIS (kind, k) group's
                # requests fail; sibling groups in the same tick — and
                # everything queued — keep serving
                from pathway_tpu.engine import probes

                now = time.monotonic()
                for req in reqs:
                    req.error = exc
                    req.finished_at = now
                    req.span.finish(error=True)
                    req.done.set()
                probes.REGISTRY.counter_add(
                    "requests_isolated", float(len(reqs)),
                    outcome="failed",
                )
                failed_groups += 1
                continue
            now = time.monotonic()
            for req, res in zip(reqs, results):
                req.result = res
                req.finished_at = now
                req.span.event("drain", group=len(reqs))
                req.span.finish()
                req.done.set()
        with self._stats_lock:
            self._ticks += 1
            self._dispatches += len(groups)
            self._requests += len(batch)
            self._group_failures += failed_groups
            n = len(batch)
            self._batch_hist[n] = self._batch_hist.get(n, 0) + 1

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._cond:
            failed = self.failed is not None
        with self._stats_lock:
            ticks = self._ticks
            reqs = self._requests
            return {
                "ticks": ticks,
                "requests": reqs,
                "dispatches": self._dispatches,
                "batch_hist": dict(sorted(self._batch_hist.items())),
                "mean_batch": round(reqs / ticks, 3) if ticks else 0.0,
                "failed": failed,
                "restarts": self._restarts,
                "group_failures": self._group_failures,
                "leaked_thread": self._leaked_thread,
            }

    def shutdown(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            from pathway_tpu.internals.errors import get_global_error_log

            with self._stats_lock:
                self._leaked_thread += 1
            get_global_error_log().log(
                f"query server thread still alive {timeout}s after "
                f"shutdown join"
            )
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            if not req.done.is_set():
                req.error = RuntimeError("query server shut down")
                req.finished_at = time.monotonic()
                req.span.finish(error=True)
                req.done.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
