"""Helper for import errors pointing at optional extras
(reference ``python/pathway/optional_import.py``)."""

from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def optional_imports(extra: str):
    try:
        yield
    except ImportError as e:
        raise ImportError(
            f"{e}. Consider installing 'pathway_tpu[{extra}]'"
        ) from e
