"""Command-line interface — ``pathway-tpu spawn`` process launcher.

Parity with the reference CLI (``python/pathway/cli.py:53-175``): ``spawn``
launches N host processes with the ``PATHWAY_THREADS / PATHWAY_PROCESSES /
PATHWAY_FIRST_PORT / PATHWAY_PROCESS_ID / PATHWAY_RUN_ID`` env contract, and
``spawn-from-env`` re-reads the same flags from ``PATHWAY_SPAWN_ARGS``.

TPU-native difference: worker processes join through ``jax.distributed``
(coordinator at ``127.0.0.1:first_port``) instead of timely's TCP cluster
(reference ``src/engine/dataflow/config.rs:63-127``); the env names are kept
so reference deployment scripts keep working. The git-repository bootstrap
mode of the reference (``cli.py:30-66``, clones a repo into a temp venv) is
supported when GitPython is importable and gated off otherwise — this build
has zero network egress.
"""

from __future__ import annotations

import logging
import os
import shlex
import subprocess
import sys
import tempfile
import uuid
import venv
from pathlib import Path

from pathway_tpu.internals.config import environ_snapshot, pathway_config

import click

import pathway_tpu as pw


def plural(n: int, singular: str, plural_form: str) -> str:
    return f"{n} {singular if n == 1 else plural_form}"


def get_temporary_paths(temp_root: tempfile.TemporaryDirectory) -> tuple[Path, Path]:
    root = Path(temp_root.name)
    return root / "repository", root / "venv"


def checkout_repository(repository_url: str | None, branch: str | None):
    """Clone ``repository_url`` into a temp dir with a fresh venv (reference
    ``cli.py:30-50``). Requires GitPython + network; errors out cleanly
    when unavailable."""
    if repository_url is None:
        return None
    try:
        import git
    except ImportError:
        logging.error("To run the code from a Git repository please install GitPython")
        raise SystemExit(1)
    temp_root_directory = tempfile.TemporaryDirectory()
    repository_path, venv_path = get_temporary_paths(temp_root_directory)
    repository = git.Repo.clone_from(repository_url, repository_path)
    if branch is not None:
        repository.git.checkout(branch)
    venv.create(venv_path, with_pip=True)
    return temp_root_directory


def spawn_program(
    *,
    threads: int,
    processes: int,
    first_port: int,
    repository_url: str | None,
    branch: str | None,
    program: str,
    arguments: tuple[str, ...],
    env_base: dict[str, str],
) -> None:
    """Launch ``processes`` copies of ``program`` with the worker-topology env
    contract (reference ``cli.py:53-109``)."""
    temp_root_directory = checkout_repository(repository_url, branch)
    if temp_root_directory is not None:
        repository_path, venv_path = get_temporary_paths(temp_root_directory)
        requirements_path = repository_path / "requirements.txt"
        if program.startswith("python"):
            program = os.fspath(venv_path / "bin" / program)
        if requirements_path.exists():
            pip_path = venv_path / "bin" / "pip"
            handle = subprocess.run(
                [os.fspath(pip_path), "install", "-r", os.fspath(requirements_path)],
                stderr=subprocess.STDOUT,
            )
            if handle.returncode != 0:
                logging.error("Failed to install requirements")
                raise RuntimeError("Failed to install dependencies")
        os.chdir(repository_path)

    processes_str = plural(processes, "process", "processes")
    workers_str = plural(processes * threads, "total worker", "total workers")
    click.echo(f"Preparing {processes_str} ({workers_str})", err=True)
    run_id = uuid.uuid4()
    process_handles: list[subprocess.Popen] = []
    try:
        for process_id in range(processes):
            env = env_base.copy()
            env["PATHWAY_THREADS"] = str(threads)
            env["PATHWAY_PROCESSES"] = str(processes)
            env["PATHWAY_FIRST_PORT"] = str(first_port)
            env["PATHWAY_PROCESS_ID"] = str(process_id)
            env["PATHWAY_RUN_ID"] = str(run_id)
            handle = subprocess.Popen([program, *arguments], env=env)
            process_handles.append(handle)
        for handle in process_handles:
            handle.wait()
    finally:
        for handle in process_handles:
            handle.terminate()
    # non-zero (incl. signal-killed, negative returncode) in any worker is a
    # failed run — don't let a clean worker's 0 mask it via max()
    sys.exit(0 if all(h.returncode == 0 for h in process_handles) else 1)


@click.group
@click.version_option(version=pw.__version__, prog_name="pathway-tpu")
def cli() -> None:
    pass


@cli.command(
    context_settings={"allow_interspersed_args": False, "show_default": True}
)
@click.option("-t", "--threads", metavar="N", type=int, default=1,
              help="number of logical workers (chips) per process")
@click.option("-n", "--processes", metavar="N", type=int, default=1,
              help="number of host processes")
@click.option("--first-port", type=int, metavar="PORT", default=10000,
              help="coordinator / first communication port")
@click.option("--record", is_flag=True,
              help="record data in the input connectors")
@click.option("--record-path", type=str, default="record",
              help="directory in which the record is saved")
@click.option("--repository-url", type=str,
              help="github repository to spawn the program from")
@click.option("--branch", type=str, help="branch if not the default")
@click.argument("program")
@click.argument("arguments", nargs=-1)
def spawn(threads, processes, first_port, record, record_path,
          repository_url, branch, program, arguments):
    """Launch PROGRAM as a multi-process pathway-tpu run."""
    env = environ_snapshot()
    if record:
        env["PATHWAY_REPLAY_STORAGE"] = record_path
        env["PATHWAY_SNAPSHOT_ACCESS"] = "record"
    spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        repository_url=repository_url,
        branch=branch,
        program=program,
        arguments=arguments,
        env_base=env,
    )


@cli.command(
    context_settings={"allow_interspersed_args": False, "show_default": True}
)
@click.option("-t", "--threads", metavar="N", type=int, default=1,
              help="number of logical workers (chips) per process")
@click.option("-n", "--processes", metavar="N", type=int, default=1,
              help="number of host processes")
@click.option("--first-port", type=int, metavar="PORT", default=10000,
              help="coordinator / first communication port")
@click.option("--record-path", type=str, default="record",
              help="directory from which the record is replayed")
@click.option("--mode", type=click.Choice(["batch", "speedrun"]),
              default="batch", help="replay mode")
@click.option("--continue-after-replay", is_flag=True,
              help="keep processing live data after the replay finishes")
@click.option("--repository-url", type=str,
              help="github repository to spawn the program from")
@click.option("--branch", type=str, help="branch if not the default")
@click.argument("program")
@click.argument("arguments", nargs=-1)
def replay(threads, processes, first_port, record_path, mode,
           continue_after_replay, repository_url, branch, program, arguments):
    """Replay PROGRAM against a recorded input stream (reference
    ``cli.py:replay``)."""
    env = environ_snapshot()
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_SNAPSHOT_ACCESS"] = "replay"
    env["PATHWAY_PERSISTENCE_MODE"] = (
        "speedrun_replay" if mode == "speedrun" else mode
    )
    if continue_after_replay:
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        repository_url=repository_url,
        branch=branch,
        program=program,
        arguments=arguments,
        env_base=env,
    )


@cli.command(context_settings={"allow_interspersed_args": False})
@click.argument("program")
@click.argument("arguments", nargs=-1)
def spawn_from_env(program, arguments):
    """Like ``spawn`` but flags come from $PATHWAY_SPAWN_ARGS (reference
    ``cli.py`` spawn-from-env)."""
    spawn_args = pathway_config.spawn_args
    argv = [*shlex.split(spawn_args), program, *arguments]
    spawn.main(args=argv, standalone_mode=True)


@cli.command()
@click.option("--url", type=str, default=None, metavar="URL",
              help="base URL of a running server (fetches URL/v1/statistics);"
                   " omit to read this process's in-memory registry")
@click.option("--as-json", is_flag=True, help="dump the raw snapshot as JSON")
def stats(url, as_json):
    """Pretty-print the unified observability snapshot (serving counters,
    latency histograms, scheduler summary) — local registry or a remote
    ``/v1/statistics`` endpoint."""
    import json

    if url is not None:
        import urllib.request

        endpoint = url.rstrip("/") + "/v1/statistics"
        with urllib.request.urlopen(endpoint, timeout=10.0) as resp:  # noqa: S310
            snap = json.loads(resp.read().decode())
    else:
        from pathway_tpu.engine import probes
        from pathway_tpu.internals import run as run_mod

        snap = probes.unified_snapshot(getattr(run_mod, "LAST_RUN_STATS", None))

    if as_json:
        click.echo(json.dumps(snap, indent=2, default=str))
        return

    serving = snap.get("serving") or {}

    def section(title: str, rows: dict) -> None:
        if not rows:
            return
        click.echo(title)
        width = max(len(str(k)) for k in rows)
        for k, v in rows.items():
            click.echo(f"  {str(k):<{width}}  {v}")

    latency = serving.get("latency") or {}
    for name, summary in sorted(latency.items()):
        if summary:
            section(f"latency/{name} (ms)", summary)
    section("prefix", serving.get("prefix") or {})
    section("spec", serving.get("spec") or {})
    section("cascade", serving.get("cascade") or {})
    attn = serving.get("attn") or {}
    section("attn", attn if attn.get("total_bytes") else {})
    section("dispatch", serving.get("dispatch") or {})
    section("stage_seconds", serving.get("stage_seconds") or {})
    section("occupancy", serving.get("occupancy") or {})
    section("lanes", serving.get("lanes") or {})
    section("tenants", serving.get("tenants") or {})
    section("kv_parked_bytes", {
        k: v for k, v in (serving.get("kv_parked_bytes") or {}).items() if v
    })
    section("retrieval", serving.get("retrieval") or {})
    hbm = snap.get("hbm") or {}
    section("hbm_bytes", hbm.get("current_bytes") or {})
    # per-device rows (PATHWAY_TPU_MESH): one section per mesh device,
    # plus the per-device total high-water capacity planning reads
    for dev, comps in sorted((hbm.get("device_bytes") or {}).items()):
        section(f"hbm_bytes/device={dev}", comps)
    section(
        "hbm_high_water_bytes/device",
        hbm.get("per_device_high_water_bytes") or {},
    )
    sched = snap.get("scheduler") or {}
    if sched:
        section("scheduler", {
            k: sched[k]
            for k in ("current_time", "epochs_total", "uptime_s", "finished")
            if k in sched
        })
    if not any((latency, serving.get("prefix"), serving.get("spec"),
                serving.get("cascade"), attn.get("total_bytes"),
                serving.get("dispatch"),
                serving.get("stage_seconds"), serving.get("occupancy"),
                hbm.get("current_bytes"), sched)):
        click.echo("no metrics recorded yet")


@cli.command()
@click.option("--url", type=str, default=None, metavar="URL",
              help="base URL of a running server (fetches URL/v1/statistics);"
                   " omit to watch this process's in-memory registry")
@click.option("--interval", type=float, default=2.0, show_default=True,
              help="seconds between evaluations")
@click.option("--iterations", type=int, default=0,
              help="stop after N evaluations (0 = run until interrupted)")
@click.option("--fail-on-alert", is_flag=True,
              help="exit nonzero if any SLO alert is firing at the end")
def watch(url, interval, iterations, fail_on_alert):
    """Live SLO watchdog view: evaluates the configured
    ``PATHWAY_TPU_SLO_*`` objectives (or reads a remote server's
    ``/v1/statistics`` slo section) every ``--interval`` seconds and
    prints per-objective burn rates and alert state."""
    import json
    import time as time_mod

    def one_pass() -> tuple[dict, dict]:
        if url is not None:
            import urllib.request

            endpoint = url.rstrip("/") + "/v1/statistics"
            with urllib.request.urlopen(endpoint, timeout=10.0) as resp:  # noqa: S310
                snap = json.loads(resp.read().decode())
            return snap.get("slo") or {}, snap.get("serving") or {}
        from pathway_tpu.engine import probes
        from pathway_tpu.engine import slo as slo_mod

        wd = slo_mod.get_watchdog()
        state = wd.tick() if wd.objectives else wd.state()
        return state, probes.serving_snapshot()

    n = 0
    state: dict = {}
    try:
        while True:
            state, serving = one_pass()
            n += 1
            objectives = state.get("objectives") or {}
            if not objectives:
                click.echo(
                    "no SLO objectives configured "
                    "(set PATHWAY_TPU_SLO_* thresholds)"
                )
            else:
                stamp = time_mod.strftime("%H:%M:%S")
                alerting = state.get("alerting") or []
                click.echo(
                    f"[{stamp}] slo: "
                    + ("ALERT " + ",".join(alerting) if alerting else "ok")
                )
                for name, o in sorted(objectives.items()):
                    value = o.get("value")
                    vtxt = (
                        f"{value:.3f}{o.get('unit', '')}"
                        if isinstance(value, (int, float)) else "-"
                    )
                    mark = "!" if o.get("alert") else " "
                    click.echo(
                        f" {mark} {name:<16} value={vtxt:<12} "
                        f"target {o['kind']} {o['threshold']} "
                        f"burn fast={o['burn_fast']:.2f} "
                        f"slow={o['burn_slow']:.2f} "
                        f"breaches={o['breaches']}"
                    )
            lanes = serving.get("lanes") or {}
            tenants = serving.get("tenants") or {}
            if lanes:
                click.echo(
                    "   lanes: " + " ".join(
                        f"{k}={v:.0f}" for k, v in sorted(lanes.items())
                    )
                )
            if tenants:
                click.echo(
                    "   tenants queued: " + " ".join(
                        f"{k}={v:.0f}" for k, v in sorted(tenants.items())
                    )
                )
            if iterations and n >= iterations:
                break
            time_mod.sleep(max(interval, 0.05))
    except KeyboardInterrupt:
        pass
    if fail_on_alert and state.get("alerting"):
        raise SystemExit(1)


@cli.command()
@click.argument("profile")
@click.option("--out", type=str, default=None, metavar="PATH",
              help="write the winning tuned-config JSON here "
                   "[default: tuned-<profile>.json]")
@click.option("--seed", type=int, default=None,
              help="search seed [default: PATHWAY_TPU_TUNE_SEED]")
@click.option("--trials", type=int, default=None,
              help="cap the candidate pool (baseline + N-1 candidates) "
                   "[default: PATHWAY_TPU_TUNE_TRIALS; 0 = full ladder]")
@click.option("--scale", type=float, default=1.0, show_default=True,
              help="trace-scale multiplier for the first halving round")
@click.option("--rounds", type=int, default=3, show_default=True,
              help="successive-halving rounds")
@click.option("--smoke", is_flag=True,
              help="seconds-scale CI invocation: 2 trials, 1 round, "
                   "half-scale traces")
def tune(profile, out, seed, trials, scale, rounds, smoke):
    """Search the tunable flag surface for a workload PROFILE, validate
    survivors under the SLO watchdog + a chaos drill, and persist the
    winner as a tuned-config JSON for ``PATHWAY_TPU_TUNED_CONFIG``.

    Exits nonzero when validation rejects every candidate (the current
    defaults stay in force)."""
    import json

    from pathway_tpu.tuning import (
        Autotuner,
        PROFILES,
        TuneError,
        save_artifact,
        to_artifact,
    )

    if profile not in PROFILES:
        click.echo(
            f"unknown profile {profile!r}; available: {sorted(PROFILES)}",
            err=True,
        )
        raise SystemExit(2)
    if smoke:
        trials = 2 if trials is None else trials
        rounds = min(rounds, 1)
        scale = min(scale, 0.5)
    tuner = Autotuner(
        profile, seed=seed, max_trials=trials,
        base_scale=scale, rounds=rounds,
    )
    try:
        result = tuner.run()
    except TuneError as exc:
        click.echo(f"tune failed: {exc}", err=True)
        raise SystemExit(3) from exc
    path = out or f"tuned-{profile}.json"
    save_artifact(result, path)
    art = to_artifact(result)
    click.echo(json.dumps(
        {
            "profile": art["profile"],
            "headline": art["headline"],
            "direction": art["direction"],
            "flags": art["flags"],
            "score": art["score"],
            "baseline_score": art["baseline_score"],
            "trials": len(result.trials),
            "rejected": len(result.rejected),
            "artifact": path,
        },
        indent=2, sort_keys=True,
    ))
    click.echo(f"export PATHWAY_TPU_TUNED_CONFIG={path}", err=True)


@cli.group()
def fleet() -> None:
    """Replicated serving fleet: spawn replicas behind the
    prefix-affinity router, or inspect a running fleet."""


@fleet.command("serve", context_settings={
    "allow_interspersed_args": False, "show_default": True,
})
@click.option("-n", "--replicas", metavar="N", type=int, default=None,
              help="initial replica count "
                   "[default: PATHWAY_TPU_FLEET_REPLICAS]")
@click.option("--host", type=str, default="127.0.0.1",
              help="router bind host")
@click.option("--port", type=int, default=0,
              help="router bind port (0 = ephemeral)")
@click.option("--health-interval", type=float, default=None, metavar="S",
              help="seconds between supervisor ticks "
                   "[default: PATHWAY_TPU_FLEET_HEALTH_MS / 1000]")
@click.option("--boot-grace", type=float, default=120.0, metavar="S",
              help="seconds a never-yet-ready replica may spend booting "
                   "(jax import + first jit) before failed health probes "
                   "count toward draining it")
@click.argument("program")
@click.argument("arguments", nargs=-1)
def fleet_serve(replicas, host, port, health_interval, boot_grace,
                program, arguments):
    """Run PROGRAM as N supervised replicas behind the affinity router.

    Each replica is spawned with the single-process env contract
    (``PATHWAY_PROCESSES=1``, its own ``PATHWAY_FIRST_PORT``) and must
    start a REST server on that port — the router health-checks
    ``/healthz`` + ``/readyz``, forwards ``/v1/pw_ai_answer`` and
    ``/v1/retrieve`` with prefix affinity, and the supervisor drains,
    respawns and autoscales off the per-replica SLO burn signal.

    Requires ``PATHWAY_TPU_FLEET=1`` (the kill switch keeps the
    single-server path byte-identical when off)."""
    import time as time_mod
    import uuid as uuid_mod

    from pathway_tpu import serving

    if not serving.fleet_enabled():
        click.echo("PATHWAY_TPU_FLEET=0: fleet serving is switched off "
                   "(single-server path unchanged)", err=True)
        raise SystemExit(2)

    run_id = str(uuid_mod.uuid4())
    next_index = [0]

    def factory(replica_id: str):
        from pathway_tpu.serving.replica import (
            HttpReplica, free_port, spawn_replica_process,
        )

        idx = next_index[0]
        next_index[0] += 1
        rport = free_port(host)
        proc = spawn_replica_process(
            [program, *arguments, "--port", str(rport)],
            replica_index=idx, port=rport, run_id=run_id,
        )
        return HttpReplica(replica_id, f"http://{host}:{rport}", proc=proc)

    manager = serving.build_fleet(
        factory, replicas=replicas, health_interval_s=health_interval,
        boot_grace_s=boot_grace,
    )
    router_srv = serving.RouterServer(
        manager.router, manager=manager, host=host, port=port,
    ).start()
    manager.run_in_thread()
    click.echo(
        f"fleet router on http://{host}:{router_srv.port} "
        f"({len(manager.router)} replicas, run {run_id})", err=True,
    )
    try:
        while True:
            time_mod.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        router_srv.stop()
        manager.shutdown()


@fleet.command("stats")
@click.option("--url", type=str, required=True, metavar="URL",
              help="base URL of a running fleet router "
                   "(fetches URL/v1/fleet)")
@click.option("--as-json", is_flag=True, help="dump the raw state as JSON")
def fleet_stats(url, as_json):
    """One-shot fleet state: members, ring, burn, respawns, events."""
    import json
    import urllib.request

    endpoint = url.rstrip("/") + "/v1/fleet"
    with urllib.request.urlopen(endpoint, timeout=10.0) as resp:  # noqa: S310
        state = json.loads(resp.read().decode())
    if as_json:
        click.echo(json.dumps(state, indent=2, default=str))
        return
    click.echo(
        f"fleet size {state.get('size')} "
        f"(min {state.get('min')} / max {state.get('max')}), "
        f"burn {state.get('burn', 0.0):.2f}, "
        f"respawns {state.get('respawns', 0)}"
    )
    for rid, info in sorted((state.get("replicas") or {}).items()):
        click.echo(
            f"  {rid:<14} kind={info.get('kind', '?'):<7} "
            f"fails={info.get('consecutive_failures', 0)}"
        )
    events = state.get("events") or []
    if events:
        click.echo("recent events:")
        for kind, rid in events[-10:]:
            click.echo(f"  {kind} {rid if rid else ''}")


@fleet.command("watch")
@click.option("--url", type=str, required=True, metavar="URL",
              help="base URL of a running fleet router")
@click.option("--interval", type=float, default=2.0, show_default=True,
              help="seconds between polls")
@click.option("--iterations", type=int, default=0,
              help="stop after N polls (0 = run until interrupted)")
def fleet_watch(url, interval, iterations):
    """Poll a fleet router's ``/v1/fleet`` and print size/burn lines."""
    import json
    import time as time_mod
    import urllib.request

    endpoint = url.rstrip("/") + "/v1/fleet"
    n = 0
    try:
        while True:
            with urllib.request.urlopen(endpoint, timeout=10.0) as resp:  # noqa: S310
                state = json.loads(resp.read().decode())
            n += 1
            stamp = time_mod.strftime("%H:%M:%S")
            click.echo(
                f"[{stamp}] size={state.get('size')} "
                f"burn={state.get('burn', 0.0):.2f} "
                f"respawns={state.get('respawns', 0)} "
                f"members={','.join(state.get('ring_members') or [])}"
            )
            if iterations and n >= iterations:
                break
            time_mod.sleep(max(interval, 0.05))
    except KeyboardInterrupt:
        pass


@cli.group()
def airbyte() -> None:
    """Airbyte connector scaffolding (reference ``cli.py:airbyte``)."""


@airbyte.command("create-source")
@click.argument("connection")
@click.option(
    "--image",
    default="airbyte/source-faker:0.1.4",
    help="any public Docker Airbyte source image",
)
def create_source(connection, image):
    """Write a starter YAML connection config for an Airbyte source.
    Running the source itself needs docker + network (gated here); the
    scaffold is generated locally."""
    import pathlib

    path = pathlib.Path(connection)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        "source:\n"
        f"  docker_image: {image}\n"
        "  config:\n"
        "    # fill in source-specific configuration here\n"
        "streams: []\n"
    )
    click.echo(
        f"Connection `{path.stem}` with source `{image}` created successfully"
    )


def main() -> None:
    cli.main()


if __name__ == "__main__":
    main()
