"""``pw.persistence`` — checkpoint/resume configuration.

Parity with reference ``python/pathway/persistence/__init__.py`` (Backend
filesystem/s3/azure/mock, Config with snapshot_interval_ms and
persistence_mode). The engine-side snapshotting (input snapshot log, replay,
metadata frontier) lives in :mod:`pathway_tpu.persistence.engine_store`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any


class Backend:
    def __init__(self, kind: str, path: str | None = None, **kwargs):
        self.kind = kind
        self.path = path
        self.options = kwargs

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "Backend":
        return cls("filesystem", os.fspath(path))

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "Backend":
        return cls("s3", root_path, bucket_settings=bucket_settings)

    @classmethod
    def azure(cls, root_path: str, account: Any = None, **kw) -> "Backend":
        return cls("azure", root_path, account=account, **kw)

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        return cls("mock", None, events=events)


@dataclass
class Config:
    backend: Backend | None = None
    snapshot_interval_ms: int = 0
    persistence_mode: str = "persisting"
    snapshot_access: str | None = None
    continue_after_replay: bool = True

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)


_persistent_sources: dict[str, Any] = {}


def register_persistent_source(persistent_id: str, connector: Any) -> None:
    _persistent_sources[persistent_id] = connector


def get_persistent_sources() -> dict[str, Any]:
    return dict(_persistent_sources)
