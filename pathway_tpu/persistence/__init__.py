"""``pw.persistence`` — checkpoint/resume configuration.

Parity with reference ``python/pathway/persistence/__init__.py`` (Backend
filesystem/s3/azure/mock, Config with snapshot_interval_ms and
persistence_mode). The engine-side snapshotting (input snapshot log, replay,
metadata frontier) lives in :mod:`pathway_tpu.persistence.engine_store`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any


class Backend:
    def __init__(self, kind: str, path: str | None = None, **kwargs):
        self.kind = kind
        self.path = path
        self.options = kwargs

    @classmethod
    def filesystem(cls, path: str | os.PathLike) -> "Backend":
        return cls("filesystem", os.fspath(path))

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "Backend":
        return cls("s3", root_path, bucket_settings=bucket_settings)

    @classmethod
    def azure(cls, root_path: str, account: Any = None, **kw) -> "Backend":
        return cls("azure", root_path, account=account, **kw)

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        return cls("mock", None, events=events)


@dataclass
class Config:
    backend: Backend | None = None
    snapshot_interval_ms: int = 0
    persistence_mode: str = "persisting"
    snapshot_access: str | None = None
    continue_after_replay: bool | None = None  # None = mode-based default

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)


_persistent_sources: dict[str, Any] = {}


def register_persistent_source(persistent_id: str, connector: Any) -> None:
    _persistent_sources[persistent_id] = connector
    connector.persistent_id = persistent_id


def get_persistent_sources() -> dict[str, Any]:
    return dict(_persistent_sources)


from pathway_tpu.persistence.backends import (  # noqa: E402
    AzureBlobBackend,
    FilesystemBackend,
    MemoryBackend,
    MockBackend,
    PersistenceBackend,
    S3Backend,
)
from pathway_tpu.persistence.engine_store import PersistenceManager  # noqa: E402
from pathway_tpu.persistence.snapshot import (  # noqa: E402
    SnapshotLogReader,
    SnapshotLogWriter,
)
from pathway_tpu.persistence.state import MetadataAccessor, StoredMetadata  # noqa: E402

__all__ = [
    "AzureBlobBackend",
    "Backend",
    "Config",
    "FilesystemBackend",
    "MemoryBackend",
    "MetadataAccessor",
    "MockBackend",
    "PersistenceBackend",
    "PersistenceManager",
    "S3Backend",
    "SnapshotLogReader",
    "SnapshotLogWriter",
    "StoredMetadata",
    "register_persistent_source",
    "get_persistent_sources",
]


from contextlib import contextmanager


@contextmanager
def get_persistence_engine_config(persistence_config):
    """Yield the engine-level persistence config for a run (reference
    ``persistence/__init__.py:165``); None passes through."""
    if persistence_config is None:
        yield None
        return
    yield persistence_config
