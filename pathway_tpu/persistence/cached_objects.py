"""Cached object storage — URI-keyed blobs for deterministic replay.

Re-design of the reference's ``src/persistence/cached_object_storage.rs``
(377 LoC): every object an object-store connector downloads is cached in the
persistence backend keyed by URI + version, so that

* a restarted run re-reads EXACTLY the bytes the crashed run saw (the
  upstream object may have changed in between — without the cache, replay
  would be nondeterministic);
* replay-only runs (``speedrun_replay``) never touch the upstream source.

One backend key per object holds a pickled ``{uri, version, data}`` record;
the backend's atomic put (tmp + rename for the fs backend) means a crash
mid-write loses at most that one object, which is then re-downloaded.
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Any

from pathway_tpu.persistence.backends import PersistenceBackend

_PREFIX = "objects"


def _uri_key(uri: str) -> str:
    return f"{_PREFIX}/{hashlib.sha1(uri.encode()).hexdigest()}"


class CachedObjectStorage:
    def __init__(self, backend: PersistenceBackend):
        self.backend = backend

    def put(self, uri: str, version: Any, data: bytes) -> None:
        self.backend.put_value(
            _uri_key(uri),
            pickle.dumps(
                {"uri": uri, "version": version, "data": bytes(data)},
                protocol=pickle.HIGHEST_PROTOCOL,
            ),
        )

    def _load(self, uri: str) -> dict | None:
        try:
            return pickle.loads(self.backend.get_value(_uri_key(uri)))
        except (KeyError, FileNotFoundError, OSError):
            return None

    def get(self, uri: str) -> tuple[Any, bytes] | None:
        """(version, data) or None."""
        rec = self._load(uri)
        if rec is None:
            return None
        return rec["version"], rec["data"]

    def get_version(self, uri: str, version: Any) -> bytes | None:
        """Data iff the cached version matches exactly."""
        rec = self._load(uri)
        if rec is None or rec["version"] != version:
            return None
        return rec["data"]

    def contains(self, uri: str, version: Any) -> bool:
        rec = self._load(uri)
        return rec is not None and rec["version"] == version

    def remove(self, uri: str) -> None:
        self.backend.remove_key(_uri_key(uri))

    def stored_uris(self) -> dict[str, Any]:
        """uri -> version for every cached object (used by tests/inspection;
        scans the prefix)."""
        out: dict[str, Any] = {}
        for key in self.backend.list_prefix(_PREFIX + "/"):
            try:
                rec = pickle.loads(self.backend.get_value(key))
            except (KeyError, FileNotFoundError, OSError):
                continue
            out[rec["uri"]] = rec["version"]
        return out
