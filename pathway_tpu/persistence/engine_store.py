"""Engine-side persistence runtime.

The analog of the reference's ``WorkerPersistentStorage``
(``src/persistence/tracker.rs``): owns the backend, per-source snapshot
writers, worker metadata, and — in operator-persisting mode — stateful
operator snapshots. Created by the graph runner when a persistence config is
active; connectors with a ``persistent_id`` are rewound (snapshot replay +
reader seek) before their threads start, and every commit appends to the
snapshot log.
"""

from __future__ import annotations

import pickle
from typing import Any

from pathway_tpu.persistence.backends import (
    AzureBlobBackend,
    FilesystemBackend,
    MemoryBackend,
    MockBackend,
    PersistenceBackend,
    S3Backend,
)
from pathway_tpu.persistence.snapshot import SnapshotLogReader, SnapshotLogWriter
from pathway_tpu.persistence.state import MetadataAccessor


def make_backend(backend_cfg: Any) -> PersistenceBackend:
    """Instantiate an engine backend from a ``pw.persistence.Backend``."""
    if isinstance(backend_cfg, PersistenceBackend):
        return backend_cfg
    kind = getattr(backend_cfg, "kind", None)
    if kind == "filesystem":
        return FilesystemBackend(backend_cfg.path)
    if kind == "azure":
        # gated on azure-storage-blob (or an injected container_client) —
        # raises instead of silently degrading to a local path
        opts = dict(backend_cfg.options)
        account = opts.pop("account", None)
        if isinstance(account, str):
            # a plain account name/url means the real SDK path — handing a
            # string to the client slot would crash deep into the run
            opts.setdefault("account_url", account)
        elif account is not None:
            if not hasattr(account, "upload_blob"):
                raise TypeError(
                    "Backend.azure account= must be an account URL string "
                    "or a container-client-like object with upload_blob/"
                    f"download_blob, got {type(account).__name__}"
                )
            # ``account`` doubles as the injected client (stub/test usage)
            opts.setdefault("container_client", account)
        return AzureBlobBackend(container=backend_cfg.path, **opts)
    if kind == "s3":
        opts = backend_cfg.options.get("bucket_settings") or {}
        if isinstance(opts, dict):
            return S3Backend(bucket=backend_cfg.path, **opts)
        return S3Backend(bucket=backend_cfg.path, client=opts)
    if kind == "mock":
        events = backend_cfg.options.get("events")
        if isinstance(events, (MemoryBackend, MockBackend)):
            return events
        name = backend_cfg.options.get("name") or "default"
        return MemoryBackend.shared(f"mock-{name}")
    raise ValueError(f"unknown persistence backend kind: {kind!r}")


class PersistenceManager:
    def __init__(self, config: Any, worker_id: int = 0, total_workers: int = 1):
        self.config = config
        self.mode = (getattr(config, "persistence_mode", None) or "persisting").lower()
        # reference SnapshotAccess: record = write-only, replay = read-only,
        # full/None = both (crash recovery)
        self.snapshot_access = (
            getattr(config, "snapshot_access", None) or "full"
        ).lower()
        self.backend = make_backend(config.backend)
        self.metadata = MetadataAccessor(self.backend, worker_id, total_workers)
        self.worker_id = worker_id
        self.snapshot_interval_ms = getattr(config, "snapshot_interval_ms", 0) or 0
        self._writers: dict[str, SnapshotLogWriter] = {}
        self._last_finalized: int | None = None
        self._forced_input_replay = False

    # ---------------------------------------------------------------- sources
    @property
    def do_replay(self) -> bool:
        """Whether stored snapshots are read back at startup."""
        return self.snapshot_access in ("full", "replay")

    @property
    def do_record(self) -> bool:
        """Whether new input data is appended to the snapshot log."""
        return self.snapshot_access in ("full", "record")

    @property
    def replay_inputs(self) -> bool:
        """Input-snapshot modes replay the log through the graph; operator
        persisting restores downstream state directly instead."""
        if self._forced_input_replay:
            return True
        return self.mode not in ("operator_persisting",)

    def force_input_replay(self) -> None:
        """Degrade operator-persisting to input replay for this run (some
        stateful operator had no usable snapshot)."""
        self._forced_input_replay = True

    @property
    def continue_after_replay(self) -> bool:
        explicit = getattr(self.config, "continue_after_replay", None)
        if explicit is not None:
            return explicit
        return self.mode not in ("speedrun_replay", "batch")

    def writer_for(self, persistent_id: str) -> SnapshotLogWriter:
        if persistent_id not in self._writers:
            self._writers[persistent_id] = SnapshotLogWriter(
                self.backend, persistent_id, self.worker_id
            )
        return self._writers[persistent_id]

    def rewind(self, persistent_id: str) -> tuple[list, Any]:
        """(replayed rows, stored reader offset) for a source. Chunks from a
        run that crashed before finalizing are deleted — their data is
        re-read via the returned offset, which predates them; leaving them
        would double-count once a later run raised the threshold."""
        reader = SnapshotLogReader(self.backend, persistent_id, self.worker_id)
        rows, chunk_offset, stale = reader.replay(self.metadata.threshold_time())
        for key in stale:
            self.backend.remove_key(key)
        # the chunk offset matches the replayed rows exactly; metadata offset
        # (written at finalize) is the fallback for logs with no stored offset
        meta_offset = self.metadata.current.offsets.get(persistent_id)
        return rows, (chunk_offset if chunk_offset is not None else meta_offset)

    def record_offset(self, persistent_id: str, offset: Any) -> None:
        if offset is not None:
            self.metadata.current.offsets[persistent_id] = offset

    # --------------------------------------------------------------- operators
    def op_state_key(self, op_sig: str) -> str:
        return f"opstate/{self.worker_id}/{op_sig}"

    def save_operator_state(self, op_sig: str, state: Any) -> None:
        self.backend.put_value(
            self.op_state_key(op_sig),
            pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL),
        )

    def load_operator_state(self, op_sig: str) -> Any | None:
        try:
            return pickle.loads(self.backend.get_value(self.op_state_key(op_sig)))
        except (KeyError, FileNotFoundError, OSError):
            return None

    # --------------------------------------------------------------- lifecycle
    def finalize(self, time: int, offsets: dict[str, Any] | None = None) -> None:
        """Record that this worker durably holds everything up to ``time``."""
        for w in self._writers.values():
            w.flush(time=time, offset=None)
        if offsets:
            self.metadata.current.offsets.update(
                {k: v for k, v in offsets.items() if v is not None}
            )
        self.metadata.update(finalized_time=time)
        self._last_finalized = time
