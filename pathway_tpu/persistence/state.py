"""Worker metadata & global finalized-frontier consensus.

Re-design of the reference's ``src/persistence/state.rs``: each worker
periodically stores a ``StoredMetadata`` blob (finalized time, reader
offsets, operator-state chunk refs). The global *threshold time* — the time
up to which ALL workers have finalized — is the min of the per-worker
finalized times; snapshot replay is truncated at the threshold so no worker
replays data another worker never durably logged.
"""

from __future__ import annotations

import pickle
import time as time_mod
from typing import Any

from pathway_tpu.persistence.backends import PersistenceBackend

_FORMAT_VERSION = 1


def _meta_key(worker_id: int) -> str:
    return f"metadata/worker-{worker_id}"


class StoredMetadata:
    def __init__(
        self,
        worker_id: int = 0,
        finalized_time: int | None = None,
        offsets: dict[str, Any] | None = None,
        operator_state_keys: dict[str, str] | None = None,
        wall_time: float | None = None,
    ):
        self.version = _FORMAT_VERSION
        self.worker_id = worker_id
        self.finalized_time = finalized_time
        self.offsets = offsets or {}
        self.operator_state_keys = operator_state_keys or {}
        self.wall_time = wall_time if wall_time is not None else time_mod.time()


class MetadataAccessor:
    def __init__(self, backend: PersistenceBackend, worker_id: int = 0, total_workers: int = 1):
        self.backend = backend
        self.worker_id = worker_id
        self.total_workers = total_workers
        self.current = self._load(worker_id) or StoredMetadata(worker_id)

    def _load(self, worker_id: int) -> StoredMetadata | None:
        try:
            return pickle.loads(self.backend.get_value(_meta_key(worker_id)))
        except (KeyError, FileNotFoundError, OSError):
            return None

    def save(self) -> None:
        self.current.wall_time = time_mod.time()
        self.backend.put_value(
            _meta_key(self.worker_id),
            pickle.dumps(self.current, protocol=pickle.HIGHEST_PROTOCOL),
        )

    def update(
        self,
        finalized_time: int | None = None,
        offsets: dict[str, Any] | None = None,
        operator_state_keys: dict[str, str] | None = None,
    ) -> None:
        if finalized_time is not None:
            self.current.finalized_time = finalized_time
        if offsets is not None:
            self.current.offsets.update(offsets)
        if operator_state_keys is not None:
            self.current.operator_state_keys.update(operator_state_keys)
        self.save()

    def threshold_time(self) -> int | None:
        """Min finalized time across all workers that have stored metadata
        (reference ``state.rs:135-155``); None = no worker finalized yet."""
        times: list[int] = []
        for w in range(self.total_workers):
            meta = self.current if w == self.worker_id else self._load(w)
            if meta is None or meta.finalized_time is None:
                return None
            times.append(meta.finalized_time)
        return min(times) if times else None
