"""Input snapshot streams — replay-then-resume event logs.

Re-design of the reference's ``src/persistence/input_snapshot.rs``: per
(persistent_id, worker) append-only log of ``SnapshotEvent``s
(Insert/Delete/AdvanceTime) plus the reader offset in effect when each chunk
was flushed. On restart the log is replayed (consolidated by key) and the
connector's reader is sought past the stored offset, giving
exactly-once-style resumption without re-reading the source.

Chunks are individually-pickled blobs named with a monotonically increasing
sequence number; a chunk is only visible after an atomic backend put, so a
crash mid-flush loses at most the unflushed tail (which the seek offset
then re-reads).
"""

from __future__ import annotations

import pickle
from typing import Any

from pathway_tpu.persistence.backends import PersistenceBackend

_FORMAT_VERSION = 1


def _chunk_key(persistent_id: str, worker_id: int, seq: int) -> str:
    return f"streams/{persistent_id}/{worker_id}/{seq:010d}"


class SnapshotLogWriter:
    """Buffers row events; each ``advance`` (commit) flushes a chunk with the
    connector's current offset."""

    def __init__(
        self,
        backend: PersistenceBackend,
        persistent_id: str,
        worker_id: int = 0,
        flush_every_rows: int = 100_000,
    ):
        from pathway_tpu.engine import chaos

        self.backend = backend
        self.persistent_id = persistent_id
        self.worker_id = worker_id
        self.flush_every_rows = flush_every_rows
        self._chaos_put = chaos.site("persist.put")
        existing = backend.list_prefix(f"streams/{persistent_id}/{worker_id}/")
        self._seq = (
            max(int(k.rsplit("/", 1)[1]) for k in existing) + 1 if existing else 0
        )
        self._rows: list[tuple[Any, tuple, int]] = []

    def write_rows(self, rows: list[tuple[Any, tuple, int]]) -> None:
        """rows: (key, value-tuple, diff)."""
        self._rows.extend(rows)
        if len(self._rows) >= self.flush_every_rows:
            self.flush(time=None, offset=None)

    def advance(self, time: int, offset: Any = None) -> None:
        self.flush(time=time, offset=offset)

    def flush(self, time: int | None, offset: Any) -> None:
        if not self._rows and offset is None:
            return
        chunk = {
            "version": _FORMAT_VERSION,
            "rows": self._rows,
            "time": time,
            "offset": offset,
        }
        if self._chaos_put is not None:
            # raise BEFORE the put: the buffered rows stay queued for the
            # next flush, matching a real backend write failure
            self._chaos_put.maybe_fail()
        self.backend.put_value(
            _chunk_key(self.persistent_id, self.worker_id, self._seq),
            pickle.dumps(chunk, protocol=pickle.HIGHEST_PROTOCOL),
        )
        self._seq += 1
        self._rows = []


class SnapshotLogReader:
    def __init__(self, backend: PersistenceBackend, persistent_id: str, worker_id: int = 0):
        self.backend = backend
        self.persistent_id = persistent_id
        self.worker_id = worker_id

    def replay(
        self, threshold_time: int | None = None
    ) -> tuple[list[tuple[Any, tuple, int]], Any, list[str]]:
        """Return (consolidated rows, last stored reader offset, stale keys).

        Rows are consolidated by (key, value) so replay emits the net state:
        inserts minus deletions, with multiplicities (reference replays the
        raw event log into an input session, which consolidates identically).

        A chunk counts as finalized only if it — or a LATER chunk in the same
        log — carries a commit time ``<= threshold_time``: untimed overflow
        chunks (flushed mid-commit by ``write_rows``) are committed by the
        next timed chunk. Everything past the cut — chunks from a run that
        crashed before finalizing — is returned in ``stale`` so the caller
        can delete it; its data is re-read via the stored reader offset,
        which predates it.
        """
        counts: dict[tuple[Any, tuple], int] = {}
        order: list[tuple[Any, tuple]] = []
        offset: Any = None
        pending: list[dict] = []  # untimed chunks awaiting a timed commit
        stale: list[str] = []

        def consume(chunk: dict) -> None:
            nonlocal offset
            for k, row, diff in chunk["rows"]:
                ck = (k, row)
                if ck not in counts:
                    counts[ck] = 0
                    order.append(ck)
                counts[ck] += diff
            if chunk.get("offset") is not None:
                offset = chunk["offset"]

        cut = False
        for key in self.backend.list_prefix(
            f"streams/{self.persistent_id}/{self.worker_id}/"
        ):
            if cut:
                stale.append(key)
                continue
            try:
                chunk = pickle.loads(self.backend.get_value(key))
                t = chunk.get("time")
            except Exception as exc:  # noqa: BLE001 - torn trailing chunk
                # a crash mid-put can leave a truncated/corrupt chunk as
                # the log's tail; its rows are re-read via the stored
                # reader offset (which predates it), so cut HERE — keep
                # everything already consolidated, mark the rest stale
                from pathway_tpu.internals.errors import get_global_error_log

                get_global_error_log().log(
                    f"snapshot replay: skipping torn chunk {key} "
                    f"({type(exc).__name__}: {exc})"
                )
                cut = True
                stale.extend(k for k, _ in pending)
                pending = []
                stale.append(key)
                continue
            if t is None:
                pending.append((key, chunk))
                continue
            if threshold_time is not None and t > threshold_time:
                cut = True
                stale.extend(k for k, _ in pending)
                pending = []
                stale.append(key)
                continue
            for _, p in pending:
                consume(p)
            pending = []
            consume(chunk)
        # untimed tail with no committing timed chunk: not finalized
        stale.extend(k for k, _ in pending)
        rows = [
            (k, row, diff) for (k, row) in order if (diff := counts[(k, row)]) != 0
        ]
        return rows, offset, stale

    def truncate(self) -> None:
        for key in self.backend.list_prefix(
            f"streams/{self.persistent_id}/{self.worker_id}/"
        ):
            self.backend.remove_key(key)
